"""Device-truth accounting: compiled-program ledger + recompile sentinel.

Everything else in ``obs/`` measures the host's view — wall-clock phases,
queue depths, reservoir latencies. This module measures what XLA is
actually doing with the device:

* :class:`ProgramLedger` wraps each of the engine's compiled programs.
  On the first call with a new argument signature (shapes/dtypes of the
  flattened args) it runs an ANALYSIS-ONLY ahead-of-time compile —
  ``fn.lower(*args).compile()`` — and records compile wall time,
  ``memory_analysis()`` HBM breakdown (argument / output / temp /
  generated-code bytes) and ``cost_analysis()`` FLOPs per program. The
  analyzed executable is then dropped: execution always goes through the
  original jitted callable, so ledger-on output is bitwise-identical to
  ledger-off by construction (the ledger pays one extra compile per
  signature, never a different program). The ledger also carries the
  host↔device transfer counters (staging bytes up, readback bytes down)
  that the engine feeds per step, and a live-buffer HBM watermark read
  from ``jax.live_arrays()``.

* :class:`RecompileSentinel` — after warmup, any new XLA compilation is
  a silent perf killer (a stray shape reaching the step fn recompiles a
  multi-second program mid-serve). Once :meth:`~RecompileSentinel.arm`\\ ed,
  the sentinel trips on (a) any ledger signature miss — with the program
  name and offending shapes — and (b) any backend-compile event from
  ``jax.monitoring`` that is NOT attributed to a ledgered compile, which
  catches compilations the ledger never saw. Each trip bumps a counter
  (exported as ``engine_recompiles_total``), records a flight-recorder
  event, drops a tracer instant, and latches an SLO-style firing gauge.

``jax.monitoring`` has no per-listener removal API, so this module
installs ONE process-wide dispatcher lazily and fans events out to a
``WeakSet`` of armed sentinels — engines come and go, the listener stays
inert when the set is empty.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

# Substring of the jax.monitoring event key fired once per real XLA
# backend compilation (cached jit calls fire nothing).
_COMPILE_EVENT = "/jax/core/compile/backend_compile"

# ---------------------------------------------------------------------------
# Process-wide compile-event dispatcher (jax.monitoring offers global
# registration only — see module doc).
# ---------------------------------------------------------------------------

_armed_sentinels: "weakref.WeakSet" = weakref.WeakSet()
_dispatcher_lock = threading.Lock()
_dispatcher_installed = False

# Compile events fire synchronously on the thread doing the compilation,
# so a thread-local attribution scope is race-free.
_attribution = threading.local()


def _current_attribution() -> Optional[Tuple[str, tuple]]:
    return getattr(_attribution, "scope", None)


def _on_monitoring_event(event: str, duration: float, **kwargs) -> None:
    if _COMPILE_EVENT not in event:
        return
    for sentinel in list(_armed_sentinels):
        sentinel._on_backend_compile(duration)


def _install_dispatcher() -> bool:
    global _dispatcher_installed
    with _dispatcher_lock:
        if _dispatcher_installed:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                _on_monitoring_event
            )
        except Exception:
            return False
        _dispatcher_installed = True
        return True


def _signature(args: tuple, kwargs: dict) -> tuple:
    """Cheap per-call signature: shapes/dtypes/weak_type of array leaves,
    repr of everything else — a superset of what distinguishes jit cache
    entries for the engine's call patterns."""
    out: List[object] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            out.append(
                (
                    tuple(shape),
                    str(dtype),
                    bool(getattr(leaf, "weak_type", False)),
                )
            )
        else:
            out.append(repr(leaf))
    return tuple(out)


def _shape_str(sig: tuple) -> str:
    parts = []
    for entry in sig:
        if isinstance(entry, tuple) and len(entry) == 3:
            shape, dtype, _ = entry
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
    return " ".join(parts) if parts else "<no array args>"


class ProgramRecord:
    """Analysis results for one (program, signature) pair."""

    __slots__ = (
        "name",
        "signature",
        "compile_seconds",
        "flops",
        "argument_bytes",
        "output_bytes",
        "temp_bytes",
        "generated_code_bytes",
        "calls",
    )

    def __init__(self, name: str, signature: tuple):
        self.name = name
        self.signature = signature
        self.compile_seconds = 0.0
        self.flops = 0.0
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.generated_code_bytes = 0
        self.calls = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "shapes": _shape_str(self.signature),
            "compile_seconds": self.compile_seconds,
            "flops": self.flops,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "calls": self.calls,
        }


class _LedgeredProgram:
    """Callable wrapper installed by :meth:`ProgramLedger.wrap`. The hit
    path is one dict probe on the signature; the miss path runs the AOT
    analysis and notifies the sentinel, all inside an attribution scope so
    the monitoring dispatcher knows these compile events are accounted."""

    __slots__ = ("ledger", "name", "fn", "_records")

    def __init__(self, ledger: "ProgramLedger", name: str, fn: Callable):
        self.ledger = ledger
        self.name = name
        self.fn = fn
        self._records: Dict[tuple, ProgramRecord] = {}

    def __call__(self, *args, **kwargs):
        sig = _signature(args, kwargs)
        record = self._records.get(sig)
        if record is not None:
            record.calls += 1
            return self.fn(*args, **kwargs)
        _attribution.scope = (self.name, sig)
        try:
            record = self.ledger._analyze(self.name, sig, self.fn, args, kwargs)
            self._records[sig] = record
            record.calls += 1
            # First jit execution compiles its own cache entry; keep the
            # attribution scope open so those events are not "foreign".
            return self.fn(*args, **kwargs)
        finally:
            _attribution.scope = None


class ProgramLedger:
    """Per-engine device-truth ledger (see module doc).

    ``analyze=False`` keeps the signature tracking (the sentinel's miss
    detector) but skips the extra AOT compile — for callers who want the
    sentinel without paying double compile time.
    """

    def __init__(self, analyze: bool = True):
        self.analyze = analyze
        self.programs: Dict[Tuple[str, tuple], ProgramRecord] = {}
        self.sentinel: Optional["RecompileSentinel"] = None
        self.analysis_failures = 0
        # Host<->device transfer ledger; the engine feeds byte counts at
        # its staging/readback sites and pulls per-step deltas for the
        # tracer counter tracks.
        self.bytes_h2d_total = 0
        self.bytes_d2h_total = 0
        # Optional per-source attribution: callers passing ``tag=`` to
        # count_h2d/count_d2h (e.g. the host KV tier's "hostkv_spill" /
        # "hostkv_fetch") get their bytes double-entry booked here, so a
        # subsystem's own byte counter can be cross-checked against the
        # device-truth ledger exactly.
        self.bytes_h2d_by_tag: Dict[str, int] = {}
        self.bytes_d2h_by_tag: Dict[str, int] = {}
        self._step_mark_h2d = 0
        self._step_mark_d2h = 0
        # Live-buffer HBM watermark.
        self.live_bytes = 0
        self.live_peak_bytes = 0

    # ------------------------------------------------------------- wrapping

    def wrap(self, name: str, fn: Callable) -> Callable:
        """Wrap one compiled program. Idempotent on already-wrapped fns."""
        if isinstance(fn, _LedgeredProgram):
            return fn
        return _LedgeredProgram(self, name, fn)

    def _analyze(
        self, name: str, sig: tuple, fn: Callable, args: tuple, kwargs: dict
    ) -> ProgramRecord:
        record = ProgramRecord(name, sig)
        self.programs[(name, sig)] = record
        if self.analyze:
            t0 = time.perf_counter()
            try:
                compiled = fn.lower(*args, **kwargs).compile()
            except Exception:
                self.analysis_failures += 1
                compiled = None
            record.compile_seconds = time.perf_counter() - t0
            if compiled is not None:
                self._fill_from_compiled(record, compiled)
        if self.sentinel is not None:
            self.sentinel._on_ledger_miss(name, sig)
        return record

    @staticmethod
    def _fill_from_compiled(record: ProgramRecord, compiled) -> None:
        try:
            mem = compiled.memory_analysis()
        except Exception:
            mem = None
        if mem is not None:
            record.argument_bytes = int(
                getattr(mem, "argument_size_in_bytes", 0) or 0
            )
            record.output_bytes = int(
                getattr(mem, "output_size_in_bytes", 0) or 0
            )
            record.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
            record.generated_code_bytes = int(
                getattr(mem, "generated_code_size_in_bytes", 0) or 0
            )
        try:
            cost = compiled.cost_analysis()
        except Exception:
            cost = None
        if isinstance(cost, (list, tuple)) and cost:
            cost = cost[0]
        if isinstance(cost, dict):
            record.flops = float(cost.get("flops", 0.0) or 0.0)

    # ------------------------------------------------------ transfer ledger

    def count_h2d(self, nbytes: int, tag: Optional[str] = None) -> None:
        self.bytes_h2d_total += int(nbytes)
        if tag is not None:
            self.bytes_h2d_by_tag[tag] = (
                self.bytes_h2d_by_tag.get(tag, 0) + int(nbytes)
            )

    def count_d2h(self, nbytes: int, tag: Optional[str] = None) -> None:
        self.bytes_d2h_total += int(nbytes)
        if tag is not None:
            self.bytes_d2h_by_tag[tag] = (
                self.bytes_d2h_by_tag.get(tag, 0) + int(nbytes)
            )

    def step_transfer_deltas(self) -> Tuple[int, int]:
        """Bytes moved since the previous call — the per-step numbers the
        engine exports as tracer counter tracks."""
        dh2d = self.bytes_h2d_total - self._step_mark_h2d
        dd2h = self.bytes_d2h_total - self._step_mark_d2h
        self._step_mark_h2d = self.bytes_h2d_total
        self._step_mark_d2h = self.bytes_d2h_total
        return dh2d, dd2h

    # ---------------------------------------------------------- live buffers

    def update_live_bytes(self) -> int:
        """Sum the bytes of every live device array and advance the peak
        watermark. O(live arrays); the engine calls it once per step."""
        total = 0
        try:
            for arr in jax.live_arrays():
                total += int(getattr(arr, "nbytes", 0) or 0)
        except Exception:
            return self.live_bytes
        self.live_bytes = total
        if total > self.live_peak_bytes:
            self.live_peak_bytes = total
        return total

    # --------------------------------------------------------------- export

    @property
    def program_count(self) -> int:
        return len(self.programs)

    def total_compile_seconds(self) -> float:
        return sum(r.compile_seconds for r in self.programs.values())

    def total_flops(self) -> float:
        return sum(r.flops for r in self.programs.values())

    def total_temp_bytes(self) -> int:
        return sum(r.temp_bytes for r in self.programs.values())

    def total_generated_code_bytes(self) -> int:
        return sum(r.generated_code_bytes for r in self.programs.values())

    def metadata(self) -> Dict[str, Any]:
        """The tracer/statusz metadata block: every analyzed program with
        its compile time, HBM breakdown, and FLOPs."""
        return {
            "programs": [
                r.to_dict()
                for r in sorted(
                    self.programs.values(), key=lambda r: r.name
                )
            ],
            "analysis_failures": self.analysis_failures,
            "bytes_h2d_total": self.bytes_h2d_total,
            "bytes_d2h_total": self.bytes_d2h_total,
            "bytes_h2d_by_tag": dict(self.bytes_h2d_by_tag),
            "bytes_d2h_by_tag": dict(self.bytes_d2h_by_tag),
            "live_buffer_bytes": self.live_bytes,
            "live_buffer_peak_bytes": self.live_peak_bytes,
        }

    def register_into(self, registry) -> None:
        """Export the ledger through a :class:`MetricsRegistry`."""
        registry.gauge_fn(
            "xla_programs",
            lambda: float(self.program_count),
            help="Distinct (program, signature) pairs compiled",
        )
        registry.counter_fn(
            "xla_compile_seconds_total",
            self.total_compile_seconds,
            help="Wall-clock spent in ledgered XLA compilation",
        )
        registry.gauge_fn(
            "xla_program_flops",
            self.total_flops,
            help="Sum of cost-analysis FLOPs across compiled programs",
        )
        registry.gauge_fn(
            "xla_temp_bytes",
            lambda: float(self.total_temp_bytes()),
            help="Sum of memory-analysis temp HBM bytes across programs",
        )
        registry.gauge_fn(
            "xla_generated_code_bytes",
            lambda: float(self.total_generated_code_bytes()),
            help="Sum of generated-code bytes across compiled programs",
        )
        registry.gauge_fn(
            "xla_live_buffer_bytes",
            lambda: float(self.live_bytes),
            help="Bytes held by live device arrays at last step",
        )
        registry.gauge_fn(
            "xla_live_buffer_peak_bytes",
            lambda: float(self.live_peak_bytes),
            help="High-water mark of live device array bytes",
        )
        registry.counter_fn(
            "transfer_h2d_bytes_total",
            lambda: float(self.bytes_h2d_total),
            help="Host-to-device staging bytes",
        )
        registry.counter_fn(
            "transfer_d2h_bytes_total",
            lambda: float(self.bytes_d2h_total),
            help="Device-to-host readback bytes",
        )


class RecompileSentinel:
    """Post-warmup compile detector (see module doc). Construct with the
    observability sinks to fan alerts into; ``arm()`` once the engine has
    seen its full working set of shapes."""

    def __init__(
        self,
        ledger: Optional[ProgramLedger] = None,
        tracer=None,
        flight=None,
        name: str = "recompile",
    ):
        self.name = name
        self.tracer = tracer
        self.flight = flight
        self.armed = False
        self.firing = False
        self.count = 0
        self.trips: List[Dict[str, Any]] = []
        self.monitoring_available = False
        if ledger is not None:
            ledger.sentinel = self

    def arm(self) -> None:
        """Start treating every new compilation as an incident."""
        self.armed = True
        self.monitoring_available = _install_dispatcher()
        _armed_sentinels.add(self)

    def disarm(self) -> None:
        self.armed = False
        _armed_sentinels.discard(self)

    # ----------------------------------------------------------- detectors

    def _on_ledger_miss(self, name: str, sig: tuple) -> None:
        if self.armed:
            self._trip(program=name, shapes=_shape_str(sig), source="ledger")

    def _on_backend_compile(self, duration: float) -> None:
        if not self.armed:
            return
        if _current_attribution() is not None:
            # A ledgered program is compiling on this thread; the ledger
            # miss already tripped (or will) with the program's name.
            return
        self._trip(
            program="unattributed",
            shapes="<unknown>",
            source="monitoring",
            compile_seconds=duration,
        )

    # -------------------------------------------------------------- fan-out

    def _trip(self, **fields) -> None:
        self.count += 1
        self.firing = True
        event = dict(fields)
        event["t"] = time.time()
        self.trips.append(event)
        if self.flight is not None:
            try:
                self.flight.record("recompile", **fields)
            except Exception:
                pass
        if self.tracer is not None and getattr(self.tracer, "enabled", False):
            try:
                self.tracer.instant("recompile_sentinel", **fields)
            except Exception:
                pass

    def acknowledge(self) -> None:
        """Clear the firing latch (the counter stays — it is monotonic)."""
        self.firing = False

    def status(self) -> Dict[str, Any]:
        return {
            "armed": self.armed,
            "firing": self.firing,
            "count": self.count,
            "monitoring_available": self.monitoring_available,
            "trips": list(self.trips[-16:]),
        }

    def register_into(self, registry) -> None:
        registry.counter_fn(
            "engine_recompiles_total",
            lambda: float(self.count),
            help="Post-warmup XLA compilations detected by the sentinel",
        )
        registry.gauge_fn(
            "recompile_sentinel_armed",
            lambda: float(self.armed),
            help="1 while the recompile sentinel is armed",
        )
        registry.gauge_fn(
            "recompile_sentinel_firing",
            lambda: float(self.firing),
            help="1 after a post-warmup recompile until acknowledged",
        )


__all__ = [
    "ProgramLedger",
    "ProgramRecord",
    "RecompileSentinel",
]
