"""Request lifecycle tracer + engine step timeline, Perfetto-exportable.

Two timelines, one clock:

* **Request spans** — one async span per accepted request, opened at
  ``submit`` and closed at retire, with instant events for every lifecycle
  transition in between: ``admit`` (slot, prefix-cache hit/miss, cached
  token count), each ``prefill_chunk``, every resolved ``decode_token`` /
  speculative ``verify_round`` (accepted-token counts), ``preempt``,
  ``cow_copy``, and page ``evict`` pressure.
* **Engine steps** — one duration slice per ``InferenceEngine.step()`` with
  nested phase slices (``schedule`` / ``cow`` / ``prefill`` / ``dispatch``
  / ``readback``) and per-step counter tracks (batch composition,
  token-budget utilization, pages free/referenced/cached-idle, queue
  depth).

Export is Chrome ``trace_event`` JSON (:meth:`Tracer.to_perfetto` /
:meth:`Tracer.save`) — load it at https://ui.perfetto.dev or
``chrome://tracing``. Request spans are async events keyed by request id,
so they line up under the engine-step track; timestamps are host
``perf_counter`` microseconds from tracer construction, the same host
clock ``jax.profiler`` stamps its XLA trace with, so a device trace
captured over the same window lines up alongside.

The disabled path is the null-object pattern: :data:`NULL_TRACER` is a
shared :class:`NullTracer` whose every method is a no-op ``pass`` and whose
``phase()`` returns a shared no-op context manager — no timestamps taken,
no dicts built, no branches in the caller beyond an attribute load. The
engine guards its per-step gauge *computation* behind ``tracer.enabled``
so a disabled engine does zero extra work; serving outputs are
bitwise-identical either way (pinned by tests).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List

# Perfetto process lanes: engine steps/phases under pid 1, request spans
# under pid 2 — two top-level tracks that scroll together.
_PID_ENGINE = 1
_PID_REQUESTS = 2


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CONTEXT = _NullContext()


class NullTracer:
    """Every method a no-op; ``enabled`` False so callers can skip gauge
    computation entirely. One shared instance (:data:`NULL_TRACER`) serves
    every disabled engine."""

    __slots__ = ()
    enabled = False

    def begin_step(self) -> None:
        pass

    def end_step(self, **gauges) -> None:
        pass

    def phase(self, name: str) -> _NullContext:
        return _NULL_CONTEXT

    def request_begin(self, req_id: int, **attrs) -> None:
        pass

    def request_event(self, req_id: int, name: str, **attrs) -> None:
        pass

    def request_end(self, req_id: int, **attrs) -> None:
        pass

    def instant(self, name: str, **attrs) -> None:
        pass

    def set_engine_label(self, label: str) -> None:
        pass


NULL_TRACER = NullTracer()


class _Phase:
    """Context manager emitting one ``X`` (complete) slice on the engine
    track; nested phases nest visually by time containment."""

    __slots__ = ("_tracer", "_name", "_t0")

    def __init__(self, tracer: "Tracer", name: str):
        self._tracer = tracer
        self._name = name

    def __enter__(self):
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, *exc):
        tr = self._tracer
        t1 = tr._now_us()
        tr.events.append(
            {
                "name": self._name,
                "cat": "engine",
                "ph": "X",
                "ts": self._t0,
                "dur": t1 - self._t0,
                "pid": _PID_ENGINE,
                "tid": 0,
                "args": {"step": tr.step_index},
            }
        )
        return False


class Tracer:
    """Recording tracer. Construct one and hand it to
    ``InferenceEngine(..., tracer=tracer)``; after the run,
    :meth:`save` writes a Perfetto-loadable JSON trace.

    Events accumulate in memory as ``trace_event`` dicts (microsecond
    timestamps relative to construction). ``spans_opened`` /
    ``spans_closed`` count request spans — a drained engine satisfies
    ``spans_closed == requests completed``.
    """

    enabled = True

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._epoch = clock()
        self.events: List[dict] = []
        self.step_index = -1
        self._step_t0 = 0.0
        self.spans_opened = 0
        self.spans_closed = 0
        self.engine_label: str = ""

    def set_engine_label(self, label: str) -> None:
        """Annotate the engine process lane (e.g. ``"mesh 2x4"``) — shows
        up in the Perfetto process name so traces from differently-sharded
        engines are tellable apart at a glance. Unset keeps the historical
        plain ``engine`` name byte-for-byte."""
        self.engine_label = str(label)

    def _now_us(self) -> float:
        return (self._clock() - self._epoch) * 1e6

    # -------------------------------------------------------- engine steps

    def begin_step(self) -> None:
        self.step_index += 1
        self._step_t0 = self._now_us()

    def end_step(self, **gauges) -> None:
        """Close the current step slice and sample every gauge onto its own
        counter track (``ph: C``) at the step boundary."""
        now = self._now_us()
        self.events.append(
            {
                "name": "step",
                "cat": "engine",
                "ph": "X",
                "ts": self._step_t0,
                "dur": now - self._step_t0,
                "pid": _PID_ENGINE,
                "tid": 1,
                "args": {"step": self.step_index, **gauges},
            }
        )
        for name, value in gauges.items():
            self.events.append(
                {
                    "name": name,
                    "cat": "gauge",
                    "ph": "C",
                    "ts": now,
                    "pid": _PID_ENGINE,
                    "args": {"value": value},
                }
            )

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    # ------------------------------------------------------- request spans

    def request_begin(self, req_id: int, **attrs) -> None:
        self.spans_opened += 1
        self.events.append(
            {
                "name": "request",
                "cat": "request",
                "ph": "b",
                "id": int(req_id),
                "ts": self._now_us(),
                "pid": _PID_REQUESTS,
                "tid": 0,
                "args": {"req_id": int(req_id), **attrs},
            }
        )

    def request_event(self, req_id: int, name: str, **attrs) -> None:
        self.events.append(
            {
                "name": name,
                "cat": "request",
                "ph": "n",
                "id": int(req_id),
                "ts": self._now_us(),
                "pid": _PID_REQUESTS,
                "tid": 0,
                "args": attrs,
            }
        )

    def request_end(self, req_id: int, **attrs) -> None:
        self.spans_closed += 1
        self.events.append(
            {
                "name": "request",
                "cat": "request",
                "ph": "e",
                "id": int(req_id),
                "ts": self._now_us(),
                "pid": _PID_REQUESTS,
                "tid": 0,
                "args": attrs,
            }
        )

    def instant(self, name: str, **attrs) -> None:
        """Global instant event (page evictions, chaos marks)."""
        self.events.append(
            {
                "name": name,
                "cat": "engine",
                "ph": "i",
                "s": "g",
                "ts": self._now_us(),
                "pid": _PID_ENGINE,
                "tid": 0,
                "args": attrs,
            }
        )

    # -------------------------------------------------------------- export

    def to_perfetto(self) -> Dict[str, object]:
        """Chrome ``trace_event`` document: recorded events plus process /
        thread name metadata so the lanes are labeled in the UI."""
        engine_name = (
            f"engine [{self.engine_label}]" if self.engine_label
            else "engine"
        )
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": _PID_ENGINE,
                "args": {"name": engine_name},
            },
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID_ENGINE,
                "tid": 0,
                "args": {"name": "step phases"},
            },
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID_ENGINE,
                "tid": 1,
                "args": {"name": "steps"},
            },
            {
                "name": "process_name",
                "ph": "M",
                "pid": _PID_REQUESTS,
                "args": {"name": "requests"},
            },
        ]
        return {
            "traceEvents": meta + self.events,
            "displayTimeUnit": "ms",
        }

    def save(self, path: str) -> str:
        """Write the Perfetto JSON trace to ``path``; returns the path."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_perfetto(), f)
        return path
