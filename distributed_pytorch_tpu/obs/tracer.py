"""Request lifecycle tracer + engine step timeline, Perfetto-exportable.

Two timelines, one clock:

* **Request spans** — one async span per accepted request, opened at
  ``submit`` and closed at retire, with instant events for every lifecycle
  transition in between: ``admit`` (slot, prefix-cache hit/miss, cached
  token count), each ``prefill_chunk``, every resolved ``decode_token`` /
  speculative ``verify_round`` (accepted-token counts), ``preempt``,
  ``cow_copy``, and page ``evict`` pressure.
* **Engine steps** — one duration slice per ``InferenceEngine.step()`` with
  nested phase slices (``schedule`` / ``cow`` / ``prefill`` / ``dispatch``
  / ``readback``) and per-step counter tracks (batch composition,
  token-budget utilization, pages free/referenced/cached-idle, queue
  depth).

Export is Chrome ``trace_event`` JSON (:meth:`Tracer.to_perfetto` /
:meth:`Tracer.save`) — load it at https://ui.perfetto.dev or
``chrome://tracing``. Request spans are async events keyed by request id,
so they line up under the engine-step track; timestamps are host
``perf_counter`` microseconds from tracer construction, the same host
clock ``jax.profiler`` stamps its XLA trace with, so a device trace
captured over the same window lines up alongside.

The disabled path is the null-object pattern: :data:`NULL_TRACER` is a
shared :class:`NullTracer` whose every method is a no-op ``pass`` and whose
``phase()`` returns a shared no-op context manager — no timestamps taken,
no dicts built, no branches in the caller beyond an attribute load. The
engine guards its per-step gauge *computation* behind ``tracer.enabled``
so a disabled engine does zero extra work; serving outputs are
bitwise-identical either way (pinned by tests).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Dict, List, Optional

# Perfetto process lanes: engine steps/phases under pid 1, request spans
# under pid 2 — two top-level tracks that scroll together. The serving
# layers above the engine get lanes of their own: front-door streams under
# pid 3, router decisions under pid 4, so a merged fleet trace reads
# top-down in causal order (door → router → engine).
_PID_ENGINE = 1
_PID_REQUESTS = 2
_PID_DOOR = 3
_PID_ROUTER = 4

# Span category per lane — async events are matched by (cat, id), so the
# door's stream #7 and the engine's request #7 never collide.
_SPAN_CAT = {_PID_REQUESTS: "request", _PID_DOOR: "door", _PID_ROUTER: "router"}


def flow_id(trace_id: str) -> int:
    """Stable integer id for Perfetto flow arrows carrying one fleet-wide
    ``trace_id``. Flow events (``ph: s/t/f``) are matched by
    (name, cat, id); hashing the string identically in every process lets
    door, router, and replicas emit linked arrows without coordination.
    48 bits keeps the id an exact JSON double."""
    digest = hashlib.sha1(trace_id.encode("utf-8")).digest()
    return int.from_bytes(digest[:6], "big")


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CONTEXT = _NullContext()


class NullTracer:
    """Every method a no-op; ``enabled`` False so callers can skip gauge
    computation entirely. One shared instance (:data:`NULL_TRACER`) serves
    every disabled engine."""

    __slots__ = ()
    enabled = False

    def begin_step(self) -> None:
        pass

    def end_step(self, **gauges) -> None:
        pass

    def phase(self, name: str) -> _NullContext:
        return _NULL_CONTEXT

    def request_begin(self, req_id: int, **attrs) -> None:
        pass

    def request_event(self, req_id: int, name: str, **attrs) -> None:
        pass

    def request_end(self, req_id: int, **attrs) -> None:
        pass

    def instant(self, name: str, **attrs) -> None:
        pass

    def set_engine_label(self, label: str) -> None:
        pass

    def span_begin(self, pid: int, sid: int, name: str, **attrs) -> None:
        pass

    def span_event(self, pid: int, sid: int, name: str, **attrs) -> None:
        pass

    def span_end(self, pid: int, sid: int, name: str, **attrs) -> None:
        pass

    def flow(self, phase: str, trace_id: str, pid: int, tid: int = 0) -> None:
        pass


NULL_TRACER = NullTracer()


class _Phase:
    """Context manager emitting one ``X`` (complete) slice on the engine
    track; nested phases nest visually by time containment."""

    __slots__ = ("_tracer", "_name", "_t0")

    def __init__(self, tracer: "Tracer", name: str):
        self._tracer = tracer
        self._name = name

    def __enter__(self):
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, *exc):
        tr = self._tracer
        t1 = tr._now_us()
        tr.events.append(
            {
                "name": self._name,
                "cat": "engine",
                "ph": "X",
                "ts": self._t0,
                "dur": t1 - self._t0,
                "pid": _PID_ENGINE,
                "tid": 0,
                "args": {"step": tr.step_index},
            }
        )
        return False


class Tracer:
    """Recording tracer. Construct one and hand it to
    ``InferenceEngine(..., tracer=tracer)``; after the run,
    :meth:`save` writes a Perfetto-loadable JSON trace.

    Events accumulate in memory as ``trace_event`` dicts (microsecond
    timestamps relative to construction). ``spans_opened`` /
    ``spans_closed`` count request spans — a drained engine satisfies
    ``spans_closed == requests completed``.
    """

    enabled = True

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        wall_clock: Callable[[], float] = time.time,
    ):
        self._clock = clock
        self._epoch = clock()
        # Wall-clock anchor for the monotonic epoch: ``ts`` microseconds
        # are relative to construction, so two independently-created
        # tracers (door, router, each replica) can only be merged onto one
        # timeline if each records WHEN its zero was. Exported in
        # :meth:`to_perfetto` metadata; `merge_traces` shifts by the epoch
        # deltas. Old saved traces without the field align at 0.0.
        self.wall_epoch_s: float = wall_clock()
        self.events: List[dict] = []
        self.step_index = -1
        self._step_t0 = 0.0
        self.spans_opened = 0
        self.spans_closed = 0
        self.engine_label: str = ""

    def set_engine_label(self, label: str) -> None:
        """Annotate the engine process lane (e.g. ``"mesh 2x4"``) — shows
        up in the Perfetto process name so traces from differently-sharded
        engines are tellable apart at a glance. Unset keeps the historical
        plain ``engine`` name byte-for-byte."""
        self.engine_label = str(label)

    def _now_us(self) -> float:
        return (self._clock() - self._epoch) * 1e6

    # -------------------------------------------------------- engine steps

    def begin_step(self) -> None:
        self.step_index += 1
        self._step_t0 = self._now_us()

    def end_step(self, **gauges) -> None:
        """Close the current step slice and sample every gauge onto its own
        counter track (``ph: C``) at the step boundary."""
        now = self._now_us()
        self.events.append(
            {
                "name": "step",
                "cat": "engine",
                "ph": "X",
                "ts": self._step_t0,
                "dur": now - self._step_t0,
                "pid": _PID_ENGINE,
                "tid": 1,
                "args": {"step": self.step_index, **gauges},
            }
        )
        for name, value in gauges.items():
            self.events.append(
                {
                    "name": name,
                    "cat": "gauge",
                    "ph": "C",
                    "ts": now,
                    "pid": _PID_ENGINE,
                    "args": {"value": value},
                }
            )

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    # ------------------------------------------------------- request spans

    def request_begin(self, req_id: int, **attrs) -> None:
        self.spans_opened += 1
        self.events.append(
            {
                "name": "request",
                "cat": "request",
                "ph": "b",
                "id": int(req_id),
                "ts": self._now_us(),
                "pid": _PID_REQUESTS,
                "tid": 0,
                "args": {"req_id": int(req_id), **attrs},
            }
        )

    def request_event(self, req_id: int, name: str, **attrs) -> None:
        self.events.append(
            {
                "name": name,
                "cat": "request",
                "ph": "n",
                "id": int(req_id),
                "ts": self._now_us(),
                "pid": _PID_REQUESTS,
                "tid": 0,
                "args": attrs,
            }
        )

    def request_end(self, req_id: int, **attrs) -> None:
        self.spans_closed += 1
        self.events.append(
            {
                "name": "request",
                "cat": "request",
                "ph": "e",
                "id": int(req_id),
                "ts": self._now_us(),
                "pid": _PID_REQUESTS,
                "tid": 0,
                "args": attrs,
            }
        )

    def instant(self, name: str, pid: int = _PID_ENGINE, **attrs) -> None:
        """Global instant event (page evictions, chaos marks, door
        backpressure windows — ``pid`` picks the lane)."""
        self.events.append(
            {
                "name": name,
                "cat": "engine",
                "ph": "i",
                "s": "g",
                "ts": self._now_us(),
                "pid": pid,
                "tid": 0,
                "args": attrs,
            }
        )

    # ------------------------------------------- door / router span lanes

    def span_begin(self, pid: int, sid: int, name: str, **attrs) -> None:
        """Open an async span on a serving-layer lane (``_PID_DOOR`` /
        ``_PID_ROUTER``). ``sid`` keys the span within its lane's category
        — door stream sequence numbers, router fleet ids — so it can never
        collide with engine req_ids (different ``cat``)."""
        self.spans_opened += 1
        self.events.append(
            {
                "name": name,
                "cat": _SPAN_CAT.get(pid, "request"),
                "ph": "b",
                "id": int(sid),
                "ts": self._now_us(),
                "pid": pid,
                "tid": 0,
                "args": attrs,
            }
        )

    def span_event(self, pid: int, sid: int, name: str, **attrs) -> None:
        self.events.append(
            {
                "name": name,
                "cat": _SPAN_CAT.get(pid, "request"),
                "ph": "n",
                "id": int(sid),
                "ts": self._now_us(),
                "pid": pid,
                "tid": 0,
                "args": attrs,
            }
        )

    def span_end(self, pid: int, sid: int, name: str, **attrs) -> None:
        self.spans_closed += 1
        self.events.append(
            {
                "name": name,
                "cat": _SPAN_CAT.get(pid, "request"),
                "ph": "e",
                "id": int(sid),
                "ts": self._now_us(),
                "pid": pid,
                "tid": 0,
                "args": attrs,
            }
        )

    def flow(self, phase: str, trace_id: str, pid: int, tid: int = 0) -> None:
        """One hop of the fleet-wide flow arrow for ``trace_id``.

        ``phase`` is ``"s"`` where the id is MINTED (door admission, or a
        bare router submit), ``"t"`` at every downstream hop (router route,
        engine admission, failover re-admission on the survivor), ``"f"``
        to terminate. All emitters hash the same string to the same 48-bit
        flow id, so the merged trace draws door → router → replica arrows
        without any cross-process coordination."""
        event = {
            "name": "trace",
            "cat": "flow",
            "ph": phase,
            "id": flow_id(trace_id),
            "ts": self._now_us(),
            "pid": pid,
            "tid": tid,
            "args": {"trace_id": trace_id},
        }
        if phase == "t":
            # Bind incoming arrows at the enclosing slice's start so the
            # arrowhead lands on the span, not after it.
            event["bp"] = "e"
        self.events.append(event)

    # -------------------------------------------------------------- export

    def to_perfetto(self) -> Dict[str, object]:
        """Chrome ``trace_event`` document: recorded events plus process /
        thread name metadata so the lanes are labeled in the UI."""
        engine_name = (
            f"engine [{self.engine_label}]" if self.engine_label
            else "engine"
        )
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": _PID_ENGINE,
                "args": {"name": engine_name},
            },
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID_ENGINE,
                "tid": 0,
                "args": {"name": "step phases"},
            },
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID_ENGINE,
                "tid": 1,
                "args": {"name": "steps"},
            },
            {
                "name": "process_name",
                "ph": "M",
                "pid": _PID_REQUESTS,
                "args": {"name": "requests"},
            },
        ]
        # Serving-layer lanes are labeled only when populated, so an
        # engine-only trace keeps its historical two-process shape.
        used_pids = {e.get("pid") for e in self.events}
        for pid, label in ((_PID_DOOR, "front door"), (_PID_ROUTER, "router")):
            if pid in used_pids:
                meta.append(
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": pid,
                        "args": {"name": label},
                    }
                )
        return {
            "traceEvents": meta + self.events,
            "displayTimeUnit": "ms",
            # Clock anchor for multi-tracer assembly (see `merge_traces`):
            # seconds-since-Unix-epoch at which this tracer's ts=0 was.
            "metadata": {"wall_epoch_s": self.wall_epoch_s},
        }

    def save(self, path: str) -> str:
        """Write the Perfetto JSON trace to ``path``; returns the path."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_perfetto(), f)
        return path
