"""Roofline attribution: how far is each compiled program from the chip?

``goodput.py`` answers "what fraction of wall-clock was productive";
this module answers the harder hardware question: for each compiled
program in the :class:`~.xla.ProgramLedger`, is it COMPUTE-bound or
BANDWIDTH-bound, what is the roofline-implied step-time floor, and what
fraction of that roof does the measured step time achieve? That
achieved-fraction gauge is the before/after number a kernel PR (the
ROADMAP's Pallas paged-attention item) gets judged against.

The classic roofline model (Williams et al., CACM 2009):

* arithmetic intensity ``I = flops / bytes`` (FLOPs per HBM byte moved);
* the machine balance ("ridge point") is ``peak_flops / peak_bw``;
* attainable FLOP/s is ``min(peak_flops, I * peak_bw)`` — programs left
  of the ridge are bandwidth-bound, right of it compute-bound;
* the implied time floor for one invocation is
  ``max(flops / peak_flops, bytes / peak_bw)`` — whichever resource is
  saturated sets the clock.

Inputs, all already on hand:

* **bytes** per program from the ledger's ``memory_analysis()``:
  argument + output + temp bytes — the HBM traffic floor for one call
  (weights and KV stream in as arguments every step, which is exactly
  why decode is bandwidth-bound);
* **flops** per program from ``cost_analysis()``, falling back to the
  analytic decode-FLOPs model via ``fallback_flops_fn`` when XLA reports
  0 (the CPU backend's cost analysis omits flops — same limitation the
  goodput MFU path works around);
* **peaks** from :data:`~.goodput.PEAK_BF16_FLOPS` and the
  :data:`HBM_BYTES_PER_SEC` table below (public spec-sheet HBM bandwidth
  per chip, substring-matched on ``device_kind`` exactly like
  :func:`~.goodput.peak_flops_per_chip`);
* **measured step time** from the TSDB's ``step_wall_seconds`` series,
  so achieved-fraction tracks the same window the dashboards show.

Host-side float arithmetic only — no device work, zero cost when off.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from .goodput import peak_flops_per_chip

# Peak HBM bandwidth per chip by generation, bytes/second (public spec
# sheets; v5e 819 GB/s matches tools/mfu_probe.py's historical default).
# Unknown kinds fall back to v5e-class DEFAULT_HBM_BW.
HBM_BYTES_PER_SEC = {
    "v6": 1640e9,
    "v5p": 2765e9,
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v4": 1228e9,
    "v3": 900e9,
    "v2": 700e9,
}
DEFAULT_HBM_BW = 819e9

# Ledger-name prefixes of programs whose hot loop is a hand-written fused
# kernel rather than plain XLA — program_rows tags these so a bench (or a
# /statusz reader) can attribute an achieved_fraction delta to the kernel
# instead of eyeballing program names. The paged decode program compiles
# under "decode_step_paged" exactly when InferenceEngine(paged_kernel=...)
# is on.
FUSED_PROGRAM_PREFIXES = ("decode_step_paged",)


def hbm_bandwidth_per_chip(device) -> float:
    """Best-effort peak HBM bytes/sec for a jax device, by kind substring
    (mirrors :func:`~.goodput.peak_flops_per_chip`)."""
    kind = getattr(device, "device_kind", "").lower()
    for key, bw in HBM_BYTES_PER_SEC.items():
        if key in kind:
            return bw
    return DEFAULT_HBM_BW


def roofline_point(
    flops: float, hbm_bytes: float, peak_flops: float, peak_bw: float
) -> dict:
    """Pure roofline math for one program invocation — the shared source
    of truth for :class:`RooflineModel` and ``tools/mfu_probe.py``.

    Returns intensity (flops/byte), the machine balance (ridge point),
    the bound classification, the implied time floor in seconds, and the
    attainable FLOP/s at this intensity. Degenerate inputs (no flops, no
    bytes, or unconfigured peaks) classify as "unknown" with a 0 floor.
    """
    flops = max(0.0, float(flops))
    hbm_bytes = max(0.0, float(hbm_bytes))
    compute_s = flops / peak_flops if peak_flops > 0 else 0.0
    memory_s = hbm_bytes / peak_bw if peak_bw > 0 else 0.0
    floor_s = max(compute_s, memory_s)
    intensity = flops / hbm_bytes if hbm_bytes > 0 else float("inf")
    ridge = peak_flops / peak_bw if peak_bw > 0 else float("inf")
    if floor_s <= 0.0:
        bound = "unknown"
    elif compute_s >= memory_s:
        bound = "compute"
    else:
        bound = "bandwidth"
    attainable = (
        min(peak_flops, intensity * peak_bw)
        if hbm_bytes > 0
        else peak_flops
    )
    return {
        "flops": flops,
        "hbm_bytes": hbm_bytes,
        "intensity_flops_per_byte": intensity,
        "ridge_flops_per_byte": ridge,
        "bound": bound,
        "compute_floor_s": compute_s,
        "memory_floor_s": memory_s,
        "floor_s": floor_s,
        "attainable_flops_per_sec": attainable,
    }


class RooflineModel:
    """Joins the program ledger's per-program bytes/FLOPs with the chip
    peaks and the TSDB's measured step time (see module doc).

    ``fallback_flops_fn(record) -> float`` supplies analytic FLOPs for
    programs whose ``cost_analysis`` read 0; the engine passes a closure
    over its decode-FLOPs model. ``window_s`` is the trailing window the
    achieved-fraction gauge averages measured step time over.

    The registered gauges are read inside every per-step TSDB sample, so
    they serve from a ``cache_ttl_s`` cache of the ledger sweep (the
    program mix changes on compile events, not per step); :meth:`report`
    always recomputes exactly.
    """

    def __init__(
        self,
        ledger,
        timeseries=None,
        *,
        device=None,
        peak_flops: Optional[float] = None,
        peak_bw: Optional[float] = None,
        fallback_flops_fn: Optional[Callable[[object], float]] = None,
        window_s: float = 60.0,
        cache_ttl_s: float = 2.0,
    ):
        self.ledger = ledger
        self.timeseries = timeseries
        self.peak_flops = (
            float(peak_flops)
            if peak_flops is not None
            else peak_flops_per_chip(device)
        )
        self.peak_bw = (
            float(peak_bw)
            if peak_bw is not None
            else hbm_bandwidth_per_chip(device)
        )
        self.device_kind = getattr(device, "device_kind", "unknown")
        self.fallback_flops_fn = fallback_flops_fn
        self.window_s = float(window_s)
        self.cache_ttl_s = float(cache_ttl_s)
        self._gauge_cache: Optional[dict] = None
        self._gauge_cache_t = 0.0

    # ------------------------------------------------------------- analysis

    def _program_flops(self, record) -> float:
        if record.flops > 0.0:
            return float(record.flops)
        if self.fallback_flops_fn is not None:
            try:
                return max(0.0, float(self.fallback_flops_fn(record)))
            except Exception:
                return 0.0
        return 0.0

    def program_rows(self) -> List[dict]:
        """One roofline row per ledgered (program, signature), call-count
        weighted ordering (hottest first)."""
        rows = []
        for record in self.ledger.programs.values():
            hbm_bytes = (
                record.argument_bytes
                + record.output_bytes
                + record.temp_bytes
            )
            point = roofline_point(
                self._program_flops(record),
                hbm_bytes,
                self.peak_flops,
                self.peak_bw,
            )
            point["name"] = record.name
            point["calls"] = record.calls
            point["flops_source"] = (
                "cost_analysis" if record.flops > 0.0 else "analytic"
            )
            point["fused_kernel"] = record.name.startswith(
                FUSED_PROGRAM_PREFIXES
            )
            rows.append(point)
        rows.sort(key=lambda r: -r["calls"])
        return rows

    def step_floor_s(self) -> float:
        """Roofline-implied floor for ONE engine step: the per-call floor
        of every program, weighted by its share of calls (programs ride
        different step shapes, so the call-weighted mix approximates the
        steady-state step). Zero until something is ledgered."""
        rows = self.program_rows()
        total_calls = sum(r["calls"] for r in rows)
        if total_calls <= 0:
            return 0.0
        return sum(r["floor_s"] * r["calls"] for r in rows) / total_calls

    def measured_step_s(self) -> Optional[float]:
        """Trailing-window mean of the TSDB's measured step wall time."""
        if self.timeseries is None:
            return None
        return self.timeseries.avg_over_time(
            "step_wall_seconds", self.window_s
        )

    def achieved_fraction(self) -> float:
        """floor / measured ∈ (0, 1]: 1.0 means the step runs AT the
        roofline (the hardware can go no faster for this program mix);
        0.0 until both a floor and a measurement exist."""
        floor = self.step_floor_s()
        measured = self.measured_step_s()
        if not floor or not measured or measured <= 0.0:
            return 0.0
        return min(1.0, floor / measured)

    def dominant_bound(self) -> str:
        """Bound classification of the step mix: whichever side claims
        the larger call-weighted share of the floor."""
        rows = self.program_rows()
        compute = sum(r["compute_floor_s"] * r["calls"] for r in rows)
        memory = sum(r["memory_floor_s"] * r["calls"] for r in rows)
        if compute <= 0.0 and memory <= 0.0:
            return "unknown"
        return "compute" if compute >= memory else "bandwidth"

    # ------------------------------------------------------------ reporting

    def report(self) -> dict:
        """The ``/statusz`` roofline block."""
        return {
            "device_kind": self.device_kind,
            "peak_flops_per_sec": self.peak_flops,
            "peak_hbm_bytes_per_sec": self.peak_bw,
            "ridge_flops_per_byte": (
                self.peak_flops / self.peak_bw if self.peak_bw else 0.0
            ),
            "step_floor_s": self.step_floor_s(),
            "measured_step_s": self.measured_step_s(),
            "achieved_fraction": self.achieved_fraction(),
            "dominant_bound": self.dominant_bound(),
            "programs": self.program_rows(),
        }

    def _cached_sweep(self) -> dict:
        """Ledger sweep (floor + bound) behind a TTL — the gauges below
        run inside every per-step registry snapshot, and the program mix
        only changes on compile events."""
        now = time.monotonic()
        if (
            self._gauge_cache is None
            or now - self._gauge_cache_t >= self.cache_ttl_s
        ):
            self._gauge_cache = {
                "step_floor_s": self.step_floor_s(),
                "bandwidth_bound": float(
                    self.dominant_bound() == "bandwidth"
                ),
            }
            self._gauge_cache_t = now
        return self._gauge_cache

    def register_into(self, registry) -> None:
        def achieved() -> float:
            floor = self._cached_sweep()["step_floor_s"]
            measured = self.measured_step_s()
            if not floor or not measured or measured <= 0.0:
                return 0.0
            return min(1.0, floor / measured)

        registry.gauge_fn(
            "roofline_achieved_fraction",
            achieved,
            help="Roofline step-time floor over measured step time",
        )
        registry.gauge_fn(
            "roofline_step_floor_seconds",
            lambda: self._cached_sweep()["step_floor_s"],
            help="Call-weighted roofline-implied step-time floor",
        )
        registry.gauge_fn(
            "roofline_bandwidth_bound",
            lambda: self._cached_sweep()["bandwidth_bound"],
            help="1 when the step mix is HBM-bandwidth-bound",
        )


__all__ = [
    "HBM_BYTES_PER_SEC",
    "DEFAULT_HBM_BW",
    "FUSED_PROGRAM_PREFIXES",
    "hbm_bandwidth_per_chip",
    "roofline_point",
    "RooflineModel",
]
