"""Crash-dump flight recorder: a fixed-size ring of structured events.

The tracer (:mod:`.tracer`) answers "where did the time go" for a run you
*planned* to trace; the flight recorder answers "what was the engine doing
right before it died" for the run you didn't. It keeps only the last
``capacity`` events in a :class:`collections.deque` ring — recording is an
append plus a float subtraction, cheap enough to leave on in production —
and the engine dumps the ring as a postmortem JSON document on fault
injection, SIGTERM drain, unhandled exceptions escaping
``InferenceEngine.run()`` / ``DrainController.drive()``, and ``close()``.

Events are flat dicts ``{"kind": ..., "t": seconds-since-construction,
**fields}``. The recorded kinds mirror the tracer's vocabulary (``step``,
``admit``, ``preempt``, ``retire``, ``page_evict``, ``chaos_fault``,
``drain``, ``restore``, ``slo_alert``, ``exception``) so a dump can be
replayed into a :class:`~distributed_pytorch_tpu.obs.tracer.Tracer` with
:func:`replay_to_tracer` and opened in Perfetto for a visual postmortem.

The disabled path is the null-object pattern, exactly like
:data:`~distributed_pytorch_tpu.obs.tracer.NULL_TRACER`: every component
holds :data:`NULL_FLIGHT_RECORDER` by default and the hot path costs one
attribute load.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Callable, Dict, List, Optional, Union

DUMP_VERSION = 1


class NullFlightRecorder:
    """Every method a no-op; ``enabled`` False so callers can skip field
    computation entirely. One shared instance (:data:`NULL_FLIGHT_RECORDER`)
    serves every disabled component."""

    __slots__ = ()
    enabled = False

    def record(self, kind: str, **fields) -> None:
        pass

    def dump(self, reason: str = "manual", *, path=None, extra=None):
        return None


NULL_FLIGHT_RECORDER = NullFlightRecorder()


class FlightRecorder:
    """Bounded ring buffer of structured engine events.

    ``capacity`` bounds memory: once full, each append silently drops the
    oldest event (``dropped`` counts how many fell off the back, so a
    postmortem reader knows the window is truncated). ``path``, when set,
    is where :meth:`dump` writes by default — the engine dumps there
    automatically on faults, drains, crashes, and ``close()``.
    """

    enabled = True

    def __init__(
        self,
        capacity: int = 4096,
        *,
        path: Optional[str] = None,
        clock: Callable[[], float] = time.perf_counter,
        wall_clock: Callable[[], float] = time.time,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.path = path
        self._clock = clock
        self._epoch = clock()
        # Wall-clock anchor for event ``t`` zero, mirroring
        # ``Tracer.wall_epoch_s``: a replayed postmortem inherits it so
        # `merge_traces` can time-align the dead replica's last moments
        # with the door / router / survivor traces.
        self.wall_epoch_s: float = wall_clock()
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity
        )
        self.recorded = 0
        self.dropped = 0
        self.dumps = 0

    def record(self, kind: str, **fields) -> None:
        """Append one event; O(1), drops the oldest event when full."""
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self.recorded += 1
        event = {"kind": kind, "t": self._clock() - self._epoch}
        event.update(fields)
        self._ring.append(event)

    def events(self) -> List[dict]:
        """Snapshot of the ring, oldest first."""
        return list(self._ring)

    def dump(
        self,
        reason: str = "manual",
        *,
        path: Optional[str] = None,
        extra: Optional[dict] = None,
    ) -> dict:
        """Serialize the ring as a postmortem document and (when a path is
        known) write it atomically. Returns the document either way, so
        callers about to SIGKILL themselves still get the dict."""
        doc: Dict[str, object] = {
            "version": DUMP_VERSION,
            "reason": reason,
            "dumped_at_s": self._clock() - self._epoch,
            "wall_epoch_s": self.wall_epoch_s,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "capacity": self.capacity,
            "events": self.events(),
        }
        if extra is not None:
            doc["extra"] = extra
        self.dumps += 1
        target = path if path is not None else self.path
        if target is not None:
            parent = os.path.dirname(os.path.abspath(target))
            os.makedirs(parent, exist_ok=True)
            tmp = f"{target}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, target)
        return doc


def replay_to_tracer(dump: Union[dict, str], tracer=None, *, pid=None):
    """Rebuild a Perfetto-loadable trace from a postmortem dump.

    ``dump`` may be the document dict, its JSON text, or a path to the
    dump file. ``step`` events (which carry ``dur_s``) become complete
    slices on the engine step track; everything else becomes an instant on
    the phase track, so admit/preempt/evict/fault marks line up under the
    step timeline exactly as a live trace would show them. ``pid``
    selects the Perfetto process lane (default: the engine lane; a
    router recovery dump replays into the router lane).

    Returns the tracer (a fresh one unless passed in); call
    ``to_perfetto()`` / ``save()`` on it for the Chrome trace-event JSON.
    """
    from distributed_pytorch_tpu.obs.tracer import _PID_ENGINE, Tracer

    if pid is None:
        pid = _PID_ENGINE

    if isinstance(dump, str):
        if os.path.exists(dump):
            with open(dump) as f:
                dump = json.load(f)
        else:
            dump = json.loads(dump)
    if not isinstance(dump, dict) or "events" not in dump:
        raise ValueError("not a flight-recorder dump: missing 'events'")
    if tracer is None:
        tracer = Tracer()
    # Inherit the recorder's wall-clock anchor (old dumps predate the
    # field): the replayed trace then merges time-aligned with the rest
    # of the fleet, and trace_id-stamped events land where they happened.
    if "wall_epoch_s" in dump:
        tracer.wall_epoch_s = float(dump["wall_epoch_s"])
    for event in dump["events"]:
        kind = event.get("kind", "event")
        t_us = float(event.get("t", 0.0)) * 1e6
        args = {
            k: v for k, v in event.items() if k not in ("kind", "t")
        }
        if kind == "step" and "dur_s" in event:
            dur_us = float(event["dur_s"]) * 1e6
            tracer.events.append(
                {
                    "name": "step",
                    "cat": "flight",
                    "ph": "X",
                    "ts": t_us - dur_us,
                    "dur": dur_us,
                    "pid": pid,
                    "tid": 1,
                    "args": args,
                }
            )
        else:
            tracer.events.append(
                {
                    "name": kind,
                    "cat": "flight",
                    "ph": "i",
                    "s": "g",
                    "ts": t_us,
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
    return tracer
