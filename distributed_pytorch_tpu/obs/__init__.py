"""Unified observability: tracing, metrics, SLOs, goodput, flight recorder.

Five pieces, designed to be wired through hot paths at zero cost when
disabled:

* :class:`~.tracer.Tracer` / :data:`~.tracer.NULL_TRACER` — per-request
  lifecycle spans and the engine step timeline, exported as Chrome/Perfetto
  ``trace_event`` JSON;
* :class:`~.registry.MetricsRegistry` — counters / gauges / labeled
  reservoirs registered by every subsystem, rendered as structured JSON,
  Prometheus text exposition, or merged across hosts;
* :class:`~.flight.FlightRecorder` / :data:`~.flight.NULL_FLIGHT_RECORDER`
  — a fixed-size ring of structured events dumped as a postmortem JSON on
  faults, drains, crashes, and ``close()``; :func:`~.flight
  .replay_to_tracer` turns a dump back into a Perfetto trace;
* :class:`~.slo.SLOMonitor` — declarative latency/rate objectives with
  multi-window burn-rate alerting over the registry's own metrics;
* :class:`~.goodput.GoodputTracker` + the analytic FLOPs model — wall-clock
  decomposed into productive vs wasted time, tokens/sec/device, and MFU;
* :class:`~.server.IntrospectionServer` / :func:`~.server.scrape` — the
  observability WIRE: a stdlib HTTP server per engine (``/metrics``,
  ``/healthz``, ``/statusz``, ``/snapshot``, ``/trace``, ``/postmortem``)
  plus :meth:`MetricsRegistry.merge_remote` fleet aggregation, with
  :func:`~.promtext.validate_exposition` holding the Prometheus text
  grammar honest;
* :class:`~.xla.ProgramLedger` / :class:`~.xla.RecompileSentinel` —
  device-truth accounting (compile time, HBM breakdown, FLOPs,
  host<->device transfer bytes, live-buffer watermark) and post-warmup
  recompile detection;
* :mod:`~.disttrace` — fleet-wide distributed tracing: one ``trace_id``
  per request across door/router/replicas, :func:`~.disttrace
  .merge_traces` clock-aligned assembly, :func:`~.disttrace
  .request_waterfall` exact-partition latency decomposition, and
  :class:`~.disttrace.TraceSampler` head+tail sampling;
* the performance observatory — :class:`~.timeseries.TimeSeriesDB`
  (fixed-memory multi-resolution history of every metric),
  :class:`~.roofline.RooflineModel` (per-program arithmetic intensity,
  compute- vs bandwidth-bound, achieved-fraction-of-roof), and
  :class:`~.regress.RegressionDetector` (O(1)/tick CUSUM change-point
  detection over step time / TPOT with per-phase blame).
"""

from distributed_pytorch_tpu.obs.disttrace import (
    WATERFALL_COMPONENTS,
    TraceSampler,
    format_waterfall,
    merge_traces,
    prune_trace,
    request_waterfall,
    trace_ids,
)
from distributed_pytorch_tpu.obs.flight import (
    NULL_FLIGHT_RECORDER,
    FlightRecorder,
    NullFlightRecorder,
    replay_to_tracer,
)
from distributed_pytorch_tpu.obs.goodput import (
    GoodputTracker,
    causal_attention_flops,
    peak_flops_per_chip,
    resnet50_train_flops,
    transformer_decode_flops_per_token,
    transformer_train_flops,
)
from distributed_pytorch_tpu.obs.promtext import (
    ExpositionError,
    validate_exposition,
)
from distributed_pytorch_tpu.obs.regress import RegressionDetector
from distributed_pytorch_tpu.obs.registry import (
    Counter,
    Gauge,
    MetricsRegistry,
)
from distributed_pytorch_tpu.obs.roofline import (
    HBM_BYTES_PER_SEC,
    RooflineModel,
    hbm_bandwidth_per_chip,
    roofline_point,
)
from distributed_pytorch_tpu.obs.server import IntrospectionServer, scrape
from distributed_pytorch_tpu.obs.slo import (
    SLObjective,
    SLOMonitor,
    default_serving_objectives,
)
from distributed_pytorch_tpu.obs.timeseries import (
    TimeSeriesDB,
    sparkline,
)
from distributed_pytorch_tpu.obs.tracer import (
    NULL_TRACER,
    NullTracer,
    Tracer,
    flow_id,
)
from distributed_pytorch_tpu.obs.xla import ProgramLedger, RecompileSentinel

__all__ = [
    "Counter",
    "ExpositionError",
    "FlightRecorder",
    "Gauge",
    "GoodputTracker",
    "HBM_BYTES_PER_SEC",
    "IntrospectionServer",
    "MetricsRegistry",
    "NULL_FLIGHT_RECORDER",
    "NULL_TRACER",
    "NullFlightRecorder",
    "NullTracer",
    "ProgramLedger",
    "RecompileSentinel",
    "RegressionDetector",
    "RooflineModel",
    "SLObjective",
    "SLOMonitor",
    "TimeSeriesDB",
    "TraceSampler",
    "Tracer",
    "WATERFALL_COMPONENTS",
    "causal_attention_flops",
    "default_serving_objectives",
    "flow_id",
    "format_waterfall",
    "hbm_bandwidth_per_chip",
    "merge_traces",
    "peak_flops_per_chip",
    "prune_trace",
    "replay_to_tracer",
    "request_waterfall",
    "resnet50_train_flops",
    "roofline_point",
    "scrape",
    "sparkline",
    "trace_ids",
    "transformer_decode_flops_per_token",
    "transformer_train_flops",
    "validate_exposition",
]
