"""Unified observability: request tracing, step timelines, metrics registry.

Three pieces, designed to be wired through hot paths at zero cost when
disabled:

* :class:`~.tracer.Tracer` / :data:`~.tracer.NULL_TRACER` — per-request
  lifecycle spans and the engine step timeline, exported as Chrome/Perfetto
  ``trace_event`` JSON;
* :class:`~.registry.MetricsRegistry` — counters / gauges / labeled
  reservoirs registered by every subsystem, rendered as structured JSON,
  Prometheus text exposition, or merged across hosts.
"""

from distributed_pytorch_tpu.obs.registry import (
    Counter,
    Gauge,
    MetricsRegistry,
)
from distributed_pytorch_tpu.obs.tracer import NULL_TRACER, NullTracer, Tracer

__all__ = [
    "Counter",
    "Gauge",
    "MetricsRegistry",
    "NULL_TRACER",
    "NullTracer",
    "Tracer",
]
