"""Deterministic fault injection for the elastic stack — the chaos harness.

Every robustness claim this repo makes (restart-the-world, snapshot resume,
store-blip survival, corrupt-checkpoint fallback) is only as good as the
failures it has actually been subjected to. This module makes those failures
*injectable, seeded, and declarative*, so the drills in ``tests/test_chaos.py``
and ``tools/chaos_smoke.sh`` are reproducible experiments rather than
anecdotes — the same philosophy as TorchTitan's failure drills, sized to run
on CPU in seconds.

Two pieces:

* :class:`FaultPlan` — a declarative list of :class:`Fault` entries (kill
  worker N at step S, hang it, corrupt the next snapshot write, partition the
  store), activated process-wide by the ``TPURUN_FAULT_PLAN`` env var (inline
  JSON or a path to a JSON file). The Trainer calls :func:`on_step` every
  batch and the checkpoint writer calls :func:`on_snapshot_write` after every
  durable write; both are exact no-ops when no plan is armed.
* :class:`FaultProxy` — a TCP shim between store clients and the real
  rendezvous store. It forwards bytes transparently until told to
  ``partition()``: existing connections are severed mid-stream and new ones
  refused until ``heal()``. The elastic agent routes its own store traffic
  through a local proxy automatically whenever the armed plan carries
  ``store_partition`` faults, so a drill needs no orchestration beyond the
  env var.

This module deliberately imports nothing heavy (no jax/numpy): pure-python
drill workers can use it without paying a framework import.
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import threading
import time
from dataclasses import dataclass, fields
from typing import List, Optional

ENV_VAR = "TPURUN_FAULT_PLAN"

# Serving-engine fault kinds, matched against the phase hooks the
# InferenceEngine step loop calls (see on_serving_phase): a fault fires at
# the named moment WITHIN a step, not merely at a step boundary, so the
# drill exercises the state a real fault would interrupt — an unresolved
# draft+verify round, half-prefilled prompts, a backed-up waiting queue.
_SERVING_KINDS = (
    "kill_mid_verify",
    "reclaim_under_queue_pressure",
    "drain_mid_prefill",
)

# Which engine phase each serving kind fires in. "mid_verify" is emitted
# right after the decode (or speculative draft+verify) dispatch and before
# its readback — the device holds uncommitted work; "mid_prefill" before
# the step's first prefill chunk; "step" at step entry (carries queue
# depth, for pressure-conditioned faults).
_SERVING_PHASE = {
    "kill_mid_verify": "mid_verify",
    "drain_mid_prefill": "mid_prefill",
    "reclaim_under_queue_pressure": "step",
}

# Fleet-level fault kinds, matched against the FleetRouter's per-pump hook
# (see on_fleet_step). Unlike the serving kinds these never signal or raise
# here: chaos only DECLARES which replica suffers what and when; the router
# is the blast radius and applies the semantics itself (abandon the engine
# object for kill, stop reaching it for partition, delay its steps for
# slow). That keeps this module free of any engine knowledge while the
# drill stays seeded and declarative.
#
# The *_process kinds are the cross-process twins: when the targeted
# replica is a real worker subprocess (serving/replica_worker.py behind a
# ProcessReplicaClient) the router delivers the REAL failure —
# kill_replica_process SIGKILLs the child, hang_replica_process SIGSTOPs
# it (SIGCONT after `duration` seconds when > 0), and
# partition_replica_process black-holes the control socket client-side for
# `duration` seconds (0 = until the run ends). Applied to an in-process
# replica they degrade to the nearest in-process semantics (kill ->
# abandon, hang/partition -> unreachable), so one plan drives both fleet
# shapes.
_FLEET_KINDS = (
    "kill_replica",
    "partition_replica",
    "slow_replica",
    "kill_replica_process",
    "hang_replica_process",
    "partition_replica_process",
)

# Router fault kinds: the control plane is the target, not a replica. They
# fire from the same on_fleet_step hook (the router's own pump is the only
# vantage point that knows queue pressure), but unlike the fleet kinds the
# blast radius is the CALLING process: "hard" mode delivers the real signal
# to self (SIGKILL for kill_router — the coordinator dies with shadows,
# streams and route state in memory; SIGTERM for restart_router_under_load,
# so a supervising shell can restart it), while "raise" raises
# InjectedFault for in-process pytest drills that model router death by
# abandoning the router object and recovering from the journal.
# restart_router_under_load accepts ``min_queue``: it waits for at least
# that many in-flight requests before firing, so the drill provably
# crashes a BUSY control plane rather than an idle one.
_ROUTER_KINDS = (
    "kill_router",
    "restart_router_under_load",
)

# Performance fault kinds: unlike every kind above, these do not kill,
# hang, or disconnect anything — they make the engine SLOWER while it
# keeps producing correct tokens, which is exactly the failure the
# perf-regression detector (obs/regress.py) exists to catch. slow_program
# stalls ONE named engine phase (schedule/cow/prefill/dispatch/readback)
# by `duration` seconds per step, persistently from `at_step` on: a
# seeded stand-in for a recompile landing on a worse layout or a DMA
# path degrading. The engine polls serving_stall(phase) inside each
# phase span, so the added time attributes to the right phase in traces,
# the per-phase series, and the detector's blame.
_PERF_KINDS = ("slow_program",)

# Engine step phases a slow_program fault may target (the spans
# InferenceEngine brackets with tracer.phase / its _phase helper).
_ENGINE_PHASES = ("schedule", "cow", "prefill", "dispatch", "readback")

_KINDS = (
    "kill",
    "hang",
    "exit",
    "preempt",
    "drain",
    "corrupt_snapshot",
    "store_partition",
) + _SERVING_KINDS + _FLEET_KINDS + _ROUTER_KINDS + _PERF_KINDS


class InjectedFault(RuntimeError):
    """Raised by a serving fault with ``mode="raise"`` — the in-process
    stand-in for SIGKILL in pytest drills (the process "dies" by abandoning
    the engine object mid-step; recovery must come from a snapshot)."""

    def __init__(self, kind: str, step: int):
        super().__init__(f"injected serving fault {kind!r} at step {step}")
        self.kind = kind
        self.step = step


@dataclass
class Fault:
    """One declarative fault. Matching is AND over the set fields:

    * ``process_id`` — fires only in the worker whose ``PROCESS_ID`` env var
      matches (None = any process);
    * ``restart`` — fires only at this ``TPURUN_RESTART_COUNT`` (None = any
      generation; default 0 so a kill does not re-fire after the restart it
      caused);
    * ``at_step`` — 1-based count of :func:`on_step` calls in this process
      (the Trainer calls it once per train batch);
    * ``at_save`` — 1-based count of :func:`on_snapshot_write` calls in this
      process (one per durable checkpoint/snapshot write);
    * ``at_time`` — seconds after :meth:`FaultProxy.start` (store faults).

    Kinds: ``kill`` (SIGKILL self — uncatchable, the external ``kill -9``
    twin), ``hang`` (sleep ``duration`` seconds, or effectively forever when
    0 — alive but silent, the SIGSTOP/wedged-collective twin), ``exit``
    (clean nonzero exit with ``exit_code``), ``preempt`` (SIGTERM the parent
    agent — a maintenance event / spot reclaim notice; when ``duration`` > 0
    a background timer escalates to SIGKILL on the agent after that many
    seconds, modelling the platform's hard grace deadline), ``drain``
    (alias ``drain_at_step``: touch this worker's own ``TPURUN_DRAIN_FILE``
    then SIGTERM self — a drain request delivered straight to the worker),
    ``corrupt_snapshot`` (truncate or bit-flip the just-written checkpoint
    file, per ``mode``), and ``store_partition`` (drop store connections for
    ``duration`` seconds — consumed by :class:`FaultProxy`, not by workers).

    Serving kinds, fired from the inference engine's phase hooks rather than
    :func:`on_step` (``at_step`` counts engine steps — ``on_serving_phase``
    calls with phase ``"step"`` — and is a LOWER bound for these kinds: the
    fault fires at the first matching phase on or after that step, since a
    step without prefill chunks never reaches ``mid_prefill``):
    ``kill_mid_verify`` (die after the decode / draft+verify dispatch,
    before its readback), ``drain_mid_prefill`` (SIGTERM-with-notice lands
    while prompts are half-prefilled), and ``reclaim_under_queue_pressure``
    (a reclaim notice while the waiting queue holds at least ``min_queue``
    requests; ``at_step`` optional — when unset, fires at the first step
    under enough pressure). Serving faults
    honor ``mode``: ``"hard"`` delivers the real signal (SIGKILL self for
    kill, SIGTERM self for the two notice kinds — a
    :class:`~distributed_pytorch_tpu.serving.elastic.DrainController` with an
    installed handler turns that into a drain), while ``"raise"`` raises
    :class:`InjectedFault` so in-process pytest drills can model death by
    abandoning the engine mid-step.

    Fleet kinds, fired from the FleetRouter's :func:`on_fleet_step` hook
    (``at_step`` counts router pump rounds and is again a LOWER bound;
    unset means "due immediately"). ``replica`` (required) is the index of
    the target in the router's attach order. ``kill_replica`` abandons the
    replica's engine object between steps with an unresolved overlapped
    dispatch in flight — the in-process SIGKILL twin; ``partition_replica``
    makes the replica unreachable (probes fail, steps stop) for
    ``duration`` seconds (0 = until the run ends); ``slow_replica`` delays
    every step of the replica by ``duration`` seconds — the tail-latency
    straggler that hedging exists for. These faults fire as *declarations*
    (mode ``"router"``): the hook returns them to the router, which applies
    the damage itself.
    """

    kind: str
    process_id: Optional[int] = None
    restart: Optional[int] = 0
    at_step: Optional[int] = None
    at_save: Optional[int] = None
    at_time: Optional[float] = None
    duration: float = 0.0
    mode: str = "flip"  # corrupt_snapshot: "flip"|"truncate"; serving: "hard"|"raise"
    exit_code: int = 13
    min_queue: Optional[int] = None  # reclaim_under_queue_pressure threshold
    replica: Optional[int] = None  # fleet kinds: router attach-order index
    phase: Optional[str] = None  # slow_program: engine phase to stall

    def __post_init__(self):
        if self.kind == "drain_at_step":
            self.kind = "drain"
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {_KINDS}")
        if self.kind in _FLEET_KINDS:
            if self.replica is None:
                raise ValueError(
                    f"{self.kind} requires 'replica' (the router "
                    "attach-order index of the target)"
                )
            # Router-applied; signal/raise modes are meaningless here.
            self.mode = "router"
        elif self.replica is not None:
            raise ValueError(
                f"'replica' only applies to fleet kinds {_FLEET_KINDS}, "
                f"not {self.kind!r}"
            )
        elif self.kind in _ROUTER_KINDS:
            # The router itself is the target; naming a replica is a typo.
            if self.mode == "flip":  # dataclass default; router = hard
                self.mode = "hard"
            if self.mode not in ("hard", "raise"):
                raise ValueError(
                    f"router fault mode must be 'hard' or 'raise', "
                    f"got {self.mode!r}"
                )
        elif self.kind in _SERVING_KINDS:
            if self.mode == "flip":  # the dataclass default; serving = hard
                self.mode = "hard"
            if self.mode not in ("hard", "raise"):
                raise ValueError(
                    f"serving fault mode must be 'hard' or 'raise', "
                    f"got {self.mode!r}"
                )
        elif self.kind in _PERF_KINDS:
            if self.phase not in _ENGINE_PHASES:
                raise ValueError(
                    f"{self.kind} requires 'phase', one of {_ENGINE_PHASES}; "
                    f"got {self.phase!r}"
                )
            if self.duration <= 0.0:
                raise ValueError(
                    f"{self.kind} requires 'duration' > 0 (seconds of stall "
                    "per step)"
                )
            # Engine-applied delay; signal/corrupt modes are meaningless.
            self.mode = "stall"
        elif self.mode not in ("flip", "truncate"):
            raise ValueError(f"unknown corrupt mode {self.mode!r}")
        if self.phase is not None and self.kind not in _PERF_KINDS:
            raise ValueError(
                f"'phase' only applies to perf kinds {_PERF_KINDS}, "
                f"not {self.kind!r}"
            )
        if self.min_queue is not None and self.kind not in (
            "reclaim_under_queue_pressure",
            "restart_router_under_load",
        ):
            raise ValueError(
                f"min_queue only applies to reclaim_under_queue_pressure "
                f"and restart_router_under_load, not {self.kind!r}"
            )


def corrupt_file(path: str, mode: str = "flip", seed: int = 0) -> None:
    """Deterministically damage ``path`` in place.

    ``truncate`` keeps the first half (a torn write / full-disk partial);
    ``flip`` XOR-flips 8 seeded byte positions (bit-rot). Both are
    reproducible from ``seed`` so a drill's corruption is identical across
    runs.
    """
    size = os.path.getsize(path)
    if size == 0:
        return
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return
    rng = random.Random(seed)
    # Flip bytes across the back half too, where npz array payloads live —
    # a corruption confined to the zip directory would understate the test.
    offsets = sorted(rng.sample(range(size), min(8, size)))
    with open(path, "r+b") as f:
        for off in offsets:
            f.seek(off)
            byte = f.read(1)
            f.seek(off)
            f.write(bytes([byte[0] ^ 0xFF]))


class FaultPlan:
    """A seeded, declarative set of faults plus the per-process firing state.

    Counters (steps, saves) are per-process and start at zero on every
    (re)start, which is exactly what makes plans deterministic across a
    restart-the-world: "kill process 1 at step 21 of generation 0" means the
    same thing on every run.
    """

    def __init__(self, faults: List[Fault], seed: int = 0):
        self.faults = list(faults)
        self.seed = int(seed)
        self._steps = 0
        self._saves = 0
        self._serving_steps = 0
        self._fleet_steps = 0
        self._fired: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- parsing
    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Inline JSON (starts with ``{``) or a path to a JSON file.

        Validation names the offending entry: a plan typo'd into an env var
        must fail loudly at parse time with the entry index and field, not
        as a drill that silently never fires (or a bare TypeError from the
        dataclass constructor)."""
        spec = spec.strip()
        if spec.startswith("{"):
            doc = json.loads(spec)
        else:
            with open(spec) as f:
                doc = json.load(f)
        entries = doc.get("faults", [])
        if not isinstance(entries, list):
            raise ValueError(
                f"'faults' must be a list, got {type(entries).__name__}"
            )
        valid = {f.name for f in fields(Fault)}
        faults = []
        for i, entry in enumerate(entries):
            if not isinstance(entry, dict):
                raise ValueError(
                    f"fault entry {i}: expected an object, "
                    f"got {type(entry).__name__}"
                )
            unknown = sorted(set(entry) - valid)
            if unknown:
                raise ValueError(
                    f"fault entry {i}: unknown field(s) "
                    f"{', '.join(repr(k) for k in unknown)}; valid fields: "
                    f"{', '.join(sorted(valid))}"
                )
            try:
                faults.append(Fault(**entry))
            except ValueError as e:
                raise ValueError(
                    f"fault entry {i} (kind={entry.get('kind')!r}): {e}"
                ) from None
        return cls(faults, seed=doc.get("seed", 0))

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        spec = os.environ.get(ENV_VAR)
        return cls.from_spec(spec) if spec else None

    def to_spec(self) -> str:
        """Inline-JSON form, suitable for a child process's env."""
        return json.dumps(
            {
                "seed": self.seed,
                "faults": [
                    {k: v for k, v in vars(f).items() if v is not None}
                    for f in self.faults
                ],
            }
        )

    # ------------------------------------------------------------ matching
    @staticmethod
    def _identity_matches(fault: Fault) -> bool:
        if fault.process_id is not None:
            pid = os.environ.get("PROCESS_ID")
            if pid is None or int(pid) != fault.process_id:
                return False
        if fault.restart is not None:
            if int(os.environ.get("TPURUN_RESTART_COUNT", "0")) != fault.restart:
                return False
        return True

    def store_partitions(self) -> List[Fault]:
        return [f for f in self.faults if f.kind == "store_partition"]

    # -------------------------------------------------------------- firing
    def on_step(self) -> None:
        """Advance the per-process step counter and fire any due
        kill/hang/exit fault. Called by the Trainer once per train batch;
        pure-python drill workers call it directly."""
        with self._lock:
            self._steps += 1
            step = self._steps
        for i, fault in enumerate(self.faults):
            if fault.kind not in ("kill", "hang", "exit", "preempt", "drain"):
                continue
            if i in self._fired or fault.at_step != step:
                continue
            if not self._identity_matches(fault):
                continue
            self._fired.add(i)
            self._fire(fault)

    def on_snapshot_write(self, path: str) -> None:
        """Advance the per-process save counter and corrupt ``path`` if a
        ``corrupt_snapshot`` fault is due. Called by the checkpoint writer
        right after each durable write."""
        with self._lock:
            self._saves += 1
            save = self._saves
        for i, fault in enumerate(self.faults):
            if fault.kind != "corrupt_snapshot":
                continue
            if i in self._fired or fault.at_save != save:
                continue
            if not self._identity_matches(fault):
                continue
            self._fired.add(i)
            print(
                f"[chaos] corrupting snapshot write #{save} at {path} "
                f"(mode={fault.mode}, seed={self.seed + i})",
                flush=True,
            )
            corrupt_file(path, mode=fault.mode, seed=self.seed + i)

    def on_serving_phase(self, phase: str, *, queue_depth: int = 0) -> None:
        """Serving-engine chaos hook. The engine calls this at step entry
        (``phase="step"``, advancing the serving step counter and carrying
        the waiting-queue depth), before the step's first prefill chunk
        (``"mid_prefill"``), and between the decode/verify dispatch and its
        readback (``"mid_verify"``). Fires any due serving fault; exact
        no-op for plans without serving kinds."""
        if phase == "step":
            with self._lock:
                self._serving_steps += 1
        step = self._serving_steps
        for i, fault in enumerate(self.faults):
            if fault.kind not in _SERVING_KINDS or i in self._fired:
                continue
            if _SERVING_PHASE[fault.kind] != phase:
                continue
            # For serving kinds at_step is a LOWER bound, not an exact
            # match: mid-phase hooks only occur on steps that actually run
            # that phase (e.g. no prefill chunks -> no mid_prefill call),
            # so exact matching would let a fault silently never fire.
            if fault.at_step is not None and step < fault.at_step:
                continue
            if fault.kind == "reclaim_under_queue_pressure":
                need = fault.min_queue if fault.min_queue is not None else 1
                if queue_depth < need:
                    continue
            if not self._identity_matches(fault):
                continue
            self._fired.add(i)
            self._fire_serving(fault)

    def serving_stall(self, phase: str) -> float:
        """Seconds of injected stall due for engine phase ``phase`` on the
        current serving step. Unlike the one-shot kinds, ``slow_program``
        is PERSISTENT: it stalls every matching phase from ``at_step``
        (lower bound, default 1) until the run ends — a perf regression
        is a level shift, not a blip, and the detector's job is to notice
        the sustained change. ``_fired`` marks first activation only (one
        log line + observer notification, not one stall)."""
        total = 0.0
        for i, fault in enumerate(self.faults):
            if fault.kind != "slow_program" or fault.phase != phase:
                continue
            due_step = fault.at_step if fault.at_step is not None else 1
            if self._serving_steps < due_step:
                continue
            if not self._identity_matches(fault):
                continue
            if i not in self._fired:
                self._fired.add(i)
                print(
                    f"[chaos] slow_program: stalling phase {phase!r} by "
                    f"{fault.duration * 1e3:.1f}ms/step from serving step "
                    f"{self._serving_steps}",
                    flush=True,
                )
                _notify_observers(fault.kind, self._serving_steps, fault.mode)
            total += fault.duration
        return total

    def has_perf_faults(self) -> bool:
        return any(f.kind in _PERF_KINDS for f in self.faults)

    def on_fleet_step(self, *, inflight: int = 0) -> List[Fault]:
        """Fleet chaos hook: the FleetRouter calls this once per pump
        round, carrying its in-flight request count. Advances the
        fleet-round counter and returns the due fleet faults (``at_step``
        is a lower bound; unset = due now) for the ROUTER to apply —
        chaos declares, the router executes, so killing "replica 2" needs
        no knowledge of engine objects here. Each fault fires once;
        observers are notified exactly as for signal-delivered kinds (the
        flight recorder's pre-SIGKILL dump hook).

        Router kinds (kill_router / restart_router_under_load) are also
        fired from here — the router's own pump is the one place that
        knows both the round count and the live queue pressure — but they
        never appear in the returned list: in "hard" mode the signal to
        self lands before this function returns, and in "raise" mode the
        InjectedFault propagates out of the router's step loop the same
        way an in-process replica death would."""
        with self._lock:
            self._fleet_steps += 1
            step = self._fleet_steps
        due: List[Fault] = []
        for i, fault in enumerate(self.faults):
            if fault.kind not in _FLEET_KINDS or i in self._fired:
                continue
            if fault.at_step is not None and step < fault.at_step:
                continue
            if not self._identity_matches(fault):
                continue
            self._fired.add(i)
            print(
                f"[chaos] fleet fault {fault.kind} on replica "
                f"{fault.replica} at router round {step}",
                flush=True,
            )
            _notify_observers(fault.kind, step, fault.mode)
            due.append(fault)
        for i, fault in enumerate(self.faults):
            if fault.kind not in _ROUTER_KINDS or i in self._fired:
                continue
            if fault.at_step is not None and step < fault.at_step:
                continue
            if fault.kind == "restart_router_under_load":
                need = fault.min_queue if fault.min_queue is not None else 1
                if inflight < need:
                    continue
            if not self._identity_matches(fault):
                continue
            self._fired.add(i)
            _notify_observers(fault.kind, step, fault.mode)
            if fault.mode == "raise":
                print(
                    f"[chaos] raising {fault.kind} at router round {step} "
                    f"(inflight={inflight})",
                    flush=True,
                )
                raise InjectedFault(fault.kind, step)
            sig = (
                signal.SIGKILL
                if fault.kind == "kill_router"
                else signal.SIGTERM
            )
            print(
                f"[chaos] {fault.kind}: {signal.Signals(sig).name} self at "
                f"router round {step} (inflight={inflight})",
                flush=True,
            )
            os.kill(os.getpid(), sig)
        return due

    def _fire_serving(self, fault: Fault) -> None:
        step = self._serving_steps
        _notify_observers(fault.kind, step, fault.mode)
        if fault.mode == "raise":
            print(
                f"[chaos] raising {fault.kind} at serving step {step}",
                flush=True,
            )
            raise InjectedFault(fault.kind, step)
        if fault.kind == "kill_mid_verify":
            print(
                f"[chaos] SIGKILL self mid-verify at serving step {step}",
                flush=True,
            )
            os.kill(os.getpid(), signal.SIGKILL)
        else:
            # A reclaim/drain notice: same delivery as the training 'drain'
            # kind, so one DrainController handler covers both halves.
            drain_file = os.environ.get("TPURUN_DRAIN_FILE")
            if drain_file:
                with open(drain_file, "w") as f:
                    f.write("chaos\n")
            print(
                f"[chaos] {fault.kind}: drain notice (SIGTERM self) "
                f"at serving step {step}",
                flush=True,
            )
            os.kill(os.getpid(), signal.SIGTERM)

    def _fire(self, fault: Fault) -> None:
        _notify_observers(fault.kind, self._steps, fault.mode)
        if fault.kind == "kill":
            print(f"[chaos] SIGKILL self at step {self._steps}", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        elif fault.kind == "exit":
            print(
                f"[chaos] exit({fault.exit_code}) at step {self._steps}",
                flush=True,
            )
            os._exit(fault.exit_code)
        elif fault.kind == "hang":
            duration = fault.duration if fault.duration > 0 else 86400.0
            print(
                f"[chaos] hanging for {duration:.0f}s at step {self._steps}",
                flush=True,
            )
            time.sleep(duration)
        elif fault.kind == "drain":
            drain_file = os.environ.get("TPURUN_DRAIN_FILE")
            if drain_file:
                with open(drain_file, "w") as f:
                    f.write("chaos\n")
            print(f"[chaos] drain request (self) at step {self._steps}", flush=True)
            os.kill(os.getpid(), signal.SIGTERM)
        elif fault.kind == "preempt":
            ppid = os.getppid()
            print(
                f"[chaos] preempting agent pid {ppid} at step {self._steps}"
                + (f" (SIGKILL after {fault.duration:.0f}s)" if fault.duration > 0 else ""),
                flush=True,
            )
            if fault.duration > 0:
                # The platform's hard deadline: grace elapses, the plug is
                # pulled regardless of drain progress.
                def _escalate(target=ppid):
                    try:
                        os.kill(target, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass

                timer = threading.Timer(fault.duration, _escalate)
                timer.daemon = True
                timer.start()
            try:
                os.kill(ppid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass


# ------------------------------------------------------- process-wide plan

_UNSET = object()
_plan = _UNSET

# Fault observers: callbacks invoked with (kind, step, mode) at the moment
# a fault FIRES, before the signal/raise — the flight recorder's last
# chance to dump a postmortem ahead of a SIGKILL. Observer exceptions are
# swallowed: diagnostics must never mask the injected fault itself.
_FAULT_OBSERVERS: list = []


def add_fault_observer(fn) -> None:
    """Register ``fn(kind, step, mode)`` to run when any fault fires."""
    _FAULT_OBSERVERS.append(fn)


def remove_fault_observer(fn) -> None:
    try:
        _FAULT_OBSERVERS.remove(fn)
    except ValueError:
        pass


def _notify_observers(kind: str, step: int, mode: str) -> None:
    for fn in list(_FAULT_OBSERVERS):
        try:
            fn(kind, step, mode)
        except Exception:
            pass


def get_plan() -> Optional[FaultPlan]:
    """The process-wide plan from ``TPURUN_FAULT_PLAN``, parsed once and
    cached (the Trainer consults this every batch)."""
    global _plan
    if _plan is _UNSET:
        _plan = FaultPlan.from_env()
    return _plan


def _reset() -> None:
    """Drop the cached plan (tests re-arm the env var within one process)."""
    global _plan
    _plan = _UNSET
    del _FAULT_OBSERVERS[:]


def on_step() -> None:
    plan = get_plan()
    if plan is not None:
        plan.on_step()


def on_snapshot_write(path: str) -> None:
    plan = get_plan()
    if plan is not None:
        plan.on_snapshot_write(path)


def on_serving_phase(phase: str, queue_depth: int = 0) -> None:
    plan = get_plan()
    if plan is not None:
        plan.on_serving_phase(phase, queue_depth=queue_depth)


def on_fleet_step(*, inflight: int = 0) -> List[Fault]:
    plan = get_plan()
    if plan is None:
        return []
    return plan.on_fleet_step(inflight=inflight)


def serving_stall(phase: str) -> float:
    """Injected stall seconds due for this engine phase, 0.0 with no plan."""
    plan = get_plan()
    if plan is None:
        return 0.0
    return plan.serving_stall(phase)


# ------------------------------------------------------------- FaultProxy


class FaultProxy:
    """TCP shim for store-partition injection.

    Listens on an ephemeral local port and pipes each accepted connection to
    the real store. ``partition()`` severs every active connection mid-stream
    and refuses new ones (exactly what a switch failure looks like to a
    client: ECONNRESET now, ECONNREFUSED-or-hang next) until ``heal()``.
    The hardened ``KVStoreClient`` must ride this out within its retry
    deadline; that contract is what ``tests/test_chaos.py`` pins down.
    """

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        *,
        listen_host: str = "127.0.0.1",
        delay: float = 0.0,
    ):
        self.upstream = (upstream_host, upstream_port)
        self.delay = delay
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()
        self._partitioned = threading.Event()
        self._stop = threading.Event()
        self._conns: set = set()
        self._lock = threading.Lock()
        self._timers: List[threading.Timer] = []
        self._accept_thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "FaultProxy":
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._timers:
            t.cancel()
        try:
            self._listener.close()
        except OSError:
            pass
        self._close_all()

    def __enter__(self) -> "FaultProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- faults
    def partition(self, duration: Optional[float] = None) -> None:
        """Sever every live connection and refuse new ones; auto-heal after
        ``duration`` seconds when given."""
        self._partitioned.set()
        self._close_all()
        if duration is not None:
            timer = threading.Timer(duration, self.heal)
            timer.daemon = True
            self._timers.append(timer)
            timer.start()

    def heal(self) -> None:
        self._partitioned.clear()

    def apply_plan(self, plan: FaultPlan) -> None:
        """Schedule the plan's ``store_partition`` faults relative to now."""
        for fault in plan.store_partitions():
            timer = threading.Timer(
                fault.at_time or 0.0, self.partition, args=(fault.duration,)
            )
            timer.daemon = True
            self._timers.append(timer)
            timer.start()

    # ----------------------------------------------------------- plumbing
    def _close_all(self) -> None:
        with self._lock:
            conns, self._conns = set(self._conns), set()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            if self._stop.is_set() or self._partitioned.is_set():
                conn.close()
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5)
            except OSError:
                conn.close()
                continue
            up.settimeout(None)  # connect-only timeout; pumps must block
            for s in (conn, up):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with self._lock:
                    self._conns.add(s)
            for src, dst in ((conn, up), (up, conn)):
                threading.Thread(
                    target=self._pump, args=(src, dst), daemon=True
                ).start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(4096)
                if not data or self._partitioned.is_set():
                    break
                if self.delay:
                    time.sleep(self.delay)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                with self._lock:
                    self._conns.discard(s)
                # shutdown() before close(): the partner pump is blocked in
                # recv() on one of these fds and holds a kernel reference, so
                # a bare close() would neither wake it nor send FIN — the
                # proxied client would then block forever on a reply that can
                # no longer arrive.
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
