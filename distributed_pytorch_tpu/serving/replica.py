"""Replica clients: the FleetRouter's handle on one replica, local or not.

Until now every replica the router drove was an ``InferenceEngine`` object
in the router's own process, so "replica death" could only ever be an
analogy — an abandoned Python object, not a vanished interpreter. This
module splits the handle from the engine behind a small interface:

* :class:`LocalReplicaClient` wraps an in-process engine. Every method is
  a direct delegate; behavior is byte-identical to the pre-refactor
  router (``tests/test_serving_fleet.py`` runs unmodified against it).
* :class:`ProcessReplicaClient` drives a replica WORKER SUBPROCESS
  (``serving/replica_worker.py``: engine + IntrospectionServer + a
  stdlib-HTTP control endpoint), spawned with the same env/handshake/
  terminate-with-grace idioms as the elastic agent's WorkerGroup. The
  child can genuinely die (SIGKILL), hang (SIGSTOP), or fall off the
  network (black-holed socket) — and the client is built to survive all
  three.

The robustness layer is the point, not a footnote:

* every control-plane call has a per-call deadline;
* idempotent calls (submit — deduped by a client-minted request id the
  worker keeps a replay map for, exactly like the KV store's
  ``(client_id, seq)`` replay map — cancel, poll, health) get bounded
  jittered-exponential retries; ``step`` is NOT retried (a landed step
  advances decode state, so replaying it is not a retry but a second
  step) — instead its results are delivered at-least-once via an ack
  protocol (the worker re-reports finished ids until the client acks
  them on its next step call);
* a per-replica :class:`CircuitBreaker` opens after K consecutive
  transport failures, fast-fails every call while open, and lets exactly
  one probe through per cooldown (half-open) — so a hung replica costs
  the fleet capacity, never tail latency;
* application errors (``QueueFull``, ``EngineDraining``, ...) cross the
  wire as HTTP 409 + exception class name and are re-raised as the real
  admission types — they are ANSWERS from a live worker, so they count
  as breaker successes and are never retried.

Failure taxonomy the router keys off:

* :class:`ReplicaUnavailable` — transport-level: deadline, refused
  connection, chaos partition, breaker open. The replica may be fine;
  degrade (skip this round) rather than declare death.
* :class:`ReplicaDead` — the worker PROCESS exited (``Popen.poll()``
  non-None). Unambiguous: trigger failover.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence

from distributed_pytorch_tpu.obs.server import scrape
from distributed_pytorch_tpu.serving import admission as _admission
from distributed_pytorch_tpu.serving.admission import AdmissionError
from distributed_pytorch_tpu.serving.elastic import (
    EngineSnapshot,
    adopt_snapshot,
    drain_engine,
    fetch_snapshot_text,
    params_to_doc,
    restore_engine,
)
from distributed_pytorch_tpu.serving.journal import (
    remove_worker_entry,
    write_worker_entry,
)
from distributed_pytorch_tpu.serving.engine import RequestStatus
from distributed_pytorch_tpu.serving.scheduler import SamplingParams

_JSON = "application/json"


class ReplicaError(RuntimeError):
    """Base for replica control-plane failures."""


class ReplicaUnavailable(ReplicaError):
    """Transport-level failure: call deadline, refused/reset connection,
    chaos partition, or a fast-fail from an open circuit breaker. The
    worker process may well be alive — callers should degrade (skip the
    replica this round), not declare it dead."""


class ReplicaDead(ReplicaError):
    """The replica worker PROCESS exited. ``reason`` carries the best
    attribution the client has: the chaos kind that killed it when the
    client itself delivered the signal, else ``"process_exit"``."""

    def __init__(self, msg: str, *, reason: str = "process_exit"):
        super().__init__(msg)
        self.reason = reason


# ------------------------------------------------------------------ breaker


class CircuitBreaker:
    """Per-replica circuit breaker over control-plane transport health.

    Classic three-state machine, driven entirely by the client's
    record_success/record_failure calls:

    * ``closed`` — normal operation. ``fail_threshold`` CONSECUTIVE
      failures trip it open (one success resets the streak: a flaky link
      is not a dead one).
    * ``open`` — every :meth:`allow` is refused for ``reset_timeout_s``
      (callers fast-fail with :class:`ReplicaUnavailable`, spending zero
      deadline budget on a replica known to be wedged).
    * ``half_open`` — after the cooldown, :meth:`allow` grants exactly ONE
      in-flight probe; its success closes the breaker, its failure
      re-opens it and restarts the cooldown.

    The clock is injectable for deterministic state-machine tests; the
    in-process :class:`LocalReplicaClient` constructs a disabled breaker
    (``enabled=False``) that never opens, since a same-process call
    cannot time out at the transport."""

    def __init__(
        self,
        *,
        fail_threshold: int = 3,
        reset_timeout_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        enabled: bool = True,
    ):
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        self.fail_threshold = fail_threshold
        self.reset_timeout_s = reset_timeout_s
        self.enabled = enabled
        self._clock = clock
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        self.opens_total = 0
        self.closes_total = 0

    @property
    def state(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.reset_timeout_s:
            return "half_open"
        return "open"

    def allow(self) -> bool:
        """May a call go out right now? half-open grants one probe."""
        st = self.state
        if st == "closed":
            return True
        if st == "open":
            return False
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        return True

    def record_success(self) -> None:
        if self._opened_at is not None:
            self.closes_total += 1
        self._failures = 0
        self._opened_at = None
        self._probe_inflight = False

    def record_failure(self) -> None:
        if not self.enabled:
            return
        if self._opened_at is not None:
            # Half-open probe failed (or a straggler failure landed while
            # open): re-open and restart the cooldown.
            self._opened_at = self._clock()
            self._probe_inflight = False
            return
        self._failures += 1
        if self._failures >= self.fail_threshold:
            self._opened_at = self._clock()
            self._probe_inflight = False
            self.opens_total += 1


# ---------------------------------------------------------------- interface


class ReplicaClient:
    """What the router needs from one replica, local or cross-process.

    Data-plane: :meth:`submit` / :meth:`step` / :meth:`poll` /
    :meth:`cancel`. Elastic: :meth:`drain` / :meth:`restore` /
    :meth:`adopt` (publish/adopt KV hand-off). Observability:
    :meth:`health`, :meth:`load`, :meth:`read_gauge`,
    :meth:`metrics_snapshot` (the ``merge_remote`` payload),
    :meth:`describe`, :meth:`trace_documents`, :meth:`slo_firing`,
    :meth:`idle_fraction`. Chaos (process implementations only — the
    router falls back to in-process semantics when ``is_process`` is
    False): :meth:`kill`, :meth:`suspend`, :meth:`partition`."""

    kind = "?"
    is_process = False
    #: The wrapped in-process engine, or None for a cross-process replica.
    #: Exposed (rather than hidden) so local fleets keep their exact
    #: pre-refactor surface — tests and drills reach through
    #: ``replica.engine`` for gauges and even setattr SLO trackers.
    engine = None
    breaker: CircuitBreaker
    #: monotonic timestamp the client delivered a chaos kill, if any —
    #: the router uses it as time-of-death for detection-latency gauges.
    killed_at: Optional[float] = None

    # -- identity / setup
    @property
    def url(self) -> Optional[str]:
        raise NotImplementedError

    def fingerprint(self) -> dict:
        raise NotImplementedError

    def reserve_ids(self, base: int) -> None:
        raise NotImplementedError

    def start_server(self) -> str:
        raise NotImplementedError

    # -- data plane
    def submit(self, prompt, params=None, metadata=None, *,
               tenant_id: str = "anon", mods=None,
               trace_id: Optional[str] = None) -> int:
        raise NotImplementedError

    def step(self) -> List[int]:
        raise NotImplementedError

    def poll(self, req_id: int) -> RequestStatus:
        raise NotImplementedError

    def cancel(self, req_id: int) -> bool:
        raise NotImplementedError

    # -- elastic
    def drain(self, reason: str = "drain") -> EngineSnapshot:
        raise NotImplementedError

    def restore(self, snapshot: EngineSnapshot, *,
                rebase_ids: bool = False) -> List[int]:
        raise NotImplementedError

    def adopt(self, store, key: str, *, delete: bool = True,
              rebase_ids: bool = False,
              timeout_s: Optional[float] = None) -> List[int]:
        raise NotImplementedError

    # -- observability
    def health(self, timeout_s: Optional[float] = None) -> str:
        raise NotImplementedError

    def load(self) -> float:
        raise NotImplementedError

    def queue_depth(self) -> float:
        raise NotImplementedError

    def read_gauge(self, name: str) -> float:
        raise NotImplementedError

    def metrics_snapshot(self) -> Optional[dict]:
        raise NotImplementedError

    def describe(self) -> dict:
        raise NotImplementedError

    def trace_documents(self) -> List[dict]:
        raise NotImplementedError

    def slo_firing(self) -> List[str]:
        raise NotImplementedError

    def idle_fraction(self) -> Optional[float]:
        raise NotImplementedError

    # -- lifecycle
    def close(self) -> None:
        """Graceful stop: drain nothing, just release resources."""
        raise NotImplementedError

    def abandon(self) -> None:
        """Tear down a replica declared dead: reap/kill the child if any,
        stop servers. Never raises."""
        raise NotImplementedError

    # -- chaos delivery (process clients only)
    def kill(self, *, chaos_kind: str = "kill_replica_process") -> None:
        raise NotImplementedError

    def suspend(self, duration_s: float = 0.0) -> None:
        raise NotImplementedError

    def resume(self) -> None:
        raise NotImplementedError

    def partition(self, duration_s: float = 0.0) -> None:
        raise NotImplementedError

    def heal(self) -> None:
        raise NotImplementedError


# -------------------------------------------------------------- local client


class LocalReplicaClient(ReplicaClient):
    """In-process replica: a thin delegate around ``InferenceEngine``.

    Every call lands directly on the engine object with zero translation,
    so a fleet of local clients is behaviorally identical to the
    pre-refactor router holding bare engines. The breaker is constructed
    disabled — an in-process call cannot fail at the transport — so
    breaker-aware routing logic treats local replicas as always-closed
    without special-casing."""

    kind = "local"
    is_process = False

    def __init__(self, engine, *, serve: bool = False):
        self.engine = engine
        self.breaker = CircuitBreaker(enabled=False)
        self.killed_at = None
        if serve:
            engine.serve()

    @property
    def url(self) -> Optional[str]:
        server = getattr(self.engine, "_server", None)
        return server.url if server is not None else None

    def start_server(self) -> str:
        return self.engine.serve().url

    def fingerprint(self) -> dict:
        e = self.engine
        return {
            "page_size": e.page_size,
            "max_seq_len": e.max_seq_len,
            "top_k": e._top_k,
            "top_p": e._top_p,
            "speculative": e.speculative,
            "mesh": e.mesh_fingerprint,
            "kv": e.kv_fingerprint,
        }

    def reserve_ids(self, base: int) -> None:
        self.engine._next_id = max(self.engine._next_id, base)

    def submit(self, prompt, params=None, metadata=None, *,
               tenant_id="anon", mods=None, trace_id=None) -> int:
        return self.engine.submit(
            prompt, params, metadata, tenant_id=tenant_id, mods=mods,
            trace_id=trace_id,
        )

    def step(self) -> List[int]:
        return self.engine.step()

    def poll(self, req_id: int) -> RequestStatus:
        return self.engine.poll(req_id)

    def cancel(self, req_id: int) -> bool:
        return self.engine.cancel(req_id)

    def drain(self, reason: str = "drain") -> EngineSnapshot:
        return drain_engine(self.engine, reason=reason)

    def restore(self, snapshot, *, rebase_ids=False) -> List[int]:
        return restore_engine(self.engine, snapshot, rebase_ids=rebase_ids)

    def adopt(self, store, key, *, delete=True, rebase_ids=False,
              timeout_s=None) -> List[int]:
        return adopt_snapshot(
            self.engine, store, key, delete=delete, rebase_ids=rebase_ids,
            timeout_s=timeout_s,
        )

    def health(self, timeout_s: Optional[float] = None) -> str:
        url = self.url
        if url is not None:
            doc = scrape(
                url, "/healthz", timeout=timeout_s or 1.0, retries=0
            )
            return doc.get("status", "dead")
        return self.engine.health()

    def load(self) -> float:
        reg = self.engine.registry
        return (
            reg.read_gauge("queue_depth")
            + reg.read_gauge("running_requests")
        )

    def queue_depth(self) -> float:
        return self.engine.registry.read_gauge("queue_depth")

    def read_gauge(self, name: str) -> float:
        return self.engine.registry.read_gauge(name)

    def metrics_snapshot(self) -> Optional[dict]:
        return self.engine.registry.snapshot(include_state=True)

    def describe(self) -> dict:
        return self.engine.status()

    def trace_documents(self) -> List[dict]:
        tracer = getattr(self.engine, "tracer", None)
        if tracer is None or not tracer.enabled:
            return []
        with self.engine.registry.lock:
            return [json.loads(json.dumps(tracer.to_perfetto()))]

    def slo_firing(self) -> List[str]:
        slo = getattr(self.engine, "slo", None)
        if slo is None:
            return []
        return [
            name for name, st in slo.state().items() if st["firing"]
        ]

    def idle_fraction(self) -> Optional[float]:
        goodput = getattr(self.engine, "goodput", None)
        if goodput is None:
            return None
        total = goodput.productive_s + goodput.wasted_total_s()
        if total <= 0:
            return None
        return goodput.wasted["budget_idle"] / total

    def close(self) -> None:
        self.engine.close()

    def abandon(self) -> None:
        # A dead local replica's engine object is abandoned un-closed
        # (the in-process SIGKILL analogy) — but its obs server thread is
        # real and must stop.
        server = getattr(self.engine, "_server", None)
        if server is not None:
            try:
                server.stop()
            except Exception:
                pass


# ------------------------------------------------------------ process client


#: Control-plane ops safe to retry on transport failure. ``submit`` and
#: ``cancel`` qualify because the worker dedups them through a replay map
#: keyed by a client-minted request id; ``poll``/``health``/``describe``
#: are read-only; ``adopt`` converges (claiming an already-claimed worker
#: is a no-op answer). ``step`` is deliberately absent (see module
#: docstring).
_IDEMPOTENT = frozenset({
    "/submit", "/cancel", "/poll", "/health", "/describe", "/gauge",
    "/reserve_ids", "/adopt",
})

_HELLO_KEY = "replica_hello"


def _status_from_doc(doc: dict) -> RequestStatus:
    return RequestStatus(
        req_id=int(doc["req_id"]),
        state=doc["state"],
        prompt_len=int(doc["prompt_len"]),
        generated=[int(t) for t in doc["generated"]],
        finished=bool(doc["finished"]),
        preempt_count=int(doc.get("preempt_count", 0)),
    )


def _params_to_doc(params: SamplingParams) -> dict:
    # One canonical codec (elastic.params_to_doc) serves the control-plane
    # wire AND the router's write-ahead journal, so a journaled submit can
    # be re-submitted byte-identically after a router crash.
    return params_to_doc(params)


class _PidProcess:
    """``Popen`` look-alike over a bare pid, for ATTACHING to a worker
    this process never spawned (router crash recovery re-adopts workers
    the DEAD router's registry points at). Implements exactly the surface
    :class:`ProcessReplicaClient` touches — ``poll``/``wait``/
    ``terminate``/``kill``, ``.pid``/``.returncode``, ``None`` pipes.
    A non-child cannot be ``waitpid``-ed, so liveness is probed with
    ``kill(pid, 0)`` and death reported as returncode ``-1`` (the true
    exit code belongs to whoever reaped it)."""

    def __init__(self, pid: int):
        self.pid = int(pid)
        self.returncode: Optional[int] = None
        self.stdin = None
        self.stdout = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self.returncode = -1
        except PermissionError:
            pass  # exists, owned by someone else: alive
        except OSError:
            self.returncode = -1
        else:
            # ``kill(pid, 0)`` succeeds on a ZOMBIE — an exited worker
            # whose (still-living) spawner has not reaped it yet. That
            # worker is gone for every purpose this shim serves.
            if self._is_zombie():
                self.returncode = -1
        return self.returncode

    def _is_zombie(self) -> bool:
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                stat = f.read()
            # Field 3, after the parenthesized (possibly space-laden) comm.
            return stat.rpartition(b")")[2].split()[0] == b"Z"
        except (OSError, IndexError):
            return False  # no procfs: fall back to kill(0) semantics

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"pid {self.pid}", timeout
                )
            time.sleep(0.02)
        return self.returncode

    def terminate(self) -> None:
        try:
            os.kill(self.pid, signal.SIGTERM)
        except OSError:
            pass

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except OSError:
            pass


class ProcessReplicaClient(ReplicaClient):
    """Drives one replica worker subprocess over localhost HTTP.

    Spawn mirrors the elastic agent's WorkerGroup: the worker inherits a
    scrubbed environment (the chaos plan env var is STRIPPED — faults are
    delivered by the router through this client, never re-armed inside
    the child), gets its spec as one env JSON blob, and announces
    readiness with a single hello line on stdout carrying its
    kernel-assigned control and introspection ports. Shutdown mirrors
    ``WorkerGroup.terminate``: polite ``/shutdown`` (the worker closes
    its engine — leak asserts run there and surface as a non-zero exit),
    then SIGTERM, then SIGKILL.

    A daemon thread pumps the child's stdout for its lifetime (tail kept
    for diagnostics); the child watches its stdin for EOF and exits if
    the parent dies first, so no drill can leak an orphan worker."""

    kind = "process"
    is_process = True
    engine = None

    def __init__(
        self,
        spec: dict,
        *,
        name: Optional[str] = None,
        python: str = sys.executable,
        spawn_timeout_s: float = 120.0,
        call_timeout_s: float = 10.0,
        step_timeout_s: Optional[float] = None,
        drain_timeout_s: float = 60.0,
        call_retries: int = 2,
        retry_backoff_s: float = 0.05,
        breaker_fail_threshold: int = 3,
        breaker_reset_s: float = 1.0,
        env: Optional[Dict[str, str]] = None,
        clock: Callable[[], float] = time.perf_counter,
        run_dir: Optional[str] = None,
        attach_entry: Optional[dict] = None,
    ):
        if attach_entry is not None and name is None:
            name = attach_entry.get("name")
        self.name = name or spec.get("name") or "replica"
        self.run_dir = run_dir
        self.spec = spec
        self.call_timeout_s = call_timeout_s
        self.step_timeout_s = step_timeout_s or call_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.call_retries = call_retries
        self.retry_backoff_s = retry_backoff_s
        self._clock = clock
        self.breaker = CircuitBreaker(
            fail_threshold=breaker_fail_threshold,
            reset_timeout_s=breaker_reset_s,
            clock=clock,
        )
        self.killed_at: Optional[float] = None
        self._chaos_kind: Optional[str] = None
        self._partitioned_until: Optional[float] = None
        self._suspended = False
        self._rids = itertools.count()
        self._nonce = f"{os.getpid():x}-{random.randrange(1 << 30):x}"
        self._statuses: Dict[int, RequestStatus] = {}
        self._to_ack: List[int] = []
        self._load = 0.0
        self._queue_depth = 0.0
        self._slo_firing: List[str] = []
        self._idle_fraction: Optional[float] = None
        self._last_trace: Optional[dict] = None
        self._last_metrics: Optional[dict] = None
        self._log_tail: collections.deque = collections.deque(maxlen=100)
        self._hello: Optional[dict] = None
        self._hello_event = threading.Event()
        #: True when this client ATTACHED to an orphaned worker (router
        #: recovery) rather than spawning it; the recovery summary counts
        #: these as re-adoptions.
        self.adopted = False

        if attach_entry is not None:
            self._attach(attach_entry)
            return

        child_env = dict(os.environ if env is None else env)
        # Chaos plans are delivered by the ROUTER through this client —
        # a worker that also armed the plan would double-fire every fault.
        child_env.pop("TPURUN_FAULT_PLAN", None)
        child_env["TPURUN_REPLICA_SPEC"] = json.dumps(spec)
        child_env["TPURUN_REPLICA_NAME"] = self.name
        self._proc = subprocess.Popen(
            [python, "-m",
             "distributed_pytorch_tpu.serving.replica_worker"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=child_env,
            text=True,
        )
        self._pump = threading.Thread(
            target=self._pump_stdout,
            name=f"replica-pump-{self.name}",
            daemon=True,
        )
        self._pump.start()
        if not self._hello_event.wait(spawn_timeout_s):
            tail = "\n".join(self._log_tail)
            self.abandon()
            raise ReplicaError(
                f"replica worker {self.name} never said hello within "
                f"{spawn_timeout_s:.0f}s; last output:\n{tail}"
            )
        if self._hello is None:
            code = self._proc.poll()
            tail = "\n".join(self._log_tail)
            raise ReplicaDead(
                f"replica worker {self.name} exited (code {code}) before "
                f"hello; last output:\n{tail}"
            )
        self.control_url: str = self._hello["control_url"]
        self.obs_url: str = self._hello["obs_url"]
        self.pid: int = int(self._hello["pid"])
        self._fingerprint: dict = dict(self._hello["fingerprint"])
        self._write_registry_entry()

    @classmethod
    def attach(cls, entry: dict, **kwargs) -> "ProcessReplicaClient":
        """Re-adopt a LIVE worker from its registry entry instead of
        spawning one — the router-recovery path. The entry must carry
        ``pid``/``control_url``/``obs_url``/``fingerprint`` (what
        :meth:`_write_registry_entry` persists); the worker is claimed
        and identity-checked via ``POST /adopt``, which refuses (409 →
        ``ValueError`` here) if the pid was reborn as a different
        process or the spec fingerprint disagrees."""
        return cls(
            dict(entry.get("spec") or {}), attach_entry=entry, **kwargs
        )

    def _attach(self, entry: dict) -> None:
        self._proc = _PidProcess(int(entry["pid"]))
        self._pump = None
        self._hello = dict(entry)
        self._hello_event.set()
        self.control_url = entry["control_url"]
        self.obs_url = entry["obs_url"]
        self.pid = int(entry["pid"])
        self._fingerprint = dict(entry.get("fingerprint") or {})
        self._check_alive()  # pid already gone: ReplicaDead, not a probe
        doc = self._call("/adopt", {
            "name": self.name,
            "pid": self.pid,
            "fingerprint": self._fingerprint or None,
        })
        self.adopted = True
        self.adopted_orphan = bool(doc.get("orphaned"))
        self._write_registry_entry()

    # ------------------------------------------------------------ registry

    def _write_registry_entry(self) -> None:
        """Persist this worker's coordinates for a successor router.

        The entry is the recovery bootstrap: everything
        :meth:`attach` needs to re-adopt the worker after THIS router
        process is gone. Written on spawn and refreshed on attach; removed
        on deliberate teardown (:meth:`close` / :meth:`abandon`) so the
        registry only ever lists workers somebody should re-adopt."""
        if self.run_dir is None:
            return
        write_worker_entry(self.run_dir, {
            "name": self.name,
            "pid": self.pid,
            "control_url": self.control_url,
            "obs_url": self.obs_url,
            "fingerprint": self._fingerprint,
            "spec": self.spec,
            "written_s": time.time(),
        })

    def _remove_registry_entry(self) -> None:
        if self.run_dir is not None:
            remove_worker_entry(self.run_dir, self.name)

    # ------------------------------------------------------------ plumbing

    def _pump_stdout(self) -> None:
        stream = self._proc.stdout
        try:
            for line in stream:
                line = line.rstrip("\n")
                if (not self._hello_event.is_set()
                        and line.startswith("{")
                        and _HELLO_KEY in line):
                    try:
                        self._hello = json.loads(line)[_HELLO_KEY]
                    except (ValueError, KeyError):
                        self._log_tail.append(line)
                    else:
                        self._hello_event.set()
                        continue
                self._log_tail.append(line)
        except (ValueError, OSError):
            pass  # stream closed under us during teardown
        finally:
            self._hello_event.set()

    def _check_alive(self) -> None:
        code = self._proc.poll()
        if code is not None:
            raise ReplicaDead(
                f"replica worker {self.name} exited with code {code}",
                reason=self._chaos_kind or "process_exit",
            )

    def _app_error(self, code: int, payload: dict) -> Exception:
        kind = payload.get("error_kind", "")
        msg = payload.get("error", f"HTTP {code}")
        cls = getattr(_admission, kind, None)
        if isinstance(cls, type) and issubclass(cls, AdmissionError):
            return cls(msg)
        if kind == "KeyError":
            return KeyError(msg)
        if kind == "ValueError":
            return ValueError(msg)
        return ReplicaError(f"{self.name}: {kind or code}: {msg}")

    def _call(self, endpoint: str, body: Optional[dict] = None, *,
              timeout_s: Optional[float] = None) -> dict:
        """One control-plane call with the full robustness stack: breaker
        gate, chaos-partition check, liveness check, per-call deadline,
        and jittered-exponential retries for idempotent endpoints."""
        now = self._clock()
        if self._partitioned_until is not None:
            if 0 < self._partitioned_until <= now:
                self._partitioned_until = None  # healed
            else:
                self.breaker.record_failure()
                raise ReplicaUnavailable(
                    f"{self.name}: control socket partitioned (chaos)"
                )
        if not self.breaker.allow():
            raise ReplicaUnavailable(
                f"{self.name}: circuit breaker {self.breaker.state}"
            )
        op = endpoint.split("?", 1)[0]
        attempts = 1 + (self.call_retries if op in _IDEMPOTENT else 0)
        delay = self.retry_backoff_s
        timeout = timeout_s if timeout_s is not None else self.call_timeout_s
        for attempt in range(attempts):
            self._check_alive()
            try:
                if body is not None:
                    data = json.dumps(body).encode("utf-8")
                    req = urllib.request.Request(
                        self.control_url + endpoint, data=data,
                        headers={"Content-Type": _JSON}, method="POST",
                    )
                else:
                    req = urllib.request.Request(
                        self.control_url + endpoint, method="GET"
                    )
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    doc = json.loads(resp.read().decode("utf-8"))
            except urllib.error.HTTPError as err:
                # The worker ANSWERED — an application error from a live
                # replica, not a transport failure.
                self.breaker.record_success()
                try:
                    payload = json.loads(err.read().decode("utf-8"))
                except ValueError:
                    payload = {}
                raise self._app_error(err.code, payload) from None
            except OSError as exc:
                # URLError (refused/reset) and socket timeouts are both
                # OSError subclasses. Re-check liveness first: a refused
                # connect from an exited child is death, not flakiness.
                self._check_alive()
                self.breaker.record_failure()
                if attempt + 1 < attempts and self.breaker.allow():
                    time.sleep(delay * (0.5 + random.random() * 0.5))
                    delay = min(delay * 2.0, 1.0)
                    continue
                raise ReplicaUnavailable(
                    f"{self.name}: {op} failed after {attempt + 1} "
                    f"attempt(s): {exc}"
                ) from exc
            else:
                self.breaker.record_success()
                return doc
        raise AssertionError("unreachable")

    def _ingest_statuses(self, docs) -> None:
        if not docs:
            return
        for entry in docs:
            st = _status_from_doc(entry)
            self._statuses[st.req_id] = st

    # ---------------------------------------------------------- interface

    @property
    def url(self) -> Optional[str]:
        return self.obs_url

    def start_server(self) -> str:
        return self.obs_url  # the worker always serves introspection

    def fingerprint(self) -> dict:
        return dict(self._fingerprint)

    def reserve_ids(self, base: int) -> None:
        self._call("/reserve_ids", {"base": int(base)})

    def submit(self, prompt, params=None, metadata=None, *,
               tenant_id="anon", mods=None, trace_id=None) -> int:
        params = params if params is not None else SamplingParams()
        rid = f"{self._nonce}-{next(self._rids)}"
        doc = self._call("/submit", {
            "rid": rid,
            "prompt": [int(t) for t in prompt],
            "params": _params_to_doc(params),
            "metadata": metadata,
            "tenant_id": tenant_id,
            "mods": mods.to_spec() if mods is not None else None,
            "trace_id": trace_id,
        })
        return int(doc["req_id"])

    def step(self) -> List[int]:
        doc = self._call(
            "/step", {"ack": self._to_ack},
            timeout_s=self.step_timeout_s,
        )
        self._ingest_statuses(doc.get("statuses"))
        self._load = float(doc.get("load", 0.0))
        self._queue_depth = float(doc.get("queue_depth", 0.0))
        self._slo_firing = list(doc.get("slo_firing", []))
        self._idle_fraction = doc.get("idle_fraction")
        if doc.get("trace") is not None:
            self._last_trace = doc["trace"]
        finished = [int(i) for i in doc.get("finished", [])]
        # At-least-once finish delivery: ack what we just consumed so the
        # worker stops re-reporting it. A step RESPONSE lost in transport
        # re-delivers these ids next round; ids are deduped router-side.
        self._to_ack = finished
        return finished

    def poll(self, req_id: int) -> RequestStatus:
        st = self._statuses.get(req_id)
        if st is not None:
            return st
        doc = self._call(f"/poll?id={int(req_id)}")
        st = _status_from_doc(doc)
        self._statuses[req_id] = st
        return st

    def cancel(self, req_id: int) -> bool:
        doc = self._call("/cancel", {"req_id": int(req_id)})
        ok = bool(doc["ok"])
        if ok:
            # The cached status predates the cancel; evict it so the next
            # poll fetches the terminal (cancelled) state from the worker.
            self._statuses.pop(int(req_id), None)
        return ok

    def drain(self, reason: str = "drain") -> EngineSnapshot:
        doc = self._call(
            "/drain", {"reason": reason}, timeout_s=self.drain_timeout_s
        )
        self._ingest_statuses(doc.get("statuses"))
        return EngineSnapshot.from_json(doc["snapshot"])

    def restore(self, snapshot, *, rebase_ids=False) -> List[int]:
        doc = self._call("/restore", {
            "snapshot": snapshot.to_json(),
            "rebase_ids": bool(rebase_ids),
        }, timeout_s=self.drain_timeout_s)
        return [int(i) for i in doc["restored"]]

    def adopt(self, store, key, *, delete=True, rebase_ids=False,
              timeout_s=None) -> List[int]:
        # Parent-side fetch (the worker has no store credentials), then
        # one restore over the control plane. delete only after the
        # restore is acknowledged: adopt-once must not drop the snapshot
        # if the worker refuses it.
        if timeout_s is None:
            text = store.get(key)
            if text is None:
                return []
        else:
            text = fetch_snapshot_text(store, key, timeout_s=timeout_s)
        ids = self.restore(
            EngineSnapshot.from_json(text), rebase_ids=rebase_ids
        )
        if delete:
            store.delete(key)
        return ids

    def health(self, timeout_s: Optional[float] = None) -> str:
        doc = self._call("/health", timeout_s=timeout_s)
        return doc["status"]

    def load(self) -> float:
        return self._load

    def queue_depth(self) -> float:
        return self._queue_depth

    def read_gauge(self, name: str) -> float:
        doc = self._call(f"/gauge?name={urllib.parse.quote(name)}")
        return float(doc["value"])

    def metrics_snapshot(self) -> Optional[dict]:
        try:
            self._check_alive()
            snap = scrape(self.obs_url, "/snapshot", retries=0)
        except (ReplicaDead, OSError):
            return self._last_metrics  # best effort: last good scrape
        self._last_metrics = snap
        return snap

    def describe(self) -> dict:
        return self._call("/describe")

    def trace_documents(self) -> List[dict]:
        try:
            self._check_alive()
            doc = scrape(self.obs_url, "/trace", retries=0)
        except ReplicaDead:
            # The victim's interpreter is gone, but its last trace doc —
            # cached from step responses — keeps its lanes in the merged
            # fleet waterfall.
            return [self._last_trace] if self._last_trace else []
        except urllib.error.HTTPError:
            return []  # 404: worker runs untraced
        except OSError:
            return [self._last_trace] if self._last_trace else []
        if isinstance(doc, dict):
            self._last_trace = doc
            return [doc]
        return []

    def slo_firing(self) -> List[str]:
        return list(self._slo_firing)

    def idle_fraction(self) -> Optional[float]:
        return self._idle_fraction

    # ----------------------------------------------------------- lifecycle

    def close(self, timeout_s: float = 30.0) -> None:
        """Polite shutdown: ``/shutdown`` runs ``engine.close()`` INSIDE
        the worker — debug-mode allocator leak asserts run there, and a
        failure comes back as an HTTP 500 (raised here as ReplicaError)
        plus a non-zero exit. Escalates SIGTERM → SIGKILL like
        ``WorkerGroup.terminate`` if the child lingers."""
        err: Optional[Exception] = None
        if self._proc.poll() is None and self._partitioned_until is None:
            self.resume()  # a SIGSTOPped child cannot run /shutdown
            try:
                self._call("/shutdown", {}, timeout_s=timeout_s)
            except ReplicaError as exc:
                err = exc
        try:
            self._proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=5.0)
        self._release_pipes()
        self._remove_registry_entry()
        code = self._proc.returncode
        if err is not None:
            raise ReplicaError(
                f"replica worker {self.name} failed to close cleanly "
                f"(exit {code}): {err}"
            ) from err
        if (
            code not in (0, None)
            and self._chaos_kind is None
            # An attached (non-child) worker cannot be reaped, so its
            # true exit code is unknowable; -1 there means "gone", not
            # "failed".
            and not isinstance(self._proc, _PidProcess)
        ):
            tail = "\n".join(self._log_tail)
            raise ReplicaError(
                f"replica worker {self.name} exited {code} on close; "
                f"last output:\n{tail}"
            )

    def abandon(self) -> None:
        try:
            if self._proc.poll() is None:
                # SIGCONT first: SIGKILL terminates a stopped process,
                # but be explicit so a SIGSTOPped child reaps promptly.
                try:
                    os.kill(self._proc.pid, signal.SIGCONT)
                except OSError:
                    pass
                self._proc.kill()
            self._proc.wait(timeout=5.0)
        except Exception:
            pass
        self._release_pipes()
        self._remove_registry_entry()

    def _release_pipes(self) -> None:
        for stream in (self._proc.stdin, self._proc.stdout):
            try:
                if stream is not None:
                    stream.close()
            except OSError:
                pass

    # --------------------------------------------------------------- chaos

    def kill(self, *, chaos_kind: str = "kill_replica_process") -> None:
        """Deliver a REAL SIGKILL to the worker. Records time-of-death so
        the router's detection-latency gauge measures kill → first failed
        contact, same as the in-process drills."""
        self._chaos_kind = chaos_kind
        self.killed_at = self._clock()
        try:
            os.kill(self._proc.pid, signal.SIGKILL)
        except OSError:
            pass

    def suspend(self, duration_s: float = 0.0) -> None:
        """SIGSTOP the worker — the truest 'hung but alive' fault: the
        kernel keeps its sockets open, connects succeed, reads stall until
        the call deadline. ``duration_s > 0`` schedules the SIGCONT."""
        self._suspended = True
        try:
            os.kill(self._proc.pid, signal.SIGSTOP)
        except OSError:
            return
        if duration_s > 0:
            timer = threading.Timer(duration_s, self.resume)
            timer.daemon = True
            timer.start()

    def resume(self) -> None:
        if not self._suspended:
            return
        self._suspended = False
        if self._proc.poll() is None:
            try:
                os.kill(self._proc.pid, signal.SIGCONT)
            except OSError:
                pass

    def partition(self, duration_s: float = 0.0) -> None:
        """Black-hole the control socket CLIENT-side: every call fails
        instantly as :class:`ReplicaUnavailable` (and feeds the breaker)
        until ``duration_s`` elapses — 0 means until :meth:`heal`."""
        self._partitioned_until = (
            self._clock() + duration_s if duration_s > 0 else float("inf")
        )

    def heal(self) -> None:
        self._partitioned_until = None


def spawn_replica_clients(
    specs: Sequence[dict], **kwargs
) -> List[ProcessReplicaClient]:
    """Spawn one :class:`ProcessReplicaClient` per spec CONCURRENTLY.

    Worker start-up is dominated by the child's JAX import + XLA warm-up
    compile, which parallelizes perfectly across processes — a 3-replica
    fleet spawns in roughly the time of one. ``kwargs`` go to every
    constructor (deadlines, breaker tuning). All-or-nothing: if any spawn
    fails, the ones that succeeded are abandoned and the first error is
    re-raised."""
    clients: List[Optional[ProcessReplicaClient]] = [None] * len(specs)
    errors: List[Optional[BaseException]] = [None] * len(specs)

    def _spawn(i: int, spec: dict) -> None:
        try:
            clients[i] = ProcessReplicaClient(spec, **kwargs)
        except BaseException as exc:  # noqa: BLE001 - reported below
            errors[i] = exc

    threads = [
        threading.Thread(
            target=_spawn, args=(i, spec),
            name=f"replica-spawn-{i}", daemon=True,
        )
        for i, spec in enumerate(specs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    first_error = next((e for e in errors if e is not None), None)
    if first_error is not None:
        for c in clients:
            if c is not None:
                c.abandon()
        raise first_error
    return [c for c in clients if c is not None]


__all__ = [
    "CircuitBreaker",
    "LocalReplicaClient",
    "ProcessReplicaClient",
    "ReplicaClient",
    "ReplicaDead",
    "ReplicaError",
    "ReplicaUnavailable",
    "spawn_replica_clients",
]
