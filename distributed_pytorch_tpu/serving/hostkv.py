"""Host-memory KV page tier: spilled prefix pages, content-addressed.

The device pools (:class:`~.kv_cache.PagePoolGroup`) hold a hard-capped
number of KV pages; under ``OutOfPages`` pressure the allocator's LRU
simply recycled cached-idle pages, so any workload whose warm-prefix
working set exceeds device HBM paid full re-prefill. This module adds the
tier below: preallocated host buffers mirroring every device pool's page
geometry, filled by asynchronous d2h spills when the prefix trie loses a
page to eviction and drained back h2d when a later prompt hits the
spilled chain.

Design points, in the order they matter:

* **Content-addressed identity.** A host page is named by the same
  hash-chained sha256 ``key_chain`` key the elastic snapshot codec uses
  (:meth:`~.kv_cache.PrefixCache.key_chain`): key ``i`` commits to the
  entire page-aligned prefix, not just its own page. That makes host
  pages nameable across tiers AND across processes — a restore target
  can match a snapshot's ``trie_keys`` against its own host tier without
  any device state crossing the wire. Keys are verified against the
  stored token window on every :meth:`match` (exact compare, no
  hash-collision corruption — same rule as the device trie).
* **Per-pool buffers in lockstep.** One host slot spans EVERY pool
  (target, and draft under speculative decoding), exactly like one
  device page id does: a spill gathers the page from all pools, a fetch
  writes it back to all pools, so draft K/V stays as valid as target
  K/V through a tier round-trip.
* **Asynchronous spill.** :meth:`note_evict` dispatches per-pool device
  gathers (``pool[page]``) and returns immediately — device dispatch
  order guarantees the gather reads the page BEFORE any later program
  overwrites it, so eviction never blocks the scheduler on a d2h sync.
  The engine drains the staged gathers into the host buffers once per
  step (:meth:`drain_spills`), off the device path, and charges the
  bytes to the ``obs/xla.py`` transfer ledger under the
  ``hostkv_spill`` tag.
* **Entry states.** ``PENDING`` (spill dispatched, host bytes not yet
  materialized) -> ``RESIDENT`` (host buffer holds the page). A fetch
  may only read a ``RESIDENT`` entry; the engine drains pending spills
  before executing any step's fetches. Entries referenced by a planned
  fetch are PINNED against the host LRU until the fetch stages them.
* **Leak-proof like the device tier.** O(1) resident/free gauges are
  cross-checked against a full O(n) sweep in :meth:`check_invariants`
  (driven by the same randomized property tests as the allocator), and
  :meth:`assert_quiescent` is part of ``engine.close()``: no pinned
  entry and no undrained spill may survive teardown.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

__all__ = ["HostPageTier"]


class _HostEntry:
    """One spilled page: its content key, host slot, exact token window,
    residency state, and pin count (planned fetches not yet staged)."""

    __slots__ = ("key", "slot", "tokens", "resident", "pins")

    def __init__(self, key: str, slot: int, tokens: Tuple[int, ...]):
        self.key = key
        self.slot = slot
        self.tokens = tokens
        self.resident = False
        self.pins = 0


class HostPageTier:
    """Preallocated host page buffers behind the device prefix trie.

    ``template`` maps pool name -> the pool's device pytree (used for
    per-page leaf shapes/dtypes only); ``gather_fn(page)`` returns the
    same mapping sliced to one page — device arrays whose materialization
    is deferred to :meth:`drain_spills`. The engine binds ``gather_fn``
    to its live :class:`~.kv_cache.PagePoolGroup` so spills always read
    the current cache arrays; tests may bind plain numpy pools.
    """

    def __init__(
        self,
        template: Dict[str, object],
        *,
        num_host_pages: int,
        page_size: int,
        gather_fn: Callable[[int], Dict[str, object]],
    ):
        if num_host_pages < 1:
            raise ValueError(
                f"need >= 1 host page, got {num_host_pages}"
            )
        self.capacity = int(num_host_pages)
        self.page_size = int(page_size)
        self._gather = gather_fn
        # Pinned-in-the-OS-sense host mirrors of every pool, page dim
        # replaced by the host capacity: [num_host_pages, page_size, ...].
        self._buffers = {
            name: jax.tree_util.tree_map(
                lambda leaf: np.zeros(
                    (self.capacity,) + tuple(leaf.shape[1:]),
                    dtype=leaf.dtype,
                ),
                pool,
            )
            for name, pool in template.items()
        }
        self.pool_names: Tuple[str, ...] = tuple(self._buffers)
        # LIFO free-slot stack + LRU entry map (oldest first), mirroring
        # the device allocator's free/_idle split.
        self._free_slots: List[int] = list(range(self.capacity - 1, -1, -1))
        self._entries: "OrderedDict[str, _HostEntry]" = OrderedDict()
        # key -> dispatched-but-undrained per-pool gathers.
        self._staged: Dict[str, Dict[str, object]] = {}
        # O(1) gauges, cross-checked against the sweep in
        # check_invariants() — a drifted counter is a bug, same contract
        # as the device allocator's _n_free/_n_referenced/_n_idle.
        self._n_resident = 0
        self._n_free = self.capacity
        # Lifetime counters (registry/bench surface).
        self.spills = 0
        self.fetches = 0
        self.spill_bytes_total = 0
        self.fetch_bytes_total = 0
        self.host_evictions = 0
        self.spill_drops = 0  # evictions lost because every slot was pinned

    # ------------------------------------------------------------- queries

    @property
    def pages_resident(self) -> int:
        """Host slots holding an entry (RESIDENT or spill-PENDING)."""
        return self._n_resident

    @property
    def pages_free(self) -> int:
        return self._n_free

    @property
    def pending_spills(self) -> int:
        """Spills dispatched but not yet drained into the host buffers."""
        return len(self._staged)

    def match(self, key: str, tokens: Sequence[int]) -> bool:
        """True when ``key`` is held with EXACTLY this token window —
        hash identity proposed, token content verified (the same
        no-collision-corruption rule as the device trie). PENDING
        entries match: the engine drains spills before any fetch reads
        them. Takes no pins and does not touch the LRU."""
        entry = self._entries.get(key)
        return entry is not None and entry.tokens == tuple(tokens)

    # ------------------------------------------------------------ spilling

    def note_evict(self, page: int, key: str, tokens: Sequence[int]) -> bool:
        """Device eviction is recycling ``page``, the trie entry for
        ``key``: spill it host-side instead of losing it. Dispatches the
        per-pool device gathers and returns immediately (True iff a spill
        was staged). A key already held is a clean write-back — content
        is immutable under its content address, so only the LRU moves.
        When every slot is pinned the spill is dropped, counted, never
        blocked on."""
        tokens = tuple(tokens)
        existing = self._entries.get(key)
        if existing is not None:
            # Same chain key => same prefix content; refresh recency only.
            self._entries.move_to_end(key)
            return False
        if not self._free_slots and not self._evict_host_lru():
            self.spill_drops += 1
            return False
        slot = self._free_slots.pop()
        self._n_free -= 1
        entry = _HostEntry(key, slot, tokens)
        self._entries[key] = entry
        self._n_resident += 1
        # The gather reads the page's pre-recycle content because device
        # programs execute in dispatch order: this dispatch lands before
        # any later prefill/decode that overwrites the page.
        self._staged[key] = self._gather(page)
        self.spills += 1
        return True

    def _evict_host_lru(self) -> bool:
        """Free the oldest unpinned host entry; False when all pinned."""
        for key, entry in self._entries.items():
            if entry.pins == 0:
                self._drop(key)
                self.host_evictions += 1
                return True
        return False

    def _drop(self, key: str) -> None:
        entry = self._entries.pop(key)
        self._staged.pop(key, None)
        self._free_slots.append(entry.slot)
        self._n_free += 1
        self._n_resident -= 1

    def drain_spills(self) -> int:
        """Materialize every staged gather into the host buffers (the
        one host sync of the spill path — the engine runs it once per
        step, overlapped work already dispatched). Returns the d2h bytes
        moved, which the engine charges to the transfer ledger under the
        ``hostkv_spill`` tag; the tier's own ``spill_bytes_total``
        counts the same bytes so the two ledgers cross-check exactly."""
        if not self._staged:
            return 0
        moved = 0
        for key, gathered in list(self._staged.items()):
            entry = self._entries.get(key)
            assert entry is not None and not entry.resident, (
                f"staged spill for unknown or resident key {key}"
            )
            slot = entry.slot
            for name, chunk in gathered.items():
                bufs = jax.tree_util.tree_leaves(self._buffers[name])
                vals = jax.tree_util.tree_leaves(chunk)
                for buf, val in zip(bufs, vals):
                    arr = np.asarray(val)
                    buf[slot] = arr
                    moved += arr.nbytes
            entry.resident = True
            del self._staged[key]
        self.spill_bytes_total += moved
        return moved

    # ------------------------------------------------------------ fetching

    def pin(self, key: str) -> None:
        """Protect ``key`` from the host LRU until its planned fetch
        stages it (or the scheduler drops the fetch and unpins)."""
        self._entries[key].pins += 1

    def unpin(self, key: str) -> None:
        entry = self._entries.get(key)
        if entry is None:
            return  # dropped fetch raced a host eviction of an unpinned twin
        entry.pins -= 1
        assert entry.pins >= 0, f"unpin underflow on host key {key}"

    def chunks(self, key: str) -> Dict[str, object]:
        """Per-pool host views of ``key``'s page for the h2d fetch
        program. Requires residency (the engine drains spills first);
        counts the fetch and its bytes, and touches the LRU. The views
        alias the host buffers — the engine's jit dispatch copies them
        h2d synchronously, before any later spill could reuse the slot."""
        entry = self._entries[key]
        assert entry.resident, (
            f"fetch of host key {key} before its spill drained"
        )
        self._entries.move_to_end(key)
        out: Dict[str, object] = {}
        nbytes = 0
        for name, bufs in self._buffers.items():
            views = jax.tree_util.tree_map(
                lambda buf: buf[entry.slot], bufs
            )
            nbytes += sum(
                v.nbytes for v in jax.tree_util.tree_leaves(views)
            )
            out[name] = views
        self.fetches += 1
        self.fetch_bytes_total += nbytes
        return out

    # --------------------------------------------------------- diagnostics

    def counters(self) -> Dict[str, int]:
        """Flat counter/gauge snapshot (``engine.stats()`` merge)."""
        return {
            "hostkv_pages_resident": self._n_resident,
            "hostkv_pages_capacity": self.capacity,
            "hostkv_spills": self.spills,
            "hostkv_fetches": self.fetches,
            "hostkv_spill_bytes": self.spill_bytes_total,
            "hostkv_fetch_bytes": self.fetch_bytes_total,
            "hostkv_evictions": self.host_evictions,
            "hostkv_spill_drops": self.spill_drops,
        }

    def status(self) -> Dict[str, object]:
        """The ``/statusz`` block (obs_top reads the resident gauge)."""
        doc: Dict[str, object] = dict(self.counters())
        doc["pools"] = list(self.pool_names)
        doc["pending_spills"] = len(self._staged)
        doc["pinned"] = sum(
            1 for e in self._entries.values() if e.pins > 0
        )
        return doc

    def check_invariants(self) -> None:
        """Full O(n) sweep: slots partition exactly into free + entries,
        no duplicates, staged keys are known and non-resident, pins are
        non-negative — and the O(1) gauges agree with the sweep."""
        free_set = set(self._free_slots)
        used = {e.slot for e in self._entries.values()}
        assert len(free_set) == len(self._free_slots), (
            "duplicate slot in host free stack"
        )
        assert len(used) == len(self._entries), (
            "two host entries share a slot"
        )
        assert not (free_set & used), (
            f"host slots both free and resident: {free_set & used}"
        )
        assert free_set | used == set(range(self.capacity)), (
            f"host slot leak: {len(free_set)} free + {len(used)} "
            f"resident != {self.capacity} slots"
        )
        assert all(e.pins >= 0 for e in self._entries.values()), (
            "negative pin count on a host entry"
        )
        for key in self._staged:
            entry = self._entries.get(key)
            assert entry is not None and not entry.resident, (
                f"staged spill for unknown or resident key {key}"
            )
        for key, entry in self._entries.items():
            assert entry.resident or key in self._staged, (
                f"non-resident host entry {key} with no staged spill"
            )
        assert self._n_resident == len(self._entries), (
            f"hostkv resident gauge drifted: "
            f"{self._n_resident} != {len(self._entries)}"
        )
        assert self._n_free == len(free_set), (
            f"hostkv free gauge drifted: {self._n_free} != {len(free_set)}"
        )

    def assert_quiescent(self) -> None:
        """Teardown gate (``engine.close()``): a pinned entry is a
        planned fetch that never executed, an undrained spill is d2h
        bytes the ledger never saw — both are leaks here."""
        pinned = [k for k, e in self._entries.items() if e.pins > 0]
        assert not pinned, (
            f"teardown leaked {len(pinned)} pinned host page(s): {pinned}"
        )
        assert not self._staged, (
            f"teardown with {len(self._staged)} undrained spill(s)"
        )
        self.check_invariants()
