"""Replica worker: one inference engine in its own process.

Spawned by :class:`~.replica.ProcessReplicaClient` as
``python -m distributed_pytorch_tpu.serving.replica_worker`` with its
spec in the ``TPURUN_REPLICA_SPEC`` env var. The worker builds the
engine, optionally warms the prefill shape buckets (so a fleet drill's
failover TTFT measures routing, not XLA compiles), starts TWO servers on
kernel-assigned ports —

* the standard :class:`~distributed_pytorch_tpu.obs.server
  .IntrospectionServer` (``/metrics`` ``/healthz`` ``/statusz``
  ``/snapshot`` ``/trace`` ``/postmortem`` — everything a fleet scraper
  or ``MetricsRegistry.merge_remote`` expects),
* a control server (this module) carrying the data plane —

then announces both in ONE hello line on stdout and serves until told to
shut down (or until stdin hits EOF: the parent died, so exit rather than
orphan — unless ``TPURUN_ORPHAN_GRACE`` grants a re-adoption window, see
below).

Orphan grace: by default stdin EOF is death (``os._exit(3)``), which is
the right call when the parent's crash means nobody will ever route to
this worker again. But when the parent is a *recoverable* router (its
journal + the worker registry let a successor re-attach), killing healthy
workers turns one control-plane crash into a whole-fleet outage. Setting
``TPURUN_ORPHAN_GRACE=<seconds>`` makes the watchdog enter an ORPHANED
state on EOF instead: the worker keeps serving its control port, records
``orphan_enter`` in the flight recorder, and waits for a successor router
to claim it via ``POST /adopt``. Adoption clears the state
(``orphan_exit``); if the grace deadline passes unclaimed the worker
records ``orphan_suicide`` and dies exactly as before — true orphans
still die, just later. Note the worker is effectively FROZEN while
orphaned: the control plane only advances the engine on ``/step``, and
nobody is calling it.

Control-plane wire format (all JSON over localhost HTTP):

==================  ========================================================
endpoint            semantics
==================  ========================================================
``POST /submit``    ``{rid, prompt, params, metadata, tenant_id, mods,
                    trace_id}`` -> ``{req_id}``. ``rid`` is the client-
                    minted idempotency key: a replayed rid returns the
                    ORIGINAL req_id without re-admitting (the replay map
                    that makes submit retry-safe). Admission refusals come
                    back as 409 + exception class name.
``POST /step``      ``{ack: [req_id...]}`` -> ``{finished, statuses, load,
                    queue_depth, slo_firing, idle_fraction, trace?}``.
                    ``finished`` is every finished-but-unacked id — an
                    at-least-once protocol: a response lost in transport
                    is re-reported next step until the client acks it.
                    ``statuses`` carries every live + unacked request, so
                    the client's shadow refresh costs zero extra calls.
``GET /poll?id=``   one request's status; 404 (KeyError) when unknown.
``POST /cancel``    ``{req_id}`` -> ``{ok}`` (False for unknown: engine
                    cancel semantics, never raises).
``POST /drain``     ``{reason}`` -> ``{snapshot, statuses}`` — the
                    SIGTERM-with-notice protocol, run worker-side.
``POST /restore``   ``{snapshot, rebase_ids}`` -> ``{restored}`` —
                    fingerprint refusals come back as 409 ValueError.
``POST /reserve_ids``  ``{base}`` -> ``{next_id}`` (id-space namespacing).
``POST /adopt``     ``{name?, pid?, fingerprint?}`` -> ``{name, pid,
                    fingerprint, orphaned}`` — a successor router claims
                    this worker after the original parent died. Any
                    provided field that mismatches the worker's identity
                    is refused with 409 (the PID-reuse guard: a registry
                    entry whose pid now belongs to a different process
                    must not be adopted). Idempotent; also answers the
                    identity probe for a router that merely wants to
                    verify a registry entry.
``GET /health``     ``{status: live|draining|closed}`` (always 200 — the
                    verdict is the payload; transport failure is the
                    signal the breaker consumes).
``GET /gauge?name=``  one registry gauge, for drill assertions.
``GET /describe``   ``engine.status()`` (the /statusz document).
``POST /shutdown``  close the engine (allocator leak asserts run HERE and
                    surface as a 500 + non-zero exit), answer, exit 0.
==================  ========================================================

Mutating handlers serialize on one worker lock AND the engine's registry
lock, so introspection scrapes keep their step-boundary-consistent view.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

_JSON = "application/json"
SPEC_ENV = "TPURUN_REPLICA_SPEC"


def _status_doc(status) -> dict:
    return {
        "req_id": status.req_id,
        "state": status.state,
        "prompt_len": status.prompt_len,
        "generated": list(status.generated),
        "finished": status.finished,
        "preempt_count": status.preempt_count,
    }


def build_engine(spec: dict):
    """Build the worker's engine from its spec: either a dotted
    ``factory`` (``"pkg.mod:fn"`` called with ``factory_kwargs``) for
    arbitrary setups, or the builtin demo path — ``model`` kwargs for
    :class:`~distributed_pytorch_tpu.models.transformer.TransformerLM`,
    ``init_seed`` for params, ``engine`` kwargs for the engine itself,
    plus ``trace`` (bool) and ``flight`` ({capacity, path}) riders."""
    if "factory" in spec:
        import importlib

        mod_name, _, fn_name = spec["factory"].partition(":")
        fn = getattr(importlib.import_module(mod_name), fn_name)
        return fn(**spec.get("factory_kwargs", {}))

    import jax
    import jax.numpy as jnp

    from distributed_pytorch_tpu.models.transformer import TransformerLM
    from distributed_pytorch_tpu.obs import FlightRecorder, Tracer
    from distributed_pytorch_tpu.serving.engine import InferenceEngine

    model_kw = dict(spec.get("model", {}))
    if "dtype" in model_kw:
        model_kw["dtype"] = jnp.dtype(model_kw["dtype"])
    model = TransformerLM(**model_kw)
    params = model.init(
        jax.random.PRNGKey(int(spec.get("init_seed", 0))),
        jnp.zeros((1, 8), jnp.int32),
    )["params"]
    engine_kw = dict(spec.get("engine", {}))
    if spec.get("trace"):
        engine_kw["tracer"] = Tracer()
    flight_spec = spec.get("flight")
    if flight_spec:
        engine_kw["flight"] = FlightRecorder(
            int(flight_spec.get("capacity", 4096)),
            path=flight_spec.get("path"),
        )
    return InferenceEngine(model, params, **engine_kw)


def warm_engine(engine, chunks) -> None:
    """Pre-compile the prefill shape buckets (one dummy request per
    prompt length) plus the decode step, then drain — so the serving run
    never pays an XLA compile mid-drill."""
    from distributed_pytorch_tpu.serving.scheduler import SamplingParams

    vocab = getattr(engine, "vocab_size", None) or 8
    for n in chunks:
        prompt = [(i % max(1, vocab - 2)) + 1 for i in range(int(n))]
        engine.submit(prompt, SamplingParams(max_new_tokens=2))
        engine.run()


class ReplicaControlServer:
    """The control half of the worker: a stdlib HTTP server whose
    handlers drive the engine under one lock. Port 0 always — the caller
    reads the kernel's choice from :attr:`url`."""

    def __init__(
        self,
        engine,
        *,
        host: str = "127.0.0.1",
        trace_every: int = 4,
        flight_dump_every: int = 0,
    ):
        self.engine = engine
        self.trace_every = max(1, int(trace_every))
        self.flight_dump_every = int(flight_dump_every)
        self._lock = threading.Lock()
        self._replay = {}  # rid -> req_id (submit idempotency)
        self._unacked = set()  # finished ids not yet acked by the client
        self._steps = 0
        self.shutdown_event = threading.Event()
        # Orphan-grace state (see module docstring): the stdin watchdog
        # flips `orphaned` on parent EOF and waits on `adopted_event`; a
        # successor router's POST /adopt sets it. `identity` is what the
        # adopter must match — main() fills it from the hello document.
        self.adopted_event = threading.Event()
        self.orphaned = False
        self.identity: dict = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                outer._route(self, None)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(
                        self.rfile.read(length).decode("utf-8") or "{}"
                    )
                except ValueError:
                    outer._send(self, 400, {
                        "error_kind": "ValueError",
                        "error": "malformed JSON body",
                    })
                    return
                outer._route(self, body)

        self._httpd = ThreadingHTTPServer((host, 0), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ReplicaControlServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="replica-control",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------- routing

    @staticmethod
    def _send(handler, code: int, doc: dict) -> None:
        payload = json.dumps(doc, default=str).encode("utf-8")
        try:
            handler.send_response(code)
            handler.send_header("Content-Type", _JSON)
            handler.send_header("Content-Length", str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client gave up (deadline); at-least-once covers it

    def _route(self, handler, body) -> None:
        from distributed_pytorch_tpu.serving.admission import AdmissionError

        parsed = urlparse(handler.path)
        op = parsed.path.rstrip("/") or "/"
        try:
            if op == "/submit":
                doc = self._submit(body)
            elif op == "/step":
                doc = self._step(body)
            elif op == "/poll":
                doc = self._poll(parse_qs(parsed.query))
            elif op == "/cancel":
                with self._lock:
                    doc = {"ok": self.engine.cancel(int(body["req_id"]))}
            elif op == "/drain":
                doc = self._drain(body)
            elif op == "/restore":
                doc = self._restore(body)
            elif op == "/reserve_ids":
                with self._lock:
                    self.engine._next_id = max(
                        self.engine._next_id, int(body["base"])
                    )
                    doc = {"next_id": self.engine._next_id}
            elif op == "/adopt":
                doc = self._adopt(body or {})
            elif op == "/health":
                doc = {"status": self.engine.health()}
            elif op == "/gauge":
                name = parse_qs(parsed.query).get("name", [""])[0]
                doc = {
                    "name": name,
                    "value": self.engine.registry.read_gauge(name),
                }
            elif op == "/describe":
                doc = self.engine.status()
            elif op == "/shutdown":
                doc = self._shutdown()
            else:
                self._send(handler, 404, {
                    "error_kind": "NotFound", "error": op,
                })
                return
        except AdmissionError as exc:
            # An ANSWER, not a failure: the class name crosses the wire
            # and the client re-raises the real admission type.
            self._send(handler, 409, {
                "error_kind": type(exc).__name__, "error": str(exc),
            })
            return
        except KeyError as exc:
            self._send(handler, 404, {
                "error_kind": "KeyError", "error": str(exc),
            })
            return
        except ValueError as exc:
            self._send(handler, 409, {
                "error_kind": "ValueError", "error": str(exc),
            })
            return
        except Exception as exc:  # handler bug or engine crash: 500
            self._send(handler, 500, {
                "error_kind": type(exc).__name__, "error": repr(exc),
            })
            return
        self._send(handler, 200, doc)
        if op == "/shutdown":
            self.shutdown_event.set()

    # ------------------------------------------------------------ handlers

    def _submit(self, body: dict) -> dict:
        from distributed_pytorch_tpu.serving.elastic import params_from_doc
        from distributed_pytorch_tpu.serving.mods import Mods

        rid = body.get("rid")
        with self._lock:
            if rid is not None and rid in self._replay:
                # Idempotent replay: the first attempt's admission stands.
                return {"req_id": self._replay[rid], "replayed": True}
            params = params_from_doc(body.get("params"))
            mods = (
                Mods.from_spec(body["mods"]) if body.get("mods") else None
            )
            req_id = self.engine.submit(
                [int(t) for t in body["prompt"]],
                params,
                body.get("metadata"),
                tenant_id=body.get("tenant_id") or "anon",
                mods=mods,
                trace_id=body.get("trace_id"),
            )
            if rid is not None:
                self._replay[rid] = req_id
            return {"req_id": req_id}

    def _step(self, body: dict) -> dict:
        engine = self.engine
        with self._lock:
            for rid in (body or {}).get("ack", []):
                self._unacked.discard(int(rid))
            finished_now = engine.step()
            self._unacked.update(finished_now)
            self._steps += 1
            statuses = []
            for rid, req in list(engine.requests.items()):
                if not req.done or rid in self._unacked:
                    statuses.append(_status_doc(engine.poll(rid)))
            reg = engine.registry
            doc = {
                "finished": sorted(self._unacked),
                "statuses": statuses,
                "load": (
                    reg.read_gauge("queue_depth")
                    + reg.read_gauge("running_requests")
                ),
                "queue_depth": reg.read_gauge("queue_depth"),
                "slo_firing": self._slo_firing(),
                "idle_fraction": self._idle_fraction(),
            }
            if (
                engine.tracer.enabled
                and self._steps % self.trace_every == 0
            ):
                # Piggybacked trace snapshot: the client caches the last
                # one, so a SIGKILLed worker's lanes survive into the
                # merged fleet waterfall.
                doc["trace"] = engine.tracer.to_perfetto()
            if (
                self.flight_dump_every
                and engine.flight.enabled
                and self._steps % self.flight_dump_every == 0
            ):
                # Rolling on-disk postmortem: the recovery artifact for a
                # SIGKILL, which by definition never dumps at fault time.
                engine._dump_postmortem("rolling")
            return doc

    def _poll(self, query: dict) -> dict:
        req_id = int(query.get("id", ["-1"])[0])
        with self._lock:
            return _status_doc(self.engine.poll(req_id))

    def _drain(self, body: dict) -> dict:
        from distributed_pytorch_tpu.serving.elastic import drain_engine

        engine = self.engine
        with self._lock, engine.registry.lock:
            snap = drain_engine(
                engine, reason=(body or {}).get("reason", "drain")
            )
            statuses = [
                _status_doc(engine.poll(rid)) for rid in engine.requests
            ]
        return {"snapshot": snap.to_json(), "statuses": statuses}

    def _restore(self, body: dict) -> dict:
        from distributed_pytorch_tpu.serving.elastic import (
            EngineSnapshot,
            restore_engine,
        )

        engine = self.engine
        with self._lock, engine.registry.lock:
            ids = restore_engine(
                engine,
                EngineSnapshot.from_json(body["snapshot"]),
                rebase_ids=bool(body.get("rebase_ids", False)),
            )
        return {"restored": ids}

    def _adopt(self, body: dict) -> dict:
        """Claim (or identity-probe) this worker for a successor router.

        Refuses with ValueError -> 409 on any identity mismatch: a
        registry entry can outlive its worker, and its recorded pid can
        be reborn as an unrelated process — adoption must never succeed
        against the wrong engine."""
        for key in ("name", "pid", "fingerprint"):
            want = body.get(key)
            if want is not None and want != self.identity.get(key):
                raise ValueError(
                    f"adopt refused: {key} mismatch "
                    f"(want {want!r}, have {self.identity.get(key)!r})"
                )
        was_orphaned = self.orphaned
        self.orphaned = False
        self.adopted_event.set()
        doc = dict(self.identity)
        doc["orphaned"] = was_orphaned
        return doc

    def _shutdown(self) -> dict:
        with self._lock:
            # Leak asserts (debug engines) raise HERE: the client sees a
            # 500 and the worker exits non-zero — a failed quiescence
            # check is loud on both sides of the process boundary.
            self.engine.close()
        return {"ok": True}

    def _slo_firing(self) -> list:
        slo = getattr(self.engine, "slo", None)
        if slo is None:
            return []
        return [n for n, st in slo.state().items() if st["firing"]]

    def _idle_fraction(self):
        goodput = getattr(self.engine, "goodput", None)
        if goodput is None:
            return None
        total = goodput.productive_s + goodput.wasted_total_s()
        if total <= 0:
            return None
        return goodput.wasted["budget_idle"] / total


def main() -> int:
    spec_text = os.environ.get(SPEC_ENV)
    if not spec_text:
        print(f"replica_worker: {SPEC_ENV} not set", file=sys.stderr)
        return 2
    spec = json.loads(spec_text)
    engine = build_engine(spec)
    if spec.get("warm_chunks"):
        warm_engine(engine, spec["warm_chunks"])
    host = spec.get("host", "127.0.0.1")
    obs = engine.serve(host=host)
    control = ReplicaControlServer(
        engine,
        host=host,
        trace_every=int(spec.get("trace_every", 4)),
        flight_dump_every=int(spec.get("flight_dump_every", 0)),
    ).start()

    fp = {
        "page_size": engine.page_size,
        "max_seq_len": engine.max_seq_len,
        "top_k": engine._top_k,
        "top_p": engine._top_p,
        "speculative": engine.speculative,
        "mesh": engine.mesh_fingerprint,
    }
    hello = {
        "pid": os.getpid(),
        "name": os.environ.get("TPURUN_REPLICA_NAME", spec.get("name")),
        "control_url": control.url,
        "obs_url": obs.url,
        "fingerprint": fp,
    }
    control.identity = dict(hello)
    print(json.dumps({"replica_hello": hello}), flush=True)

    def _watch_stdin():
        # Orphan prevention: stdin EOF means the parent is gone. os._exit
        # because a vanished parent deserves SIGKILL semantics, not
        # graceful teardown racing interpreter shutdown. Raw os.read, NOT
        # sys.stdin.buffer: a daemon thread blocked holding the buffered
        # reader's lock deadlocks CPython finalization on a clean exit.
        try:
            while os.read(0, 4096):
                pass
        except OSError:
            pass
        if control.shutdown_event.is_set():
            return
        try:
            grace = float(os.environ.get("TPURUN_ORPHAN_GRACE", "0") or 0.0)
        except ValueError:
            grace = 0.0
        if grace <= 0:
            os._exit(3)
        # Re-adoption window: survive the parent's death for `grace`
        # seconds so a recovered router can claim us via /adopt. The
        # deadline is HARD — grace is not re-armed by near-miss adopters,
        # and a second parent death after adoption gets no second window
        # (the event stays set); true orphans die, just late enough for
        # recovery to happen.
        flight = getattr(engine, "flight", None)

        def _say(msg):
            # Our stdout pipe's reader just died; writing to it raises
            # BrokenPipeError, which would kill this thread before the
            # grace machinery runs. Best-effort only, past this point.
            try:
                print(msg, flush=True)
            except (OSError, ValueError):
                pass

        control.orphaned = True
        if flight is not None and flight.enabled:
            flight.record(
                "orphan_enter", grace_s=grace, pid=os.getpid(),
            )
        _say(
            f"[worker] parent EOF; orphaned, serving {grace:.1f}s "
            f"awaiting re-adoption (pid {os.getpid()})"
        )
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if control.adopted_event.wait(timeout=0.05):
                break
            if control.shutdown_event.is_set():
                return
        if control.adopted_event.is_set():
            if flight is not None and flight.enabled:
                flight.record("orphan_exit", adopted=True)
            _say("[worker] re-adopted; resuming service")
            return
        if control.shutdown_event.is_set():
            return
        if flight is not None and flight.enabled:
            flight.record("orphan_suicide", grace_s=grace)
            try:
                engine._dump_postmortem("orphan_suicide")
            except Exception:
                pass
        _say(f"[worker] orphan grace expired after {grace:.1f}s; exiting")
        os._exit(3)

    threading.Thread(
        target=_watch_stdin, name="parent-watch", daemon=True
    ).start()

    control.shutdown_event.wait()
    control.stop()
    # engine.close() (already run by /shutdown) stops the obs server too;
    # stop again for the factory-path engines that override close().
    try:
        obs.stop()
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
