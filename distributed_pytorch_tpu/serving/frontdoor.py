"""The production front door: streaming, cancellation, multi-tenant
fair-share admission, and per-request model mods over one surface.

Everything below `submit/step/poll` is an engine implementation detail;
everything a *client* touches lives here, behind
:meth:`FrontDoor.open_stream`:

* **Token streaming.** The engine's overlapped step loop already
  produces tokens incrementally (``note_decode_dispatched`` at dispatch,
  ``resolve_decoded`` at readback); a :class:`TokenStream` exposes that
  split as an ordered per-request iterator. Delivery is zero-copy — the
  stream reads straight out of the request's committed ``generated``
  list at its ``delivered`` high-water mark, so streamed tokens are
  definitionally bitwise-identical to polled ones. The high-water mark
  lives ON the request, which is what lets a drain snapshot record it
  and a restored stream resume without replaying or skipping a token.
* **Backpressure.** A slow consumer's undelivered backlog
  (``len(generated) - delivered``) is bounded by ``max_stream_buffer``:
  the pump refuses to step the engine while any open stream is over
  budget (counted in ``backpressure_stalls_total``), so generation never
  runs unboundedly ahead of consumption.
* **Cancellation.** ``stream.cancel()`` plumbs the engine's ``cancel()``
  through the handle — pages freed mid-flight, partial output still
  drainable, ``cancelled_by_client_total`` counted. Queued-but-unadmitted
  streams cancel without ever touching the engine.
* **Fair share.** Stride scheduling (WFQ) over per-tenant queues: each
  admission advances the tenant's virtual time by ``cost / weight``
  (cost = prompt + max_new_tokens), and the backlogged tenant with the
  LOWEST virtual time admits next, so throughput converges to the
  weight ratio under contention. A tenant returning from idle re-enters
  at ``max(own, global)`` virtual time — idle credit does not bank, and
  its share redistributes to active tenants while it is away. Engine
  priority remains submission order, so door-admission order IS engine
  priority. Per-tenant token-rate buckets and queue quotas bound each
  tenant independently of the shared engine queue.
* **Per-tenant SLOs.** The door measures what the *client* sees — TTFT
  and TPOT at token visibility, per tenant, in ``ReservoirGroup``
  reservoirs — and feeds them to ``obs/slo.py`` burn-rate objectives per
  tenant class, so one tenant's overload fires that tenant's alerts and
  nobody else's.
* **Model mods.** ``open_stream(mods=Mods(...))`` threads per-request
  stop-sequences (via ``SamplingParams``), logit-bias, grammar masks,
  and LoRA adapter selection down to the engine's one compiled decode
  program as fixed-shape operands / params swaps — never a recompile.

The door fronts either a single :class:`~.engine.InferenceEngine` or a
:class:`~.fleet.FleetRouter` (streams then ride fleet ids, surviving
failover and hedging); the backend is detected by duck type.
"""

from __future__ import annotations

import collections
import time
from typing import Deque, Dict, List, Optional, Tuple

import dataclasses

from distributed_pytorch_tpu.metrics import ReservoirGroup
from distributed_pytorch_tpu.obs import MetricsRegistry
from distributed_pytorch_tpu.obs.disttrace import prune_trace
from distributed_pytorch_tpu.obs.slo import SLObjective, SLOMonitor
from distributed_pytorch_tpu.obs.tracer import NULL_TRACER, _PID_DOOR
from distributed_pytorch_tpu.serving.admission import (
    AdmissionError,
    EngineDraining,
    QueueFull,
    RequestTooLong,
)
from distributed_pytorch_tpu.serving.mods import Mods
from distributed_pytorch_tpu.serving.scheduler import SamplingParams


class TenantQuotaExceeded(AdmissionError):
    """The tenant's own door-queue quota is full (the shared engine queue
    may be empty — quotas isolate tenants from each other's bursts)."""


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """One tenant class's contract with the front door.

    ``weight`` is the fair-share stride weight (2.0 gets twice the
    admissions of 1.0 under contention). ``max_queued`` bounds the
    tenant's DOOR queue (None = unbounded); ``rate_tokens_per_s`` /
    ``burst_tokens`` configure the admission token bucket, charged at
    admission with the request's cost (prompt + max_new_tokens).
    ``ttft_slo_s`` / ``tpot_slo_s`` declare per-tenant latency
    objectives: set, they become ``obs/slo.py`` burn-rate alerts over
    the door's per-tenant reservoirs."""

    weight: float = 1.0
    max_queued: Optional[int] = None
    rate_tokens_per_s: Optional[float] = None
    burst_tokens: Optional[float] = None
    ttft_slo_s: Optional[float] = None
    tpot_slo_s: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")


class _Pending:
    """A stream waiting in its tenant's door queue for fair-share
    admission."""

    __slots__ = (
        "stream", "prompt", "params", "mods", "metadata",
        "pace_t0", "paced_s",
    )

    def __init__(self, stream, prompt, params, mods, metadata):
        self.stream = stream
        self.prompt = prompt
        self.params = params
        self.mods = mods
        self.metadata = metadata
        # Token-bucket pacing accounting: ``pace_t0`` is set while this
        # pending sits at the head of its tenant queue with an empty
        # bucket; the accumulated ``paced_s`` is reported on the door's
        # "admitted" trace event so the waterfall can carve pacing delay
        # out of generic queue wait.
        self.pace_t0: Optional[float] = None
        self.paced_s = 0.0

    @property
    def cost(self) -> int:
        return len(self.prompt) + self.params.max_new_tokens


class TokenStream:
    """Ordered per-request token iterator with a final-status terminator.

    Iteration yields committed tokens as they resolve (pumping the door
    as needed) and raises ``StopIteration`` once the request is terminal
    and fully delivered; ``status`` then reports the terminator
    (``"finished"``, ``"cancelled"``, ``"expired"``, or ``"rejected"``).
    ``delivered`` is the client-visible high-water mark — it advances
    only when the consumer takes a token, and it is what a drain
    snapshot records mid-stream."""

    def __init__(self, door: "FrontDoor", tenant: str):
        self._door = door
        self.tenant = tenant
        self.req_id: Optional[int] = None
        self.delivered = 0
        # Door-side terminal override for streams that never reached the
        # engine ("cancelled" while queued, "rejected" at admission).
        self._override: Optional[str] = None
        self._reject_reason: Optional[str] = None
        self._finalized = False
        # Client-visibility timing (what the per-tenant SLO reservoirs
        # record): set by the door's pump as tokens become visible.
        self.submit_t: float = 0.0
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.seen = 0
        # Fleet-wide trace identity: minted by the door at open_stream
        # (or supplied by the caller), carried down through router and
        # engine so one id names the request in every layer's trace.
        self.trace_id: Optional[str] = None
        self.sid: int = -1  # door span id (stream sequence number)
        self._minted_trace = True
        self._trace_closed = False

    # ------------------------------------------------------------- status

    @property
    def status(self) -> str:
        if self._override is not None:
            return self._override
        if self.req_id is None:
            return "queued"
        return self._door._backend.state(self.req_id)

    @property
    def done(self) -> bool:
        if self._override is not None:
            return True
        if self.req_id is None:
            return False
        return self._door._backend.done(self.req_id)

    def backlog(self) -> int:
        """Committed-but-undelivered tokens (the backpressure measure)."""
        if self.req_id is None:
            return 0
        return len(self._door._backend.generated(self.req_id)) - (
            self.delivered
        )

    # ----------------------------------------------------------- consume

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        pumps = 0
        while True:
            if self._override is not None and self.req_id is None:
                raise StopIteration
            if self.req_id is not None:
                gen = self._door._backend.generated(self.req_id)
                if self.delivered < len(gen):
                    tok = int(gen[self.delivered])
                    self.delivered += 1
                    self._door._backend.note_delivered(
                        self.req_id, self.delivered
                    )
                    return tok
                if self.done:
                    raise StopIteration
            self._door.pump()
            pumps += 1
            if pumps > self._door.max_pumps_per_token:
                raise RuntimeError(
                    f"stream for tenant {self.tenant!r} made no progress "
                    f"after {pumps} pumps — another stream is likely "
                    "holding the door at its backpressure cap without "
                    "being consumed"
                )

    def drain(self) -> List[int]:
        """Consume the stream to its terminator; returns the tokens taken
        by THIS call (resuming mid-stream returns only the remainder)."""
        return list(self)

    def cancel(self) -> None:
        self._door.cancel(self)


class FrontDoor:
    """Async-style serving gateway over an engine or fleet router.

    Single-threaded by design, like everything in the serving stack:
    ``pump()`` is one cooperative round (refill rate buckets, fair-share
    admit, step the backend unless backpressured, observe per-tenant
    latencies, tick SLOs), and stream iteration pumps on demand. Tests
    and the bench drive it in a loop; an async wrapper would call it
    from an event loop."""

    def __init__(
        self,
        backend,
        *,
        tenants: Optional[Dict[str, TenantConfig]] = None,
        default_tenant: str = "anon",
        max_stream_buffer: int = 64,
        max_inflight: Optional[int] = None,
        reservoir_capacity: int = 1024,
        clock=time.perf_counter,
        slo: bool = True,
        max_pumps_per_token: int = 10_000,
        tracer=None,
        sampler=None,
    ):
        self._backend = _make_backend(backend)
        self._clock = clock
        # Door-lane tracer (pid 3 in the merged fleet trace) and the
        # optional head+tail trace sampler that decides, at stream end,
        # whether a trace_id's spans stay in every layer's tracer.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.sampler = sampler
        self._next_sid = 0
        self._stall_t0: Optional[float] = None
        self.max_stream_buffer = int(max_stream_buffer)
        self.max_pumps_per_token = int(max_pumps_per_token)
        self.tenants: Dict[str, TenantConfig] = dict(tenants or {})
        self.tenants.setdefault(default_tenant, TenantConfig())
        self.default_tenant = default_tenant
        # Admitted-but-unfinished cap: bounds how deep the door stuffs
        # the engine queue. Too deep and engine FIFO (id = priority)
        # overrides fair share; one batch-worth of headroom keeps slots
        # fed while leaving ordering decisions at the door.
        self.max_inflight = (
            int(max_inflight)
            if max_inflight is not None
            else 2 * self._backend.slots_hint()
        )
        self._queues: Dict[str, Deque[_Pending]] = {
            t: collections.deque() for t in self.tenants
        }
        # Stride/WFQ state. ``_global_v`` tracks the virtual time of the
        # last admission; a tenant going from idle to backlogged rejoins
        # at max(own, global) so idle time never banks credit.
        self._vtime: Dict[str, float] = {t: 0.0 for t in self.tenants}
        self._global_v = 0.0
        # Token buckets: level (tokens) + last refill stamp, per tenant.
        now = self._clock()
        self._bucket: Dict[str, Tuple[float, float]] = {}
        for t, cfg in self.tenants.items():
            if cfg.rate_tokens_per_s is not None:
                burst = (
                    cfg.burst_tokens
                    if cfg.burst_tokens is not None
                    else cfg.rate_tokens_per_s
                )
                self._bucket[t] = (float(burst), now)
        # Streams the pump still watches (admitted or queued, not yet
        # finalized). Finalized streams stay iterable — they just stop
        # costing the pump anything.
        self._active: List[TokenStream] = []
        self._by_req: Dict[int, TokenStream] = {}
        # Counters (pull-registered below).
        self.streams_opened = 0
        self.admitted = 0
        self.finished = 0
        self.cancelled_by_client = 0
        self.rejected_quota = 0
        self.rejected = 0
        self.backpressure_stalls = 0
        self.pumps = 0
        labels = tuple(sorted(self.tenants))
        self._ttft = ReservoirGroup(
            labels, capacity=reservoir_capacity, seed=11
        )
        self._tpot = ReservoirGroup(
            labels, capacity=reservoir_capacity, seed=13
        )
        # Per-tenant waterfall components, recorded door-side as requests
        # pass each stage (`tools/obs_top.py --tenant` renders these).
        self._wf_queue_wait = ReservoirGroup(
            labels, capacity=reservoir_capacity, seed=17
        )
        self._wf_pacing = ReservoirGroup(
            labels, capacity=reservoir_capacity, seed=19
        )
        self._wf_decode = ReservoirGroup(
            labels, capacity=reservoir_capacity, seed=23
        )
        self.registry = self._build_registry()
        objectives = self.slo_objectives()
        self.slo = (
            SLOMonitor(self.registry, objectives, clock=clock)
            if slo and objectives
            else None
        )

    # ------------------------------------------------------------ metrics

    def _build_registry(self) -> MetricsRegistry:
        reg = MetricsRegistry(namespace="frontdoor")
        reg.counter_fn("streams_opened_total", lambda: self.streams_opened)
        reg.counter_fn("admitted_total", lambda: self.admitted)
        reg.counter_fn("finished_total", lambda: self.finished)
        reg.counter_fn(
            "cancelled_by_client_total", lambda: self.cancelled_by_client
        )
        reg.counter_fn(
            "rejected_quota_total", lambda: self.rejected_quota
        )
        reg.counter_fn("rejected_total", lambda: self.rejected)
        reg.counter_fn(
            "backpressure_stalls_total", lambda: self.backpressure_stalls
        )
        reg.counter_fn("pumps_total", lambda: self.pumps)
        reg.gauge_fn(
            "queued_streams",
            lambda: sum(len(q) for q in self._queues.values()),
        )
        reg.gauge_fn("active_streams", lambda: len(self._active))
        reg.reservoir(
            "ttft_by_tenant",
            lambda: self._ttft,
            label="tenant",
            help="Client-visible time to first token, per tenant",
        )
        reg.reservoir(
            "tpot_by_tenant",
            lambda: self._tpot,
            label="tenant",
            help="Client-visible per-token latency, per tenant",
        )
        reg.reservoir(
            "waterfall_queue_wait_by_tenant",
            lambda: self._wf_queue_wait,
            label="tenant",
            help="Door queue wait to admission (pacing excluded), per tenant",
        )
        reg.reservoir(
            "waterfall_pacing_by_tenant",
            lambda: self._wf_pacing,
            label="tenant",
            help="Token-bucket pacing delay at the door, per tenant",
        )
        reg.reservoir(
            "waterfall_decode_by_tenant",
            lambda: self._wf_decode,
            label="tenant",
            help="First-to-last token decode window, per tenant",
        )
        return reg

    def slo_objectives(self) -> List[SLObjective]:
        """Burn-rate objectives derived from the tenant contracts — one
        latency objective per declared threshold, labeled by tenant, so
        each class burns its own budget and only its own."""
        objs: List[SLObjective] = []
        for tenant, cfg in sorted(self.tenants.items()):
            if cfg.ttft_slo_s is not None:
                objs.append(
                    SLObjective(
                        name=f"ttft_{tenant}",
                        metric="ttft_by_tenant",
                        quantile=0.95,
                        threshold_s=cfg.ttft_slo_s,
                        label=tenant,
                    )
                )
            if cfg.tpot_slo_s is not None:
                objs.append(
                    SLObjective(
                        name=f"tpot_{tenant}",
                        metric="tpot_by_tenant",
                        quantile=0.95,
                        threshold_s=cfg.tpot_slo_s,
                        label=tenant,
                    )
                )
        return objs

    # ---------------------------------------------------------------- API

    def open_stream(
        self,
        prompt,
        tenant: Optional[str] = None,
        *,
        params: Optional[SamplingParams] = None,
        mods: Optional[Mods] = None,
        metadata: Optional[dict] = None,
        trace_id: Optional[str] = None,
    ) -> TokenStream:
        """Enqueue one request under ``tenant`` and return its stream.

        The request reaches the engine at the door's fair-share pace (the
        stream pumps as you iterate — callers never wait on admission
        explicitly). Raises :class:`TenantQuotaExceeded` when the
        tenant's own queue quota is full and ``KeyError`` for an
        undeclared tenant.

        ``trace_id`` (normally minted here) is the fleet-wide identity
        this request keeps through routing, hedging, preemption, and
        failover — pass one only to join an externally-initiated trace."""
        tenant = tenant if tenant is not None else self.default_tenant
        cfg = self.tenants.get(tenant)
        if cfg is None:
            raise KeyError(
                f"undeclared tenant {tenant!r}; declared: "
                f"{sorted(self.tenants)}"
            )
        queue = self._queues[tenant]
        if cfg.max_queued is not None and len(queue) >= cfg.max_queued:
            self.rejected_quota += 1
            raise TenantQuotaExceeded(
                f"tenant {tenant!r} queue quota ({cfg.max_queued}) full"
            )
        params = params or SamplingParams()
        stream = TokenStream(self, tenant)
        stream.submit_t = self._clock()
        stream._minted_trace = trace_id is None
        if trace_id is None:
            trace_id = f"d{self._next_sid:06x}"
        stream.trace_id = trace_id
        stream.sid = self._next_sid
        self._next_sid += 1
        if self.tracer.enabled:
            self.tracer.span_begin(
                _PID_DOOR,
                stream.sid,
                "stream",
                trace_id=trace_id,
                tenant=tenant,
                prompt_len=len(prompt),
                max_new_tokens=params.max_new_tokens,
            )
        if not queue:
            # Idle -> backlogged: rejoin the stride race at the current
            # global virtual time (no banked credit from idling).
            self._vtime[tenant] = max(self._vtime[tenant], self._global_v)
        queue.append(
            _Pending(stream, [int(t) for t in prompt], params, mods,
                     metadata)
        )
        self._active.append(stream)
        self.streams_opened += 1
        return stream

    def cancel(self, stream: TokenStream) -> None:
        """Client cancellation through the stream handle. Queued streams
        die at the door; admitted ones cancel in the engine (pages freed
        mid-flight, partial output still drainable). Idempotent."""
        if stream.done:
            return
        if stream.req_id is None:
            queue = self._queues[stream.tenant]
            try:
                queue.remove(
                    next(p for p in queue if p.stream is stream)
                )
            except StopIteration:
                pass
            stream._override = "cancelled"
            # Never reached the engine: the door span is this stream's
            # whole trace — close it (and let the sampler judge it) now.
            self._close_trace(stream, "cancelled")
        else:
            self._backend.cancel(stream.req_id)
        self.cancelled_by_client += 1

    def pump(self) -> List[int]:
        """One cooperative round; returns backend-finished request ids."""
        self.pumps += 1
        self._admit()
        blocked = any(
            s.backlog() >= self.max_stream_buffer
            for s in self._active
            if s.req_id is not None and not s.done
        )
        if blocked:
            self.backpressure_stalls += 1
            if self._stall_t0 is None:
                self._stall_t0 = self._clock()
            finished: List[int] = []
        else:
            if self._stall_t0 is not None:
                # Stall window just closed: one instant on the door lane
                # whose ``dur_s`` reaches back over the stalled interval
                # (the waterfall re-buckets overlapping decode time).
                dur_s = self._clock() - self._stall_t0
                self._stall_t0 = None
                if self.tracer.enabled and dur_s > 0:
                    self.tracer.instant(
                        "backpressure_stall", pid=_PID_DOOR, dur_s=dur_s
                    )
            finished = self._backend.step()
        self._observe()
        if self.slo is not None:
            self.slo.tick()
        return finished

    def drive(self, max_pumps: int = 100_000) -> None:
        """Pump until every watched stream is terminal (admitted work
        drained, queues empty). Consumers must still iterate their
        streams if buffers could fill — this is the poll-style helper
        for tests and the bench."""
        for _ in range(max_pumps):
            if not self._active and not any(
                self._queues[t] for t in self._queues
            ):
                return
            self.pump()
        raise RuntimeError(f"drive() did not quiesce in {max_pumps} pumps")

    def adopt_streams(self) -> Dict[int, TokenStream]:
        """Resume streaming after an elastic restore: build a stream for
        every live backend request, resuming delivery at each request's
        restored ``delivered`` high-water mark — the client sees one
        uninterrupted token sequence across the migration. Returns
        ``{req_id: stream}``."""
        adopted: Dict[int, TokenStream] = {}
        for req_id, tenant, delivered in self._backend.live_requests():
            if req_id in self._by_req:
                continue
            if tenant not in self.tenants:
                # Restored tenancy the door was not configured with:
                # deliver under the default class rather than dropping.
                tenant = self.default_tenant
            stream = TokenStream(self, tenant)
            stream.req_id = req_id
            stream.delivered = delivered
            stream.seen = delivered
            stream.submit_t = self._clock()
            self._active.append(stream)
            self._by_req[req_id] = stream
            adopted[req_id] = stream
        return adopted

    # ----------------------------------------------------------- internals

    def _bucket_level(self, tenant: str, now: float) -> Optional[float]:
        state = self._bucket.get(tenant)
        if state is None:
            return None
        cfg = self.tenants[tenant]
        level, last = state
        burst = (
            cfg.burst_tokens
            if cfg.burst_tokens is not None
            else cfg.rate_tokens_per_s
        )
        level = min(burst, level + cfg.rate_tokens_per_s * (now - last))
        self._bucket[tenant] = (level, now)
        return level

    def _admit(self) -> None:
        """Fair-share admission: repeatedly admit the backlogged,
        rate-eligible tenant with the lowest virtual time until the
        inflight cap, the engine queue, or every bucket says stop."""
        while True:
            inflight = sum(
                1
                for s in self._active
                if s.req_id is not None and not s.done
            )
            if inflight >= self.max_inflight:
                return
            now = self._clock()
            best: Optional[str] = None
            for tenant in sorted(self._queues):
                queue = self._queues[tenant]
                if not queue:
                    continue
                level = self._bucket_level(tenant, now)
                if level is not None and level < queue[0].cost:
                    # Head-of-line blocked on the token bucket: this is
                    # PACING, not generic queue wait — clock it.
                    if queue[0].pace_t0 is None:
                        queue[0].pace_t0 = now
                    continue
                if queue[0].pace_t0 is not None:
                    queue[0].paced_s += now - queue[0].pace_t0
                    queue[0].pace_t0 = None
                if best is None or self._vtime[tenant] < self._vtime[best]:
                    best = tenant
            if best is None:
                return
            queue = self._queues[best]
            pending = queue[0]
            try:
                req_id = self._backend.submit(
                    pending.prompt,
                    pending.params,
                    pending.metadata,
                    tenant_id=best,
                    mods=pending.mods,
                    trace_id=pending.stream.trace_id,
                )
            except (QueueFull, EngineDraining):
                return
            except AdmissionError as exc:
                # Structurally inadmissible (e.g. RequestTooLong): this
                # request can never run — reject its stream and move on.
                queue.popleft()
                pending.stream._override = "rejected"
                pending.stream._reject_reason = str(exc)
                self.rejected += 1
                self._close_trace(
                    pending.stream, "rejected", reason=str(exc)
                )
                continue
            queue.popleft()
            stream = pending.stream
            stream.req_id = req_id
            self._by_req[req_id] = stream
            self.admitted += 1
            queue_wait_s = max(
                0.0, now - stream.submit_t - pending.paced_s
            )
            self._wf_queue_wait.record(best, queue_wait_s)
            self._wf_pacing.record(best, pending.paced_s)
            if self.tracer.enabled and stream.trace_id is not None:
                self.tracer.span_event(
                    _PID_DOOR,
                    stream.sid,
                    "admitted",
                    trace_id=stream.trace_id,
                    req_id=req_id,
                    queue_wait_s=queue_wait_s,
                    pacing_s=pending.paced_s,
                )
                # The flow arrow's origin: "s" where the id was minted,
                # "t" when the caller brought its own trace context.
                self.tracer.flow(
                    "s" if stream._minted_trace else "t",
                    stream.trace_id,
                    _PID_DOOR,
                )
            if best in self._bucket:
                level, last = self._bucket[best]
                self._bucket[best] = (level - pending.cost, last)
            self._vtime[best] += pending.cost / self.tenants[best].weight
            self._global_v = max(self._global_v, self._vtime[best])

    def _observe(self) -> None:
        """Record client-visible latencies and retire terminal streams
        from the watch list (they remain drainable)."""
        now = self._clock()
        still: List[TokenStream] = []
        for stream in self._active:
            if stream._override is not None:
                continue
            if stream.req_id is None:
                still.append(stream)
                continue
            n = len(self._backend.generated(stream.req_id))
            if n > stream.seen:
                if stream.first_token_t is None:
                    stream.first_token_t = now
                    self._ttft.record(
                        stream.tenant, now - stream.submit_t
                    )
                stream.last_token_t = now
                stream.seen = n
            if stream.done:
                self._finalize(stream)
            else:
                still.append(stream)
        self._active = still

    def _finalize(self, stream: TokenStream) -> None:
        if stream._finalized:
            return
        stream._finalized = True
        self.finished += 1
        tpot: Optional[float] = None
        if (
            stream.first_token_t is not None
            and stream.last_token_t is not None
            and stream.seen > 1
        ):
            tpot = (
                stream.last_token_t - stream.first_token_t
            ) / (stream.seen - 1)
            self._tpot.record(stream.tenant, tpot)
            self._wf_decode.record(
                stream.tenant,
                stream.last_token_t - stream.first_token_t,
            )
        status = stream.status
        cfg = self.tenants[stream.tenant]
        slo_violated = False
        if (
            cfg.ttft_slo_s is not None
            and stream.first_token_t is not None
            and stream.first_token_t - stream.submit_t > cfg.ttft_slo_s
        ):
            slo_violated = True
        if cfg.tpot_slo_s is not None and tpot is not None:
            slo_violated = slo_violated or tpot > cfg.tpot_slo_s
        failed_over = (
            stream.req_id is not None
            and self._backend.failovers(stream.req_id) > 0
        )
        self._close_trace(
            stream,
            status,
            failed_over=failed_over,
            slo_violated=slo_violated,
        )

    def _close_trace(
        self,
        stream: TokenStream,
        status: str,
        *,
        failed_over: bool = False,
        slo_violated: bool = False,
        reason: Optional[str] = None,
    ) -> None:
        """Close the stream's door span and hand its trace_id to the
        sampler; apply any resulting drop decisions to every tracer in
        the stack (door + backend layers). Idempotent per stream."""
        if stream._trace_closed or stream.trace_id is None:
            return
        stream._trace_closed = True
        if self.tracer.enabled:
            attrs = {"trace_id": stream.trace_id, "status": status,
                     "tokens": stream.seen}
            if reason is not None:
                attrs["reason"] = reason
            self.tracer.span_end(_PID_DOOR, stream.sid, "stream", **attrs)
        if self.sampler is None:
            return
        self.sampler.note_end(
            stream.trace_id,
            failed=status in ("cancelled", "rejected", "expired"),
            failed_over=failed_over,
            slo_violated=slo_violated,
        )
        drops = self.sampler.drain_drops()
        if drops:
            self._prune(drops)

    def _prune(self, drops) -> None:
        if self.tracer.enabled:
            prune_trace(self.tracer, drops)
        for tracer, lock in self._backend.tracers():
            if lock is not None:
                with lock:
                    prune_trace(tracer, drops)
            else:
                prune_trace(tracer, drops)

    # ------------------------------------------------------- introspection

    def trace_documents(self) -> List[dict]:
        """Every layer's Perfetto document, door first — feed straight to
        :func:`~distributed_pytorch_tpu.obs.disttrace.merge_traces` (the
        ``/requestz`` endpoint does exactly that)."""
        docs: List[dict] = []
        if self.tracer.enabled:
            docs.append(self.tracer.to_perfetto())
        docs.extend(self._backend.trace_documents())
        return docs

    def health(self) -> str:
        return "live"

    def status(self) -> dict:
        """Door live-state for ``/statusz`` — headline counters plus a
        per-tenant block (queue depth, SLO latencies, and the waterfall
        component quantiles ``tools/obs_top.py --tenant`` renders)."""
        with self.registry.lock:
            doc: Dict[str, object] = {
                "streams_opened": self.streams_opened,
                "admitted": self.admitted,
                "finished": self.finished,
                "rejected": self.rejected + self.rejected_quota,
                "backpressure_stalls": self.backpressure_stalls,
                "queued_streams": sum(
                    len(q) for q in self._queues.values()
                ),
                "active_streams": len(self._active),
            }
            tenants: Dict[str, dict] = {}
            for tenant in sorted(self.tenants):
                tenants[tenant] = {
                    "queued": len(self._queues[tenant]),
                    "weight": self.tenants[tenant].weight,
                    "ttft_p95_s": self.registry.read_quantile(
                        "ttft_by_tenant", 0.95, tenant
                    ),
                    "tpot_p95_s": self.registry.read_quantile(
                        "tpot_by_tenant", 0.95, tenant
                    ),
                    "queue_wait_p95_s": self.registry.read_quantile(
                        "waterfall_queue_wait_by_tenant", 0.95, tenant
                    ),
                    "pacing_p95_s": self.registry.read_quantile(
                        "waterfall_pacing_by_tenant", 0.95, tenant
                    ),
                    "decode_p95_s": self.registry.read_quantile(
                        "waterfall_decode_by_tenant", 0.95, tenant
                    ),
                }
            doc["tenants"] = tenants
            router = getattr(self._backend, "router", None)
            if router is not None:
                # Fleet block: route table, shadow census, and (after a
                # crash restart) the recovery reconciliation summary.
                doc["fleet"] = router.describe()
            if self.sampler is not None:
                sampler_doc = dict(self.sampler.counters())
                sampler_doc["kept"] = len(self.sampler.kept_ids())
                doc["trace_sampler"] = sampler_doc
            return doc

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Attach an :class:`~distributed_pytorch_tpu.obs.server.
        IntrospectionServer` to the door itself: ``/metrics`` and
        ``/statusz`` read the door registry, ``/requestz`` merges door +
        backend traces into per-request waterfalls."""
        from distributed_pytorch_tpu.obs.server import IntrospectionServer

        return IntrospectionServer(self, host=host, port=port).start()


# ------------------------------------------------------------- backends


class _EngineBackend:
    """Duck-type adapter over a single :class:`~.engine.InferenceEngine`.
    ``generated`` returns the live committed-token list (PENDING never
    appears there), so streams read engine truth with no copies."""

    def __init__(self, engine):
        self.engine = engine

    def slots_hint(self) -> int:
        return self.engine.max_slots

    def submit(
        self, prompt, params, metadata, *, tenant_id, mods, trace_id=None
    ) -> int:
        return self.engine.submit(
            prompt, params, metadata, tenant_id=tenant_id, mods=mods,
            trace_id=trace_id,
        )

    def step(self) -> List[int]:
        return self.engine.step()

    def generated(self, req_id: int) -> List[int]:
        return self.engine.requests[req_id].generated

    def state(self, req_id: int) -> str:
        return self.engine.requests[req_id].state.value

    def done(self, req_id: int) -> bool:
        return self.engine.requests[req_id].done

    def cancel(self, req_id: int) -> None:
        self.engine.cancel(req_id)

    def note_delivered(self, req_id: int, n: int) -> None:
        req = self.engine.requests.get(req_id)
        if req is not None:
            req.delivered = n

    def live_requests(self):
        for req_id, req in sorted(self.engine.requests.items()):
            if not req.done:
                yield req_id, req.tenant_id, req.delivered

    def failovers(self, req_id: int) -> int:
        return 0  # a single engine has nowhere to fail over to

    def tracers(self):
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None and getattr(tracer, "enabled", False):
            yield tracer, self.engine.registry.lock

    def trace_documents(self) -> List[dict]:
        return self.engine.trace_documents()


class _RouterBackend:
    """Adapter over a :class:`~.fleet.FleetRouter`: streams ride FLEET
    ids, so they survive failover and hedging untouched. ``generated``
    is the router's committed shadow view — exactly what failover would
    preserve, so a stream can never deliver a token a recovery would
    later contradict."""

    def __init__(self, router):
        self.router = router

    def slots_hint(self) -> int:
        total = 0
        for r in self.router.replicas():
            if r.state != "live":
                continue
            if r.engine is not None:
                total += r.engine.max_slots
            else:
                # Process replica: no in-process engine, read the spec.
                spec = getattr(r.client, "spec", None) or {}
                total += int(
                    (spec.get("engine") or {}).get("max_slots", 1) or 1
                )
        return max(1, total)

    def submit(
        self, prompt, params, metadata, *, tenant_id, mods, trace_id=None
    ) -> int:
        return self.router.submit(
            prompt, params, metadata, tenant_id=tenant_id, mods=mods,
            trace_id=trace_id,
        )

    def step(self) -> List[int]:
        return self.router.step()

    def generated(self, fid: int) -> List[int]:
        return self.router.poll(fid).generated

    def state(self, fid: int) -> str:
        return self.router.poll(fid).state

    def done(self, fid: int) -> bool:
        return self.router.poll(fid).state in (
            "finished", "cancelled", "expired",
        )

    def cancel(self, fid: int) -> None:
        self.router.cancel(fid)

    def note_delivered(self, fid: int, n: int) -> None:
        # The router records the mark on the shadow (journaled when a
        # journal is attached — the recovery resume point) and
        # propagates it to the owning in-process engine for drain
        # snapshots.
        self.router.note_delivered(fid, n)

    def live_requests(self):
        # Finished-but-undelivered shadows are included: after a router
        # recovery their tails drain from the journaled finish record,
        # and the stream must resume at the journaled high-water mark.
        for fid, shadow in sorted(self.router._shadows.items()):
            if shadow.cancelled:
                continue
            if shadow.finished and shadow.delivered >= len(
                shadow.generated
            ):
                continue
            yield fid, shadow.tenant_id, shadow.delivered

    def failovers(self, fid: int) -> int:
        shadow = self.router._shadows.get(fid)
        return shadow.failovers if shadow is not None else 0

    def tracers(self):
        if getattr(self.router.tracer, "enabled", False):
            # The router tracer shares the door's single-threaded pump —
            # no lock to take.
            yield self.router.tracer, None
        for replica in self.router.replicas():
            if replica.state == "removed":
                continue
            tracer = getattr(replica.engine, "tracer", None)
            if tracer is not None and getattr(tracer, "enabled", False):
                yield tracer, replica.engine.registry.lock

    def trace_documents(self) -> List[dict]:
        return self.router.trace_documents()


def _make_backend(obj):
    if isinstance(obj, (_EngineBackend, _RouterBackend)):
        return obj
    if hasattr(obj, "fleet_snapshot"):
        return _RouterBackend(obj)
    if hasattr(obj, "requests") and hasattr(obj, "step"):
        return _EngineBackend(obj)
    raise TypeError(
        f"FrontDoor needs an InferenceEngine or FleetRouter, got "
        f"{type(obj).__name__}"
    )
