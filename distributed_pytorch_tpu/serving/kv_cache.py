"""Paged KV-cache management: host-side page accounting for the device pool.

The device holds ONE global cache per attention layer, laid out
``[num_pages, page_size, Hkv, D]`` (see ``models/transformer.py``'s paged
decode mode). This module owns the host half: a free-list allocator over
physical page ids and a per-sequence :class:`BlockTable` mapping logical
pages to physical ones. Two invariants make slot reuse copy-free:

* **Page 0 is the NULL page** — never allocated. Inactive decode slots and
  padded block-table entries all point at it; the attention visibility mask
  guarantees nothing read from it survives the softmax, so retired pages
  need no zeroing before reuse (stale K/V beyond a row's ``seq_len`` is
  masked exactly like stale cache beyond ``cache_index`` in offline decode).
* **Every allocated page is owned by exactly one table** — the allocator
  tracks the owning set, so a double-free or a leak is an immediate
  ``AssertionError`` in :meth:`PagedBlockAllocator.check_invariants`, not a
  silent cross-request cache corruption. The scheduler property test drives
  1k randomized submit/finish/preempt cycles against this.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

NULL_PAGE = 0


class OutOfPages(RuntimeError):
    """Raised when an allocation cannot be satisfied — the scheduler's cue
    to preempt the lowest-priority running sequence."""


class PagedBlockAllocator:
    """LIFO free-list over physical page ids ``1..num_pages-1``.

    LIFO keeps reuse hot (the page most recently retired is reassigned
    first) and, with the deterministic initial ordering, makes the whole
    engine reproducible on CPU: identical submit/finish order yields
    identical physical page assignments."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (page {NULL_PAGE} is reserved), got {num_pages}"
            )
        self.num_pages = num_pages
        # pop() takes from the end: seed the stack so pages come out
        # 1, 2, 3, ... on a fresh allocator.
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._owned: set = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._owned)

    @staticmethod
    def pages_needed(n_tokens: int, page_size: int) -> int:
        return -(-n_tokens // page_size) if n_tokens > 0 else 0

    def allocate(self, n: int = 1) -> List[int]:
        """Take ``n`` pages or raise :class:`OutOfPages` taking NONE —
        partial grabs would leak on the error path."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages, {len(self._free)} free "
                f"of {self.num_pages - 1} allocatable"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._owned.update(pages)
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for page in pages:
            if page not in self._owned:
                raise AssertionError(
                    f"freeing page {page} that is not allocated "
                    "(double free or foreign page)"
                )
            self._owned.discard(page)
            self._free.append(page)

    def check_invariants(self) -> None:
        """Free + owned partition the allocatable pages exactly."""
        free_set = set(self._free)
        assert len(free_set) == len(self._free), "duplicate page in free list"
        assert NULL_PAGE not in free_set, "null page leaked into free list"
        assert NULL_PAGE not in self._owned, "null page was allocated"
        assert not (free_set & self._owned), (
            f"pages both free and owned: {free_set & self._owned}"
        )
        assert len(free_set) + len(self._owned) == self.num_pages - 1, (
            f"page leak: {len(free_set)} free + {len(self._owned)} owned "
            f"!= {self.num_pages - 1} allocatable"
        )


class BlockTable:
    """One sequence's logical-page -> physical-page map."""

    def __init__(self):
        self.pages: List[int] = []

    def __len__(self) -> int:
        return len(self.pages)

    def ensure(
        self, n_tokens: int, page_size: int, allocator: PagedBlockAllocator
    ) -> int:
        """Grow the table to cover ``n_tokens`` positions; returns how many
        pages were newly allocated. All-or-nothing per call: a failed grow
        raises :class:`OutOfPages` without taking any pages."""
        need = PagedBlockAllocator.pages_needed(n_tokens, page_size)
        grow = need - len(self.pages)
        if grow <= 0:
            return 0
        self.pages.extend(allocator.allocate(grow))
        return grow

    def release(self, allocator: PagedBlockAllocator) -> int:
        """Return every page to the allocator (retire/preempt); returns the
        count released. No device-side work: stale contents are masked."""
        n = len(self.pages)
        if n:
            allocator.free(self.pages)
            self.pages = []
        return n

    def as_row(self, width: int) -> np.ndarray:
        """``[width]`` int32 row for the device block-table batch, padded
        with the null page."""
        if len(self.pages) > width:
            raise ValueError(
                f"table holds {len(self.pages)} pages, row width is {width}"
            )
        row = np.full((width,), NULL_PAGE, np.int32)
        row[: len(self.pages)] = self.pages
        return row
