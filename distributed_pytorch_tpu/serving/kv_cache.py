"""Paged KV-cache management: host-side page accounting for the device pool.

The device holds ONE global cache per attention layer, laid out
``[num_pages, page_size, Hkv, D]`` (see ``models/transformer.py``'s paged
decode mode). This module owns the host half: a refcounted allocator over
physical page ids, a per-sequence :class:`BlockTable` mapping logical pages
to physical ones, and a :class:`PrefixCache` hash-trie that maps
page-aligned token prefixes to already-computed pages so shared prompts are
prefilled once. Invariants that keep sharing copy-free and leak-proof:

* **Page 0 is the NULL page** — never allocated. Inactive decode slots and
  padded block-table entries all point at it; the attention visibility mask
  guarantees nothing read from it survives the softmax, so retired pages
  need no zeroing before reuse (stale K/V beyond a row's ``seq_len`` is
  masked exactly like stale cache beyond ``cache_index`` in offline decode).
* **Every page is in exactly one of three states**: *free* (content
  meaningless), *referenced* (refcount >= 1 readers hold it in a block
  table), or *cached-idle* (refcount 0 but registered in the prefix trie;
  content is valid K/V, kept on an LRU and evicted only under allocation
  pressure). A double-unref or a leak is an immediate ``AssertionError`` in
  :meth:`PagedBlockAllocator.check_invariants`, not a silent cross-request
  cache corruption. The scheduler property tests drive randomized
  submit/finish/preempt/evict cycles against this.
* **Writers own their write page exclusively.** A shared page (refcount
  > 1) is never written in place: the scheduler copies it first
  (copy-on-write) so concurrent extenders of a cached partial page cannot
  clobber each other's tokens. Pages with refcount 1 may be extended in
  place even while registered — appending beyond a registered prefix never
  changes the prefix content a future matcher reads.
* **Draft pages move in lockstep with target pages** (speculative
  decoding): the draft model's pool is built with the SAME
  ``(num_pages, page_size)`` geometry, so one physical page id names the
  same logical token span in BOTH pools (:class:`PagePoolGroup`). One
  allocator and one block table per sequence then govern both pools at
  once — allocate/ref/unref/retire/evict are decided once on the shared
  id — and rejected-token rollback is O(1) in both pools for the same
  reason retire is copy-free: reads past ``seq_len`` are masked, so stale
  speculative K/V is dead by construction.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

NULL_PAGE = 0


def chain_next(prev: str, chunk: Sequence[int]) -> str:
    """One link of the content-addressed page chain: the key of a full
    page holding ``chunk`` whose predecessor page hashed to ``prev``
    (``"root"`` for the first page). Hash-chained, so each key commits to
    the ENTIRE page-aligned prefix — the identity shared by
    :meth:`PrefixCache.key_chain`, the elastic snapshot's ``trie_keys``,
    and the host page tier (``serving/hostkv.py``), which is what makes a
    page nameable across tiers and across processes."""
    return hashlib.sha256(
        (prev + "|" + ",".join(map(str, chunk))).encode()
    ).hexdigest()[:16]


class OutOfPages(RuntimeError):
    """Raised when an allocation cannot be satisfied even after evicting
    every cached-idle page — the scheduler's cue to preempt the
    lowest-priority running sequence."""


class PagedBlockAllocator:
    """Refcounted allocator over physical page ids ``1..num_pages-1``.

    The free list is LIFO: reuse stays hot (the page most recently retired
    is reassigned first) and, with the deterministic initial ordering, the
    whole engine is reproducible on CPU: identical submit/finish order
    yields identical physical page assignments.

    Refcounts support prefix sharing: :meth:`ref` adds a reader to a page
    another sequence already holds, :meth:`unref` drops one. When the count
    reaches zero the page either returns to the free list or — if the
    prefix cache registered it via :meth:`mark_cached` — parks on the
    cached-idle LRU, where its contents stay valid until allocation
    pressure evicts it (``evict_hook`` tells the trie to forget it first).
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (page {NULL_PAGE} is reserved), got {num_pages}"
            )
        self.num_pages = num_pages
        # pop() takes from the end: seed the stack so pages come out
        # 1, 2, 3, ... on a fresh allocator.
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        # Cached-but-unreferenced pages, oldest first (LRU eviction order).
        self._idle: "OrderedDict[int, None]" = OrderedDict()
        # Pages registered in the prefix trie (referenced or idle).
        self._cached: set = set()
        # Names of the device pools this id space governs — one pool for a
        # plain engine, ("target", "draft") under speculative decoding (the
        # engine overwrites this from its PagePoolGroup). Page-leak
        # diagnostics name them: one leaked id pins K/V in EVERY pool.
        self.pool_names: Tuple[str, ...] = ("target",)
        # Called with the page id just before an idle page is recycled, so
        # the prefix trie can drop the nodes that point at it.
        self.evict_hook: Optional[Callable[[int], None]] = None
        self.evictions = 0
        # Copy-on-write page splits, counted here (the scheduler decides
        # them, but the allocator is the page ledger of record) so the
        # metrics registry reads every page-lifecycle counter off one
        # object. note_cow() increments it.
        self.cow_copies = 0
        # O(1) running state counts, maintained at every page transition
        # and cross-checked against the full sweep in check_invariants() —
        # the gauges the engine exports every step without debug=True.
        self._n_free = num_pages - 1
        self._n_referenced = 0
        self._n_idle = 0
        # Optional tracer / flight recorder (duck-typed; NULL by default)
        # so page evictions surface on the engine timeline and in
        # postmortem dumps.
        from distributed_pytorch_tpu.obs.flight import NULL_FLIGHT_RECORDER
        from distributed_pytorch_tpu.obs.tracer import NULL_TRACER

        self.tracer = NULL_TRACER
        self.flight = NULL_FLIGHT_RECORDER

    @property
    def num_free(self) -> int:
        """Pages allocatable right now (free list + evictable idle)."""
        return self._n_free + self._n_idle

    @property
    def num_allocated(self) -> int:
        """Pages with at least one reader."""
        return self._n_referenced

    @property
    def num_idle(self) -> int:
        """Cached pages with no readers (evictable under pressure)."""
        return self._n_idle

    def counters(self) -> Dict[str, int]:
        """O(1) gauge/counter snapshot — page-state populations (strict
        free list vs cached-idle, unlike :attr:`num_free` which pools
        them), plus the lifetime CoW-split and eviction counters."""
        return {
            "pages_free": self._n_free,
            "pages_referenced": self._n_referenced,
            "pages_cached_idle": self._n_idle,
            "cow_copies": self.cow_copies,
            "page_evictions": self.evictions,
        }

    def note_cow(self) -> None:
        """The scheduler split a shared page copy-on-write."""
        self.cow_copies += 1

    @staticmethod
    def pages_needed(n_tokens: int, page_size: int) -> int:
        return -(-n_tokens // page_size) if n_tokens > 0 else 0

    def _evict_one(self) -> None:
        page, _ = self._idle.popitem(last=False)  # oldest first
        self._cached.discard(page)
        self.evictions += 1
        self._n_idle -= 1
        if self.evict_hook is not None:
            self.evict_hook(page)
        self.tracer.instant("page_evict", page=page)
        self.flight.record("page_evict", page=page)
        self._free.append(page)
        self._n_free += 1

    def allocate(self, n: int = 1) -> List[int]:
        """Take ``n`` fresh pages (refcount 1 each) or raise
        :class:`OutOfPages` taking NONE — partial grabs would leak on the
        error path. Cached-idle pages are evicted LRU-first to satisfy the
        request when the free list runs dry."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > self.num_free:
            raise OutOfPages(
                f"need {n} pages, {len(self._free)} free + "
                f"{len(self._idle)} cached-idle "
                f"of {self.num_pages - 1} allocatable"
            )
        pages = []
        for _ in range(n):
            if not self._free:
                self._evict_one()
            page = self._free.pop()
            self._ref[page] = 1
            self._n_free -= 1
            self._n_referenced += 1
            pages.append(page)
        return pages

    def ref(self, page: int) -> None:
        """Add a reader to ``page`` — either sharing a live page or
        reactivating a cached-idle one (a prefix-cache hit)."""
        if page in self._ref:
            self._ref[page] += 1
        elif page in self._idle:
            del self._idle[page]
            self._ref[page] = 1
            self._n_idle -= 1
            self._n_referenced += 1
        else:
            raise AssertionError(
                f"ref of page {page} that is neither live nor cached-idle"
            )

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def unref(self, page: int) -> None:
        """Drop one reader. At zero readers the page parks on the
        cached-idle LRU when the trie registered it, else frees."""
        count = self._ref.get(page)
        if count is None:
            raise AssertionError(
                f"unref of page {page} that has no readers "
                "(double free or foreign page)"
            )
        if count > 1:
            self._ref[page] = count - 1
            return
        del self._ref[page]
        self._n_referenced -= 1
        if page in self._cached:
            self._idle[page] = None  # most-recently-used end
            self._n_idle += 1
        else:
            self._free.append(page)
            self._n_free += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reader from each page (block-table release)."""
        for page in pages:
            self.unref(page)

    def mark_cached(self, page: int) -> None:
        """The prefix trie registered ``page``: at refcount 0 it will idle
        (content retained) instead of freeing."""
        assert page in self._ref or page in self._idle, (
            f"mark_cached on page {page} that is not live"
        )
        self._cached.add(page)

    def touch(self, page: int) -> None:
        """LRU-touch a cached-idle page (trie hit on an existing node)."""
        if page in self._idle:
            self._idle.move_to_end(page)

    def check_invariants(self) -> None:
        """Free + referenced + cached-idle partition the allocatable pages
        exactly; every cached page is live or idle; refcounts positive."""
        free_set = set(self._free)
        idle_set = set(self._idle)
        ref_set = set(self._ref)
        assert len(free_set) == len(self._free), "duplicate page in free list"
        assert NULL_PAGE not in free_set, "null page leaked into free list"
        assert NULL_PAGE not in ref_set, "null page was allocated"
        assert NULL_PAGE not in idle_set, "null page in the idle pool"
        assert not (free_set & ref_set), (
            f"pages both free and referenced: {free_set & ref_set}"
        )
        assert not (free_set & idle_set), (
            f"pages both free and cached-idle: {free_set & idle_set}"
        )
        assert not (idle_set & ref_set), (
            f"pages both cached-idle and referenced: {idle_set & ref_set}"
        )
        assert all(c >= 1 for c in self._ref.values()), (
            "non-positive refcount"
        )
        assert self._cached <= (ref_set | idle_set), (
            f"trie-registered pages neither live nor idle: "
            f"{self._cached - ref_set - idle_set}"
        )
        assert idle_set <= self._cached, (
            f"idle pages not registered in the trie: {idle_set - self._cached}"
        )
        total = len(free_set) + len(ref_set) + len(idle_set)
        assert total == self.num_pages - 1, (
            f"page leak in pool(s) {'/'.join(self.pool_names)}: "
            f"{len(free_set)} free + {len(ref_set)} referenced "
            f"+ {len(idle_set)} idle != {self.num_pages - 1} allocatable"
        )
        # The O(1) running gauges must agree with the sweep-derived truth —
        # a drifted counter is as much a bug as a leaked page.
        assert self._n_free == len(free_set), (
            f"pages_free gauge drifted: {self._n_free} != {len(free_set)}"
        )
        assert self._n_referenced == len(ref_set), (
            f"pages_referenced gauge drifted: "
            f"{self._n_referenced} != {len(ref_set)}"
        )
        assert self._n_idle == len(idle_set), (
            f"pages_cached_idle gauge drifted: "
            f"{self._n_idle} != {len(idle_set)}"
        )

    def assert_quiescent(self) -> None:
        """Teardown gate (engine close / post-drain): no page may still be
        referenced. Cached-idle pages are fine — they are reclaimable and
        die with the device arrays — but a nonzero referenced gauge here is
        a leaked block table, the exact silent loss close() exists to
        catch. One page id pins K/V in every governed pool, so the message
        names them all (target vs target/draft)."""
        assert self._n_referenced == 0, (
            f"teardown leaked {self._n_referenced} referenced page(s) in "
            f"pool(s) {'/'.join(self.pool_names)}: {sorted(self._ref)}"
        )
        self.check_invariants()


class BlockTable:
    """One sequence's logical-page -> physical-page map."""

    def __init__(self):
        self.pages: List[int] = []

    def __len__(self) -> int:
        return len(self.pages)

    def ensure(
        self, n_tokens: int, page_size: int, allocator: PagedBlockAllocator
    ) -> int:
        """Grow the table to cover ``n_tokens`` positions; returns how many
        pages were newly allocated. All-or-nothing per call: a failed grow
        raises :class:`OutOfPages` without taking any pages."""
        need = PagedBlockAllocator.pages_needed(n_tokens, page_size)
        grow = need - len(self.pages)
        if grow <= 0:
            return 0
        self.pages.extend(allocator.allocate(grow))
        return grow

    def release(self, allocator: PagedBlockAllocator) -> int:
        """Drop this table's reader from every page (retire/preempt);
        returns the count released. No device-side work: a page with other
        readers lives on, a trie-registered page idles with its contents
        intact, anything else frees (stale contents are masked)."""
        n = len(self.pages)
        if n:
            allocator.free(self.pages)
            self.pages = []
        return n

    def as_row(self, width: int) -> np.ndarray:
        """``[width]`` int32 row for the device block-table batch, padded
        with the null page."""
        if len(self.pages) > width:
            raise ValueError(
                f"table holds {len(self.pages)} pages, row width is {width}"
            )
        row = np.full((width,), NULL_PAGE, np.int32)
        row[: len(self.pages)] = self.pages
        return row


class PagePoolGroup:
    """Named device page pools sharing ONE physical page-id space — the
    ``"target"`` model's pool always, plus a ``"draft"`` pool when the
    engine runs speculative decoding.

    Every pool is built with the SAME ``(num_pages, page_size)`` geometry
    (per-layer shapes ``[num_pages, page_size, Hkv, D]`` differ freely — a
    draft model is narrower), so a physical page id names the same logical
    token span in every pool. That is the whole lockstep mechanism: ONE
    :class:`PagedBlockAllocator` and ONE :class:`BlockTable` per sequence
    govern all pools at once — allocation, refcounting, prefix-cache
    adoption, copy-on-write, and release are decided once on the shared id
    and apply to target and draft K/V alike. The engine prefills and
    decode-writes both pools for every position, so a page's draft K/V is
    always exactly as valid as its target K/V, including pages resurrected
    from the prefix trie by a later request.

    Rejected-token rollback needs NO device work in any pool: the attention
    visibility mask hides everything past a row's ``seq_len``, so lowering
    the host-side ``len_cached`` IS the rollback — stale speculative K/V
    (target's verify writes and the draft's proposal writes alike) is dead
    by construction and simply overwritten when the real continuation is
    fed (write-then-attend)."""

    def __init__(self, **pools):
        if "target" not in pools:
            raise ValueError("PagePoolGroup needs at least a 'target' pool")
        self.pools = dict(pools)

    def __getitem__(self, name: str):
        return self.pools[name]

    def __setitem__(self, name: str, value) -> None:
        if name not in self.pools:
            raise KeyError(
                f"unknown pool {name!r}; declared: {tuple(self.pools)}"
            )
        self.pools[name] = value

    def __contains__(self, name: str) -> bool:
        return name in self.pools

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(self.pools)

    def copy_page(self, copy_fn, src, dst) -> None:
        """Fan the engine's compiled page-copy out over EVERY pool — the
        device half of copy-on-write must clone a shared page's draft K/V
        in the same step as its target K/V, or a later speculative write
        through the fresh id would diverge the two pools. ``copy_fn`` is
        one program shared by every pool, or a mapping pool-name ->
        program when pools carry their own shardings (the mesh-sharded
        engine compiles one per pool so in/out shardings stay explicit)."""
        per_pool = isinstance(copy_fn, dict)
        for name in self.pools:
            fn = copy_fn[name] if per_pool else copy_fn
            self.pools[name] = fn(self.pools[name], src, dst)


class PrefixCache:
    """Hash-trie over page-aligned token prefixes -> physical pages.

    Nodes live at full-page granularity: the child key is
    ``(parent_node_id, tuple(page_size tokens))``, so two prompts share a
    node exactly when they share that page-aligned prefix — token content is
    compared exactly (no hash-collision corruption). Each node pins one
    physical page of already-computed K/V. A retired request additionally
    registers its final *partial* page under the last full node, keyed by
    its (< page_size) token tuple; a later request may extend it, with the
    scheduler copy-on-writing when more than one extender holds it.

    Lookup walks full-page children greedily, then tries the longest
    matching partial child, never consuming a request's last token (the
    decode step must be fed at least one). Every page returned is ref'd on
    behalf of the caller. Registration dedupes: if a node already exists
    for the same (parent, tokens), the existing page wins and the caller's
    page stays private (freed normally at release).

    Eviction is driven by the allocator: when allocation pressure recycles
    a cached-idle page, ``_on_evict`` removes every trie entry pointing at
    it. Descendants of an evicted node become unreachable and drain off the
    LRU naturally — readers are unaffected either way because block tables
    hold refs independently of the trie.
    """

    ROOT = 0

    def __init__(self, allocator: PagedBlockAllocator, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.allocator = allocator
        self.page_size = page_size
        self._next_id = 1
        # (parent_id, full-page token tuple) -> (node_id, page)
        self._full: Dict[Tuple[int, Tuple[int, ...]], Tuple[int, int]] = {}
        # parent_id -> {partial token tuple -> page}
        self._partial: Dict[int, Dict[Tuple[int, ...], int]] = {}
        # page -> list of trie entries pointing at it (a page can carry a
        # partial node and later the full node that extends it in place).
        self._by_page: Dict[int, List[tuple]] = {}
        allocator.evict_hook = self._on_evict
        # Second trie level: a HostPageTier (serving/hostkv.py) catches
        # full-page evictions d2h and serves them back on later prefix
        # hits. None keeps the classic single-tier behavior bit-for-bit.
        self.host = None
        # Device pages whose h2d fetch is planned but not yet executed —
        # their device content is garbage until the engine's fetch
        # program lands, so an eviction racing the plan must NOT spill
        # them (the host tier already holds the key).
        self.fetch_pending: set = set()
        # node id -> its content-addressed chain key (ROOT = "root").
        # Maintained incrementally at registration so the scheduler can
        # extend a device match into the host tier in O(pages), not by
        # re-hashing the whole prefix.
        self._node_key: Dict[int, str] = {self.ROOT: "root"}
        self.lookups = 0
        self.hits = 0  # lookups that matched at least one token
        self.tokens_hit = 0
        self.tokens_hit_host = 0
        self.tokens_missed = 0

    # ------------------------------------------------------------- queries

    @property
    def num_nodes(self) -> int:
        return len(self._full) + sum(len(d) for d in self._partial.values())

    def _walk(self, tokens: Sequence[int], limit: int):
        """Longest cached match of ``tokens[:limit]``: yields the full-page
        chain then at most one partial page. Returns
        ``(pages, matched, node)`` WITHOUT taking refs."""
        pages: List[int] = []
        node = self.ROOT
        matched = 0
        page_size = self.page_size
        while matched + page_size <= limit:
            entry = self._full.get(
                (node, tuple(tokens[matched : matched + page_size]))
            )
            if entry is None:
                break
            node, page = entry
            pages.append(page)
            matched += page_size
        best_len = 0
        best_page = None
        for ptoks, page in self._partial.get(node, {}).items():
            m = len(ptoks)
            if (
                m > best_len
                and matched + m <= limit
                and tuple(tokens[matched : matched + m]) == ptoks
            ):
                best_len, best_page = m, page
        if best_page is not None:
            pages.append(best_page)
            matched += best_len
        return pages, matched, node

    def key_chain(self, tokens: Sequence[int]) -> List[str]:
        """Content-addressed keys for the page-aligned trie chain covering
        ``tokens``' currently cached prefix — the KV metadata the elastic
        snapshot records per request. Key ``i`` digests the first
        ``(i+1) * page_size`` tokens (hash-chained, so each key commits to
        the whole prefix, not just its own page): identical token prefixes
        produce identical chains on ANY engine, letting a restore target
        predict which pages its own trie will re-serve without shipping
        device K/V. Takes no refs and does not touch the LRU."""
        keys: List[str] = []
        node = self.ROOT
        matched = 0
        prev = "root"
        page_size = self.page_size
        while matched + page_size <= len(tokens):
            chunk = tuple(tokens[matched : matched + page_size])
            entry = self._full.get((node, chunk))
            if entry is None:
                break
            prev = chain_next(prev, chunk)
            keys.append(prev)
            node = entry[0]
            matched += page_size
        return keys

    def key_chain_tiered(
        self, tokens: Sequence[int]
    ) -> Tuple[List[str], List[str]]:
        """:meth:`key_chain` split by tier: the device chain, then the
        host-resident continuation beyond it — the residency record the
        elastic snapshot persists so a restore target knows which pages
        the adopter can re-serve by h2d fetch instead of re-prefill."""
        keys = self.key_chain(tokens)
        host_keys: List[str] = []
        if self.host is not None:
            matched = len(keys) * self.page_size
            prev = keys[-1] if keys else "root"
            while matched + self.page_size <= len(tokens):
                chunk = tuple(tokens[matched : matched + self.page_size])
                key = chain_next(prev, chunk)
                if not self.host.match(key, chunk):
                    break
                host_keys.append(key)
                prev = key
                matched += self.page_size
        return keys, host_keys

    def node_key(self, node: int) -> Optional[str]:
        """The content-addressed chain key of ``node`` (``"root"`` for
        ROOT); None for a node that was evicted out from under its id."""
        return self._node_key.get(node)

    def host_continuation(
        self, tokens: Sequence[int], matched: int, node: int, limit: int
    ):
        """Full-page windows of ``tokens[matched:limit]`` the HOST tier
        can serve, continuing the chain from device node ``node`` —
        ``[(key, chunk), ...]`` in order. Empty when no host tier is
        attached, when the device match ended mid-page (a partial page
        breaks the full-page chain), or at the first window the host
        cannot serve. Pure query: no refs, pins, or LRU motion."""
        out: List[Tuple[str, Tuple[int, ...]]] = []
        if self.host is None or matched % self.page_size:
            return out
        prev = self._node_key.get(node)
        if prev is None:
            return out
        while matched + self.page_size <= limit:
            chunk = tuple(tokens[matched : matched + self.page_size])
            key = chain_next(prev, chunk)
            if not self.host.match(key, chunk):
                break
            out.append((key, chunk))
            prev = key
            matched += self.page_size
        return out

    def peek(self, tokens: Sequence[int]) -> int:
        """How many leading tokens of ``tokens`` (capped at ``len - 1``)
        are cached right now in EITHER tier — admission's feasibility
        estimate. Takes no refs and does not touch the LRU."""
        limit = max(0, len(tokens) - 1)
        _, matched, node = self._walk(tokens, limit)
        if self.host is not None:
            matched += self.page_size * len(
                self.host_continuation(tokens, matched, node, limit)
            )
        return matched

    def lookup(self, tokens: Sequence[int]):
        """Match the longest cached prefix of ``tokens`` (never the last
        token), ref every matched page for the caller, and return
        ``(pages, n_cached_tokens, last_full_node_id)``."""
        limit = max(0, len(tokens) - 1)
        pages, matched, node = self._walk(tokens, limit)
        for page in pages:
            self.allocator.ref(page)
        self.lookups += 1
        if matched:
            self.hits += 1
        self.tokens_hit += matched
        self.tokens_missed += limit - matched
        return pages, matched, node

    def note_host_hit(self, n_tokens: int) -> None:
        """The scheduler extended the last :meth:`lookup` by ``n_tokens``
        served from the host tier: reclassify them from missed (where
        lookup counted them) to host-hit, keeping the totals exact."""
        self.tokens_hit_host += n_tokens
        self.tokens_missed -= n_tokens

    # ---------------------------------------------------------- mutation

    def register_full(
        self, parent: int, tokens: Tuple[int, ...], page: int
    ) -> Tuple[int, bool]:
        """Register a freshly filled full page under ``parent``. If the
        node already exists the existing page wins (the caller's page stays
        private); returns ``(node_id, registered)``."""
        assert len(tokens) == self.page_size, (
            f"full node needs {self.page_size} tokens, got {len(tokens)}"
        )
        key = (parent, tokens)
        entry = self._full.get(key)
        if entry is not None:
            self.allocator.touch(entry[1])
            return entry[0], False
        node_id = self._next_id
        self._next_id += 1
        self._full[key] = (node_id, page)
        self._by_page.setdefault(page, []).append(("full", key))
        parent_key = self._node_key.get(parent)
        if parent_key is not None:
            self._node_key[node_id] = chain_next(parent_key, tokens)
        self.allocator.mark_cached(page)
        return node_id, True

    def register_partial(
        self, parent: int, tokens: Tuple[int, ...], page: int
    ) -> bool:
        """Register a retiring request's final partial page (``< page_size``
        tokens) under ``parent``. First writer wins on identical content."""
        if not tokens:
            return False
        assert len(tokens) < self.page_size, (
            f"partial node must hold < {self.page_size} tokens"
        )
        children = self._partial.setdefault(parent, {})
        if tokens in children:
            self.allocator.touch(children[tokens])
            return False
        children[tokens] = page
        self._by_page.setdefault(page, []).append(("partial", parent, tokens))
        self.allocator.mark_cached(page)
        return True

    def _on_evict(self, page: int) -> None:
        """Allocation pressure recycled ``page``: forget every trie entry
        pointing at it before its contents are overwritten — but first,
        when a host tier is attached, spill full-page entries d2h so the
        prefix survives demotion instead of costing a re-prefill. A page
        whose h2d fetch is still pending holds garbage and is NEVER
        spilled (the host tier already owns the key); partial pages are
        not spilled either — the content-addressed chain names full
        pages only."""
        entries = self._by_page.pop(page, [])
        pending = page in self.fetch_pending
        self.fetch_pending.discard(page)
        for entry in entries:
            if entry[0] == "full":
                full = self._full.pop(entry[1], None)
                if full is None:
                    continue
                key = self._node_key.pop(full[0], None)
                if self.host is not None and key is not None and not pending:
                    # Dispatches the d2h gather; the engine drains it
                    # into the host buffers before the page's new
                    # content could be read back.
                    self.host.note_evict(page, key, entry[1][1])
            else:
                children = self._partial.get(entry[1])
                if children is not None:
                    children.pop(entry[2], None)
                    if not children:
                        del self._partial[entry[1]]

    def stats(self) -> Dict[str, float]:
        # Host-served tokens were reclassified out of tokens_missed by
        # note_host_hit, so the three buckets partition every looked-up
        # token: device hit / host hit / miss.
        looked = self.tokens_hit + self.tokens_hit_host + self.tokens_missed
        return {
            "prefix_lookups": self.lookups,
            "prefix_hits": self.hits,
            "prefix_tokens_hit": self.tokens_hit,
            "prefix_tokens_hit_host": self.tokens_hit_host,
            "prefix_tokens_missed": self.tokens_missed,
            "prefix_hit_rate": self.tokens_hit / looked if looked else 0.0,
            "prefix_hit_rate_total": (
                (self.tokens_hit + self.tokens_hit_host) / looked
                if looked else 0.0
            ),
            "prefix_nodes": self.num_nodes,
        }
