"""Per-request model mods: logit bias, grammar masks, LoRA adapters.

Three layers, one per lifetime:

* :class:`Mods` — the immutable, JSON-serializable *spec* a client
  attaches to a request (and the form that rides inside an elastic
  snapshot so mods survive drain/restore and fleet failover).
* :class:`ModState` — the live engine-side state the spec binds to:
  the compiled grammar DFA plus its current state, and the request's
  combined additive bias row. The scheduler advances it via
  ``note_token``; the engine reads ``bias_row()`` at every dispatch.
* :class:`AdapterStore` — named LoRA adapters (low-rank deltas from
  ``training/lora.py``) merged over the shared base weights on demand
  and LRU-evicted like KV pages. Merged trees have *identical* pytree
  structure and shapes to the base params, so swapping them into the
  one compiled decode program is a jit cache hit — never a recompile.

Recompile-safety contract (the sentinel must stay zero): every mask /
bias is a fixed-shape ``float32[max_slots, vocab]`` operand staged as
data; adapters must be registered (and therefore merged — merging jits
once per rank) BEFORE ``arm_recompile_sentinel()``.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Dict, Mapping, Optional, Tuple

import jax
import numpy as np

from distributed_pytorch_tpu.serving.grammar import TokenDFA, compile_grammar
from distributed_pytorch_tpu.training.lora import merge_lora


@dataclasses.dataclass(frozen=True)
class Mods:
    """Per-request model-mod spec. All fields optional and composable:

    * ``logit_bias`` — additive per-token logit offsets (token id ->
      float), applied before temperature, truncation, and sampling.
    * ``grammar`` — a token regex (see :mod:`.grammar`); decoding is
      masked to the DFA's allowed set each step and finishes when the
      grammar reaches a forced end.
    * ``adapter`` — name of a LoRA adapter previously registered with
      the engine; the request decodes under base-plus-delta weights.

    ``stop_sequences`` deliberately live in ``SamplingParams`` (next to
    ``stop_token``), not here: they are pure host-side finish detection
    with no device-side footprint, and they work on speculative engines
    where device mods are refused."""

    logit_bias: Optional[Mapping[int, float]] = None
    grammar: Optional[str] = None
    adapter: Optional[str] = None

    def __post_init__(self):
        if self.logit_bias is not None:
            frozen = tuple(
                sorted((int(t), float(b)) for t, b in dict(self.logit_bias).items())
            )
            object.__setattr__(self, "logit_bias", frozen)

    @property
    def device_mods(self) -> bool:
        """True when any mod touches the device program's operands (vs
        stop sequences, which are host-only)."""
        return bool(self.logit_bias) or self.grammar is not None or (
            self.adapter is not None
        )

    def to_spec(self) -> dict:
        doc: dict = {}
        if self.logit_bias:
            doc["logit_bias"] = {str(t): b for t, b in self.logit_bias}
        if self.grammar is not None:
            doc["grammar"] = self.grammar
        if self.adapter is not None:
            doc["adapter"] = self.adapter
        return doc

    @classmethod
    def from_spec(cls, doc: Mapping) -> "Mods":
        bias = doc.get("logit_bias")
        return cls(
            logit_bias=(
                {int(t): float(b) for t, b in bias.items()}
                if bias
                else None
            ),
            grammar=doc.get("grammar"),
            adapter=doc.get("adapter"),
        )


class ModState:
    """Live per-request mod state bound to one engine's vocabulary.

    The scheduler calls :meth:`note_token` on every committed token
    (grammar state advance; True = forced end, finish the request).
    The engine calls :meth:`bias_row` at dispatch to stage this row of
    the fixed-shape bias operand."""

    def __init__(self, mods: Mods, vocab_size: int) -> None:
        self.mods = mods
        self.vocab_size = vocab_size
        self._static_bias: Optional[np.ndarray] = None
        if mods.logit_bias:
            row = np.zeros((vocab_size,), dtype=np.float32)
            for tok, bias in mods.logit_bias:
                if not 0 <= tok < vocab_size:
                    raise ValueError(
                        f"logit_bias token {tok} outside vocab "
                        f"[0, {vocab_size})"
                    )
                row[tok] = bias
            row.setflags(write=False)
            self._static_bias = row
        self.dfa: Optional[TokenDFA] = (
            compile_grammar(mods.grammar, vocab_size)
            if mods.grammar is not None
            else None
        )
        self.gstate: Optional[int] = self.dfa.start if self.dfa else None

    @property
    def adapter(self) -> Optional[str]:
        return self.mods.adapter

    @property
    def needs_sync(self) -> bool:
        """Grammar rows need the committed token before the next mask
        can be staged; adapter rows dispatch in their own per-adapter
        group. Both resolve in-step (forfeiting dispatch/readback
        overlap for that row only). Bias-only rows stay async — their
        row is request-constant."""
        return self.dfa is not None or self.mods.adapter is not None

    def bias_row(self) -> Optional[np.ndarray]:
        """The request's additive logit row for the NEXT dispatch:
        static bias plus the grammar mask of the current DFA state.
        None = all-zeros (caller may skip staging entirely)."""
        if self.dfa is None:
            return self._static_bias
        mask = self.dfa.mask_row(self.gstate)
        if self._static_bias is None:
            return mask
        return mask + self._static_bias

    def note_token(self, token: int) -> bool:
        if self.dfa is None:
            return False
        self.gstate = self.dfa.advance(self.gstate, int(token))
        return self.dfa.is_end(self.gstate)

    def replay(self, tokens) -> None:
        """Rebuild grammar state deterministically from committed tokens
        (elastic restore: the DFA is pure, so replay == the original
        walk)."""
        for tok in tokens:
            self.note_token(tok)


@functools.lru_cache(maxsize=None)
def _merge_fn(rank: int, alpha: Optional[float]):
    def merge(params, adapters):
        return merge_lora(params, adapters, rank=rank, alpha=alpha)

    return jax.jit(merge)


class AdapterStore:
    """Named LoRA adapters with an LRU device cache of merged weights.

    ``register`` keeps the (small) low-rank host trees; ``params_for``
    returns base-plus-delta full weights, merging on miss via a jitted
    ``merge_lora`` (one compile per distinct rank/alpha — do it before
    arming the recompile sentinel; ``register`` warms by default) and
    evicting the least-recently-used merged tree beyond ``max_live``
    (each merged tree is a full model copy — the KV-page economics,
    applied to weights)."""

    def __init__(self, base_params, max_live: int = 4) -> None:
        self._base = base_params
        self._specs: Dict[str, Tuple[object, int, Optional[float]]] = {}
        self._merged: "collections.OrderedDict[str, object]" = (
            collections.OrderedDict()
        )
        self.max_live = max(1, int(max_live))
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def register(
        self,
        name: str,
        adapters,
        *,
        rank: int,
        alpha: Optional[float] = None,
        warm: bool = True,
    ) -> None:
        if name in self._specs:
            raise ValueError(f"adapter {name!r} already registered")
        self._specs[name] = (adapters, int(rank), alpha)
        if warm:
            self.params_for(name)

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def names(self) -> Tuple[str, ...]:
        return tuple(self._specs)

    @property
    def live(self) -> Tuple[str, ...]:
        return tuple(self._merged)

    def params_for(self, name: str):
        spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown adapter {name!r}")
        tree = self._merged.get(name)
        if tree is not None:
            self.hits += 1
            self._merged.move_to_end(name)
            return tree
        self.misses += 1
        adapters, rank, alpha = spec
        while len(self._merged) >= self.max_live:
            self._merged.popitem(last=False)
            self.evictions += 1
        tree = _merge_fn(rank, alpha)(self._base, adapters)
        self._merged[name] = tree
        return tree
