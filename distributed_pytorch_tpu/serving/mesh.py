"""Mesh-sharded serving: GSPMD placement for the continuous-batching engine.

The serving engine's five compiled programs (decode, the prefill ladder,
page copy, draft prefill, draft+verify) are pure jit programs over a
device-resident state: the params and the per-layer KV page pools. Making
them multi-chip is therefore a PLACEMENT problem, not a code change — the
pjit recipe (PAPERS.md, arXiv 2204.06514): lay the chips out on a
``("data", "model")`` mesh, annotate every program input/output with a
:class:`~jax.sharding.NamedSharding`, and let the SPMD partitioner insert
the collectives. This module owns those annotations:

* **Weights** follow the Megatron split the training side already encodes
  in :data:`~distributed_pytorch_tpu.parallel.partitioning
  .TRANSFORMER_TP_RULES`, rebound from the training mesh's ``"tensor"``
  axis name onto serving's ``"model"`` (:data:`SERVING_PARAM_RULES`) —
  column-then-row attention/MLP splits, one all-reduce per block.
* **KV page pools** ``[num_pages, page_size, Hkv, D]`` split the KV-head
  dim over ``"model"`` (:func:`kv_pool_shardings`) — each model shard
  writes and reads exactly the head slice its Q/K/V column shards
  produce, so paged attention needs NO extra collective beyond the ones
  the weight split already implies. Page IDs are replicated metadata: the
  host-side allocator, block tables, scheduler, and prefix trie never see
  the mesh.
* **Everything else** (token rows, block-table batches, lengths,
  temperatures, RNG keys, sampled outputs) is replicated
  (:func:`replicated`); the unused ``data`` axis replicates the whole
  engine, so every data replica holds identical tokens — the single-host
  proxy for engine replicas riding the data axis.

Exactness contract: a ``(1, 1)`` mesh compiles to the same math as the
unsharded engine (bitwise-identical tokens); larger meshes reorder float
reductions across shards, so cross-geometry parity is greedy-token
(argmax) rather than bitwise — pinned by ``tests/test_serving_mesh.py``
on the 8-virtual-CPU rig.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    TRANSFORMER_TP_RULES,
    make_param_specs,
    rules_on_axis,
    specs_to_shardings,
)

#: The serving mesh's axis names, in mesh order: engine replicas ride
#: ``data``, tensor-parallel shards ride ``model``.
SERVING_AXES: Tuple[str, str] = ("data", "model")

#: :data:`TRANSFORMER_TP_RULES` with every ``"tensor"`` occurrence rebound
#: to the serving mesh's ``"model"`` axis.
SERVING_PARAM_RULES = rules_on_axis(TRANSFORMER_TP_RULES, "model")

#: Per-layer paged KV pools ``[num_pages, page_size, Hkv, D]`` split their
#: KV-head dim; pages and in-page positions are never split (a physical
#: page id must name the same token span on every shard — the host
#: allocator hands out ids with no idea a mesh exists).
KV_POOL_SPEC = P(None, None, "model", None)
# Int8 KV scale pools [num_pages, page_size, Hkv] shard the same Hkv axis.
KV_SCALE_SPEC = P(None, None, "model")


def make_serving_mesh(
    data: int = 1,
    model: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A ``(data, model)`` mesh over the first ``data * model`` devices.

    Unlike :func:`~distributed_pytorch_tpu.parallel.mesh.make_mesh` alone,
    submeshes are allowed implicitly — a ``(1, 1)`` serving mesh on the
    8-virtual-device test rig is the parity baseline, not a typo.
    """
    if data < 1 or model < 1:
        raise ValueError(
            f"mesh axes must be >= 1, got (data={data}, model={model})"
        )
    if devices is None:
        devices = jax.devices()
    need = data * model
    if need > len(devices):
        raise ValueError(
            f"serving mesh ({data},{model}) needs {need} devices, "
            f"have {len(devices)}"
        )
    return make_mesh(
        {"data": data, "model": model}, devices=list(devices)[:need]
    )


def mesh_fingerprint(mesh: Optional[Mesh]) -> str:
    """``"DxM"`` geometry string; ``"1x1"`` for an unsharded engine.

    Threaded through ``EngineSnapshot`` so ``restore_engine`` can refuse a
    geometry mismatch: shards reorder float accumulation, so a sampled
    stream recovered onto different geometry could silently diverge."""
    if mesh is None:
        return "1x1"
    shape = dict(mesh.shape)
    return f"{shape.get('data', 1)}x{shape.get('model', 1)}"


def axis_sizes(mesh: Optional[Mesh]) -> Tuple[int, int]:
    """``(data_size, model_size)``; ``(1, 1)`` for an unsharded engine."""
    if mesh is None:
        return (1, 1)
    shape = dict(mesh.shape)
    return (shape.get("data", 1), shape.get("model", 1))


def validate_kv_heads(model, mesh: Optional[Mesh], *, role: str = "target"):
    """Up-front refusal when a model's heads cannot split over ``model``.

    The KV pools shard dim 2 (``Hkv``) and the Q/K/V kernels shard their
    head dims, so both ``Hkv`` and ``n_heads`` must divide the model-axis
    size. :func:`~distributed_pytorch_tpu.parallel.partitioning
    .make_param_specs` would also catch this at spec time, but its error
    names a kernel path — this one names the head counts, which is what
    the operator actually tunes."""
    _, tp = axis_sizes(mesh)
    if tp == 1:
        return
    n_heads = model.n_heads
    n_kv = getattr(model, "n_kv_heads", 0)
    kv_heads = n_kv or n_heads
    if kv_heads % tp:
        raise ValueError(
            f"{role} model has Hkv={kv_heads} KV heads "
            f"(n_kv_heads={n_kv}, n_heads={n_heads}) — not divisible by "
            f"the mesh 'model' axis (size {tp}). The paged KV pools shard "
            "heads over 'model', so Hkv % model_size must be 0; lower the "
            "model axis or raise n_kv_heads"
        )
    if n_heads % tp:
        raise ValueError(
            f"{role} model has n_heads={n_heads} query heads — not "
            f"divisible by the mesh 'model' axis (size {tp}); the Q "
            "projection shards its head dim over 'model'"
        )


def serving_param_shardings(mesh: Mesh, params):
    """NamedSharding pytree for a TransformerLM params tree on the serving
    mesh — :data:`SERVING_PARAM_RULES` with up-front divisibility
    validation (a readable shape error now beats XLA's at compile)."""
    specs = make_param_specs(params, SERVING_PARAM_RULES, mesh=mesh)
    return specs_to_shardings(mesh, specs)


def kv_pool_shardings(mesh: Mesh, cache):
    """NamedSharding pytree for one paged cache collection: every 4-d leaf
    is a per-layer pool ``[num_pages, page_size, Hkv, D]`` and gets
    :data:`KV_POOL_SPEC`; every 3-d leaf is an int8 scale pool
    ``[num_pages, page_size, Hkv]`` and gets :data:`KV_SCALE_SPEC` — both
    put KV heads on ``model``."""

    def sharding(leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 4:
            return NamedSharding(mesh, KV_POOL_SPEC)
        if ndim == 3:
            return NamedSharding(mesh, KV_SCALE_SPEC)
        raise ValueError(
            "paged cache leaf has shape "
            f"{getattr(leaf, 'shape', None)}; expected a 4-d "
            "[num_pages, page_size, Hkv, D] pool or a 3-d "
            "[num_pages, page_size, Hkv] scale pool"
        )

    return jtu.tree_map(sharding, cache)


def replicated(mesh: Mesh) -> NamedSharding:
    """The replicated sharding for host-staged program inputs (token rows,
    block tables, lengths, temps, keys) and sampled-token outputs."""
    return NamedSharding(mesh, P())
