"""Grammar-constrained decoding: token-level regex -> DFA -> logit masks.

The front door accepts a *token regex* — a regular expression whose
alphabet is token ids, not characters (this codebase is tokenizer-free,
so constraints are expressed directly over the vocabulary). The pattern
compiles once per request into a DFA; at each decode step the DFA's
current state yields a fixed-shape ``float32[vocab]`` additive mask row
(0.0 on allowed tokens, :data:`NEG_MASK` elsewhere) that the engine
stages into the one compiled decode program. Masking is therefore pure
data — the program never recompiles, and an all-zeros row is the exact
bitwise no-op the mods-off parity tests pin.

Pattern syntax (whitespace separates atoms; concatenation is implicit):

    atom     := INT | '.' | '[' (INT | INT '-' INT)+ ']' | '(' expr ')'
    postfix  := atom ('*' | '+' | '?')?
    expr     := seq ('|' seq)*

Examples over a 48-token vocab::

    "7 (1 2)* 9"        # 7, then any number of 1,2 pairs, then 9
    "[10-19]+ 3"        # one or more tokens in [10, 19], then 3
    "(5 | 6 | 7) .*"    # starts with 5, 6 or 7, anything after

Semantics chosen for serving:

* **Forced end**: a request finishes when the DFA reaches a state with
  no outgoing transitions (the grammar cannot continue). Accepting
  states *with* continuations do not stop generation — ``max_new_tokens``
  or stop sequences handle early exit, composably.
* **No dead ends by construction**: subset construction only creates
  reachable states, and a state whose mask would be empty simply has no
  outgoing transitions — it is a forced end, finished host-side before
  any dispatch, so the device never sees an all-``NEG_MASK`` row.
* Patterns that match only the empty sequence (or nothing) are refused
  at compile time: a grammar that is already over cannot constrain
  generation.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

# Additive logit penalty for disallowed tokens. Large enough that a
# masked token never wins argmax and its softmax weight underflows to
# zero, small enough to stay comfortably finite in float32 arithmetic.
NEG_MASK = -1.0e9


# --------------------------------------------------------------- pattern


def _lex(pattern: str) -> List[Tuple[str, Optional[int]]]:
    toks: List[Tuple[str, Optional[int]]] = []
    i, n = 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c.isspace():
            i += 1
        elif c in "()[]|*+?.-":
            toks.append((c, None))
            i += 1
        elif c.isdigit():
            j = i
            while j < n and pattern[j].isdigit():
                j += 1
            toks.append(("INT", int(pattern[i:j])))
            i = j
        else:
            raise ValueError(
                f"grammar: unexpected character {c!r} at {i} in "
                f"{pattern!r}"
            )
    return toks


class _Nfa:
    """Thompson-construction NFA: per-state epsilon edges plus
    symbol-set edges (each labelled with a frozenset of token ids)."""

    def __init__(self) -> None:
        self.eps: List[List[int]] = []
        self.sym: List[List[Tuple[FrozenSet[int], int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.sym.append([])
        return len(self.eps) - 1


class _Parser:
    """Recursive-descent token-regex parser producing NFA fragments
    ``(start, accept)`` with a single accept state each."""

    def __init__(self, pattern: str, vocab_size: int) -> None:
        self.toks = _lex(pattern)
        self.pos = 0
        self.vocab = vocab_size
        self.nfa = _Nfa()
        self.pattern = pattern

    def _peek(self) -> Optional[str]:
        return self.toks[self.pos][0] if self.pos < len(self.toks) else None

    def _take(self, kind: str) -> Optional[int]:
        if self._peek() != kind:
            raise ValueError(
                f"grammar: expected {kind!r}, got {self._peek()!r} in "
                f"{self.pattern!r}"
            )
        _, val = self.toks[self.pos]
        self.pos += 1
        return val

    def parse(self) -> Tuple[int, int]:
        frag = self._expr()
        if self.pos != len(self.toks):
            raise ValueError(
                f"grammar: trailing tokens from position {self.pos} in "
                f"{self.pattern!r}"
            )
        return frag

    def _expr(self) -> Tuple[int, int]:
        frags = [self._seq()]
        while self._peek() == "|":
            self._take("|")
            frags.append(self._seq())
        if len(frags) == 1:
            return frags[0]
        s, a = self.nfa.state(), self.nfa.state()
        for fs, fa in frags:
            self.nfa.eps[s].append(fs)
            self.nfa.eps[fa].append(a)
        return s, a

    def _seq(self) -> Tuple[int, int]:
        frags = []
        while self._peek() in ("INT", "(", "[", "."):
            frags.append(self._postfix())
        if not frags:
            # An empty branch ("a |" or "()") would admit the empty
            # sequence — refused below anyway, but fail early and clearly.
            raise ValueError(
                f"grammar: empty sequence branch in {self.pattern!r}"
            )
        s, a = frags[0]
        for fs, fa in frags[1:]:
            self.nfa.eps[a].append(fs)
            a = fa
        return s, a

    def _postfix(self) -> Tuple[int, int]:
        s, a = self._atom()
        op = self._peek()
        if op in ("*", "+", "?"):
            self._take(op)
            ns, na = self.nfa.state(), self.nfa.state()
            self.nfa.eps[ns].append(s)
            self.nfa.eps[a].append(na)
            if op in ("*", "?"):
                self.nfa.eps[ns].append(na)
            if op in ("*", "+"):
                self.nfa.eps[a].append(s)
            return ns, na
        return s, a

    def _atom(self) -> Tuple[int, int]:
        kind = self._peek()
        if kind == "(":
            self._take("(")
            frag = self._expr()
            self._take(")")
            return frag
        if kind == "[":
            return self._edge(self._cls())
        if kind == ".":
            self._take(".")
            return self._edge(frozenset(range(self.vocab)))
        tok = self._take("INT")
        return self._edge(frozenset((self._check(tok),)))

    def _cls(self) -> FrozenSet[int]:
        self._take("[")
        ids: set = set()
        while self._peek() == "INT":
            lo = self._take("INT")
            if self._peek() == "-":
                self._take("-")
                hi = self._take("INT")
                if hi < lo:
                    raise ValueError(
                        f"grammar: empty range {lo}-{hi} in "
                        f"{self.pattern!r}"
                    )
                ids.update(range(self._check(lo), self._check(hi) + 1))
            else:
                ids.add(self._check(lo))
        self._take("]")
        if not ids:
            raise ValueError(
                f"grammar: empty token class in {self.pattern!r}"
            )
        return frozenset(ids)

    def _check(self, tok: int) -> int:
        if not 0 <= tok < self.vocab:
            raise ValueError(
                f"grammar: token {tok} outside vocab [0, {self.vocab}) "
                f"in {self.pattern!r}"
            )
        return tok

    def _edge(self, syms: FrozenSet[int]) -> Tuple[int, int]:
        s, a = self.nfa.state(), self.nfa.state()
        self.nfa.sym[s].append((syms, a))
        return s, a


# ------------------------------------------------------------------- DFA


class TokenDFA:
    """Deterministic automaton over token ids with per-state cached
    float32 mask rows. States are dense ints; 0 is the start state."""

    def __init__(
        self,
        vocab_size: int,
        transitions: List[Dict[int, int]],
        accepting: FrozenSet[int],
        pattern: str,
    ) -> None:
        self.vocab_size = vocab_size
        self.pattern = pattern
        self._trans = transitions
        self.accepting = accepting
        self._masks: Dict[int, np.ndarray] = {}

    @property
    def start(self) -> int:
        return 0

    @property
    def n_states(self) -> int:
        return len(self._trans)

    def allowed(self, state: int) -> FrozenSet[int]:
        return frozenset(self._trans[state])

    def is_end(self, state: int) -> bool:
        """No outgoing transitions: generation under this grammar is
        forced to stop here."""
        return not self._trans[state]

    def mask_row(self, state: int) -> np.ndarray:
        """Additive logit mask for ``state``: 0.0 on allowed token ids,
        :data:`NEG_MASK` elsewhere. Cached per state; callers must not
        mutate the returned row (it is staged as-is every step)."""
        row = self._masks.get(state)
        if row is None:
            row = np.full((self.vocab_size,), NEG_MASK, dtype=np.float32)
            ids = list(self._trans[state])
            if ids:
                row[ids] = 0.0
            row.setflags(write=False)
            self._masks[state] = row
        return row

    def advance(self, state: int, token: int) -> int:
        try:
            return self._trans[state][int(token)]
        except KeyError:
            raise ValueError(
                f"grammar {self.pattern!r}: token {token} not allowed "
                f"in state {state}"
            ) from None


def compile_grammar(pattern: str, vocab_size: int) -> TokenDFA:
    """Compile a token regex into a :class:`TokenDFA` via Thompson NFA
    construction and subset construction. Refuses patterns whose
    language is empty or contains only the empty sequence."""
    if vocab_size <= 0:
        raise ValueError("grammar: vocab_size must be positive")
    parser = _Parser(pattern, vocab_size)
    start, accept = parser.parse()
    nfa = parser.nfa

    def eclose(states: FrozenSet[int]) -> FrozenSet[int]:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = eclose(frozenset((start,)))
    index: Dict[FrozenSet[int], int] = {start_set: 0}
    order: List[FrozenSet[int]] = [start_set]
    transitions: List[Dict[int, int]] = [{}]
    accepting: set = set()
    todo = [start_set]
    while todo:
        cur = todo.pop()
        ci = index[cur]
        if accept in cur:
            accepting.add(ci)
        # Group reachable NFA targets by token id across the member
        # states' symbol edges, then close and intern each target set.
        by_token: Dict[int, set] = {}
        for s in cur:
            for syms, dst in nfa.sym[s]:
                for tok in syms:
                    by_token.setdefault(tok, set()).add(dst)
        for tok, dsts in by_token.items():
            nxt = eclose(frozenset(dsts))
            ni = index.get(nxt)
            if ni is None:
                ni = len(order)
                index[nxt] = ni
                order.append(nxt)
                transitions.append({})
                todo.append(nxt)
            transitions[ci][tok] = ni
    if not transitions[0]:
        raise ValueError(
            f"grammar {pattern!r}: matches at most the empty sequence — "
            "cannot constrain generation"
        )
    return TokenDFA(vocab_size, transitions, frozenset(accepting), pattern)
