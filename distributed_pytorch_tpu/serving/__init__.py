"""Continuous-batching inference engine — the serving side of the LM family.

``generation.py`` is strictly offline: one fixed batch in, one compiled
``fori_loop`` out, and no request may join until every sequence in the batch
finishes. This package turns the same decode math into a REQUEST-level
engine, split exactly along the pjit paper's host/device line:

* the device runs ONE fixed-shape jit decode step (padded slots masked out,
  so there is exactly one compilation per shape bucket);
* the host owns everything irregular: the refcounted paged KV allocator and
  the prefix-cache trie (:mod:`.kv_cache`), the waiting queue /
  chunked-prefill / copy-on-write / preemption policy (:mod:`.scheduler`),
  and admission control + latency metrics (:mod:`.admission`);
* :class:`.engine.InferenceEngine` glues them behind
  ``submit(prompt, params) -> request_id`` / ``step()`` / ``poll()``.

Deterministic on CPU (``JAX_PLATFORMS=cpu``): tests assert continuous
batching reproduces offline ``generate()`` token for token.
"""

from distributed_pytorch_tpu.serving.admission import (
    AdmissionController,
    AdmissionError,
    EngineDraining,
    QueueFull,
    RequestTooLong,
    ServingMetrics,
)
from distributed_pytorch_tpu.serving.elastic import (
    DrainController,
    EngineSnapshot,
    RequestSnapshot,
    SnapshotUnavailable,
    adopt_snapshot,
    drain_engine,
    publish_snapshot,
    restore_engine,
    snapshot_engine,
)
from distributed_pytorch_tpu.serving.engine import InferenceEngine
from distributed_pytorch_tpu.serving.fleet import (
    AutoscalePolicy,
    FleetRouter,
    NoLiveReplica,
    prefix_affinity_key,
)
from distributed_pytorch_tpu.serving.frontdoor import (
    FrontDoor,
    TenantConfig,
    TenantQuotaExceeded,
    TokenStream,
)
from distributed_pytorch_tpu.serving.grammar import (
    TokenDFA,
    compile_grammar,
)
from distributed_pytorch_tpu.serving.hostkv import HostPageTier
from distributed_pytorch_tpu.serving.journal import (
    Journal,
    JournalError,
    JournalState,
    pid_alive,
    read_worker_registry,
    replay_journal,
)
from distributed_pytorch_tpu.serving.mods import (
    AdapterStore,
    Mods,
    ModState,
)
from distributed_pytorch_tpu.serving.kv_cache import (
    BlockTable,
    OutOfPages,
    PagePoolGroup,
    PagedBlockAllocator,
    PrefixCache,
)
from distributed_pytorch_tpu.serving.mesh import (
    make_serving_mesh,
    mesh_fingerprint,
)
from distributed_pytorch_tpu.serving.replica import (
    CircuitBreaker,
    LocalReplicaClient,
    ProcessReplicaClient,
    ReplicaClient,
    ReplicaDead,
    ReplicaError,
    ReplicaUnavailable,
    spawn_replica_clients,
)
from distributed_pytorch_tpu.serving.scheduler import (
    PENDING_TOKEN,
    Request,
    RequestState,
    SamplingParams,
    Scheduler,
    StepPlan,
)

__all__ = [
    "AdapterStore",
    "AdmissionController",
    "AdmissionError",
    "AutoscalePolicy",
    "BlockTable",
    "CircuitBreaker",
    "DrainController",
    "EngineDraining",
    "EngineSnapshot",
    "FleetRouter",
    "FrontDoor",
    "HostPageTier",
    "InferenceEngine",
    "Journal",
    "JournalError",
    "JournalState",
    "LocalReplicaClient",
    "ModState",
    "Mods",
    "NoLiveReplica",
    "OutOfPages",
    "PENDING_TOKEN",
    "PagePoolGroup",
    "PagedBlockAllocator",
    "PrefixCache",
    "ProcessReplicaClient",
    "QueueFull",
    "ReplicaClient",
    "ReplicaDead",
    "ReplicaError",
    "ReplicaUnavailable",
    "Request",
    "RequestSnapshot",
    "RequestState",
    "RequestTooLong",
    "SamplingParams",
    "Scheduler",
    "ServingMetrics",
    "SnapshotUnavailable",
    "StepPlan",
    "TenantConfig",
    "TenantQuotaExceeded",
    "TokenDFA",
    "TokenStream",
    "adopt_snapshot",
    "compile_grammar",
    "drain_engine",
    "make_serving_mesh",
    "mesh_fingerprint",
    "pid_alive",
    "prefix_affinity_key",
    "publish_snapshot",
    "read_worker_registry",
    "replay_journal",
    "restore_engine",
    "snapshot_engine",
    "spawn_replica_clients",
]
