"""Elastic serving: drain protocol, engine snapshot/restore codec, and the
serving half of the chaos drills.

The training stack already survives reclaims (SIGTERM drain marks,
step-granular snapshots); this module gives the inference engine the same
story. The key observation is that the engine's preemption path ALREADY
proves most of it: a preempted request keeps its generated tokens, releases
its pages, and resumes token-identically on re-admission, because

* greedy decode is a pure function of (params, tokens), and
* a sampled request draws token i with ``fold_in(PRNGKey(seed), n_issued)``
  where ``n_issued`` counts from ``len(prompt)`` — independent of batch
  composition, slot assignment, and restarts.

Restore is therefore "re-admission on a fresh engine": the snapshot records
HOST state only — prompt, committed generated tokens, sampling params,
tenant-opaque metadata, deadline age — plus just enough KV metadata
(committed token count and the content-addressed prefix-trie key chain of
the request's cached pages) for capacity planning on the restore side.
Device pages are deliberately NOT persisted: the restored engine re-prefills
prompt+generated through its prefix cache, so a fleet of requests sharing a
system prompt re-pays that prefix once, not per request.

In-flight work at snapshot time is ROLLED BACK, not awaited: any token
whose device readback never landed (a PENDING placeholder under overlap, an
unresolved draft+verify round) is simply absent from the snapshot, and the
restored engine re-issues the identical dispatch — same fold index, same
sample. A clean drain (:func:`drain_engine`) first finishes the in-flight
step so nothing is re-paid; a kill recovers from the last rolling snapshot
and re-generates the (identical) tail.

:class:`DrainController` wires this into a process: it installs a SIGTERM
handler (the reclaim notice — also what the serving chaos fault kinds
deliver in "hard" mode), drives the engine step loop, drains on notice, and
optionally writes rolling snapshots so even an uncatchable SIGKILL loses
nothing admitted. :func:`publish_snapshot` / :func:`adopt_snapshot` hand a
drained engine's queue to a peer replica through the elastic KV store.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Dict, List, Optional, Tuple

import jax

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.obs.tracer import _PID_REQUESTS
from distributed_pytorch_tpu.serving.mods import Mods, ModState
from distributed_pytorch_tpu.serving.scheduler import (
    Request,
    SamplingParams,
)

SNAPSHOT_VERSION = 1


class SnapshotUnavailable(RuntimeError):
    """No snapshot appeared under the polled key within the deadline.

    Raised only by the bounded-poll mode of :func:`adopt_snapshot` /
    :func:`fetch_snapshot_text` (``timeout_s`` set): the fail-fast mode
    keeps returning ``[]`` / ``None`` so existing probe-style callers
    ("adopt if a peer left something") stay cheap and exception-free."""


@dataclasses.dataclass(frozen=True)
class RequestSnapshot:
    """One admitted-but-unfinished request, as the codec persists it.

    ``generated`` holds only COMMITTED tokens (readback landed); the
    restored engine regenerates anything that was in flight. ``age_s`` is
    elapsed time since submission at snapshot — restore rebases
    ``submit_time`` so deadlines keep counting across the migration —
    and ``ttft_s`` the first-token latency if one was emitted (restored
    for e2e-latency continuity). ``kv_committed`` / ``trie_keys`` are the
    KV metadata: how many tokens had device K/V and the content-addressed
    prefix-trie chain covering them (see ``PrefixCache.key_chain``), so a
    restore target can predict its re-prefill bill without any device
    state crossing the wire."""

    req_id: int
    prompt: Tuple[int, ...]
    generated: Tuple[int, ...]
    max_new_tokens: int
    temperature: float
    seed: int
    stop_token: Optional[int]
    deadline_s: Optional[float]
    metadata: Optional[dict]
    preempt_count: int
    age_s: float
    ttft_s: Optional[float]
    kv_committed: int
    trie_keys: Tuple[str, ...]
    # Defaulted-last for wire compatibility (snapshots written before the
    # front door existed decode as anonymous, nothing-delivered, modless).
    # ``tenant_id`` preserves tenancy across drain/restore and failover;
    # ``delivered`` is the streaming high-water mark (tokens the client
    # already consumed) so a resumed stream neither replays nor skips;
    # ``stop_sequences``/``mods`` rebuild SamplingParams and the live
    # ModState (grammar DFAs re-walk ``generated`` — pure, so the state
    # lands exactly where it was).
    tenant_id: str = "anon"
    delivered: int = 0
    stop_sequences: Tuple[Tuple[int, ...], ...] = ()
    mods: Optional[dict] = None
    # Fleet-wide trace identity: survives drain hand-off and failover
    # id-rebasing (req_ids are engine-local; this string is not).
    # Defaulted so snapshots written before distributed tracing decode.
    trace_id: Optional[str] = None
    # Content-addressed keys of the pages HOST-resident in the source
    # engine's hostkv tier beyond the device chain (``trie_keys``
    # continues into ``host_keys``). Purely informational to the codec —
    # an adopter whose own host tier holds these keys recovers the
    # request by h2d fetch instead of re-prefill (the scheduler's
    # admission-time host continuation does the matching) — but it lets
    # a restore target predict its fetch-vs-reprefill bill up front.
    # Defaulted so snapshots written before the host tier decode.
    host_keys: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class EngineSnapshot:
    """A drained (or rolling) engine snapshot: every live request plus the
    engine fingerprint needed to validate a restore target. ``top_k`` /
    ``top_p`` are compiled into the decode program — restoring onto an
    engine with different truncation would silently change sampled
    outputs, so :func:`restore_engine` refuses. ``next_id`` preserves the
    id space: request ids ARE priorities, and a restored engine must not
    mint an id that outranks a recovered request. ``mesh`` is the
    ``"DxM"`` geometry fingerprint (``"1x1"`` unsharded) — same refusal
    logic: shards reorder float accumulation, so a sampled stream
    recovered onto different geometry could silently diverge."""

    version: int
    page_size: int
    max_seq_len: int
    top_k: int
    top_p: float
    speculative: bool
    next_id: int
    requests: Tuple[RequestSnapshot, ...]
    # Defaulted-last for wire compatibility: version-1 snapshots written
    # before mesh sharding existed decode as unsharded.
    mesh: str = "1x1"
    # KV-page dtype fingerprint ("fp" | "int8"): int8 pages round every
    # written K/V through quantization, so a request recovered across the
    # boundary would re-prefill into a numerically different cache and
    # sampled streams could silently diverge — same refusal logic as
    # ``mesh``. Defaulted so snapshots written before KV quantization
    # decode as fp.
    kv: str = "fp"

    # --------------------------------------------------------------- codec

    def to_json(self) -> str:
        doc = dataclasses.asdict(self)
        return json.dumps(doc, separators=(",", ":"), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "EngineSnapshot":
        doc = json.loads(text)
        if doc.get("version") != SNAPSHOT_VERSION:
            raise ValueError(
                f"snapshot version {doc.get('version')!r} != "
                f"{SNAPSHOT_VERSION}"
            )
        doc.setdefault("mesh", "1x1")
        doc.setdefault("kv", "fp")
        reqs = []
        for entry in doc["requests"]:
            entry = dict(entry)
            entry["prompt"] = tuple(entry["prompt"])
            entry["generated"] = tuple(entry["generated"])
            entry["trie_keys"] = tuple(entry["trie_keys"])
            entry["host_keys"] = tuple(entry.get("host_keys", ()))
            entry["stop_sequences"] = tuple(
                tuple(int(t) for t in seq)
                for seq in entry.get("stop_sequences", ())
            )
            reqs.append(RequestSnapshot(**entry))
        doc["requests"] = tuple(reqs)
        return cls(**doc)

    def save(self, path: str) -> str:
        """Atomic write (tmp + rename), then the chaos hook — a
        ``corrupt_snapshot`` fault in an armed plan damages engine
        snapshots exactly as it does training checkpoints."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json())
        os.replace(tmp, path)
        chaos.on_snapshot_write(path)
        return path

    @classmethod
    def load(cls, path: str) -> "EngineSnapshot":
        with open(path) as f:
            return cls.from_json(f.read())


# ----------------------------------------------------- sampling-params codec


def params_to_doc(params: SamplingParams) -> dict:
    """Canonical JSON-able form of :class:`SamplingParams` — ONE codec for
    every place a request's sampling config crosses a process or crash
    boundary (the replica control plane's ``/submit`` body, the router's
    write-ahead journal). JSON round-trips tuples as lists, so
    ``stop_sequences`` is listified here and re-tupled by
    :func:`params_from_doc`; keeping both directions side by side is what
    stops the wire format and the journal format from drifting apart."""
    doc = dataclasses.asdict(params)
    doc["stop_sequences"] = [
        [int(t) for t in seq] for seq in params.stop_sequences
    ]
    return doc


def params_from_doc(doc: Optional[dict]) -> SamplingParams:
    """Inverse of :func:`params_to_doc`. Tolerates a doc that came through
    JSON (lists re-tuple) and one written by an older incarnation (missing
    keys take the dataclass defaults)."""
    pdoc = dict(doc or {})
    pdoc["stop_sequences"] = tuple(
        tuple(int(t) for t in seq)
        for seq in pdoc.get("stop_sequences", ())
    )
    return SamplingParams(**pdoc)


# ----------------------------------------------------------------- snapshot


def snapshot_engine(engine) -> EngineSnapshot:
    """Codec every live (admitted, non-terminal) request of ``engine``.

    Read-only: nothing in the engine is mutated, so this serves both the
    clean drain (post ``finish_inflight``, no pending anywhere) and the
    ROLLING snapshot an overlapped engine writes between steps — there,
    tokens still awaiting readback are rolled back in the *copied* data
    (truncated at the oldest PENDING position); the restored engine
    re-issues those dispatches at the same fold indices and samples the
    identical values."""
    now = time.perf_counter()
    recs: List[RequestSnapshot] = []
    live = sorted(
        (r for r in engine.requests.values() if not r.done),
        key=lambda r: r.req_id,
    )
    for req in live:
        tokens = req.tokens
        if req.pending_idx:
            tokens = tokens[: req.pending_idx[0]]
        generated = tokens[len(req.prompt):]
        assert generated == req.generated[: len(generated)], (
            f"request {req.req_id}: committed tokens out of sync"
        )
        kv_committed = 0
        trie_keys: Tuple[str, ...] = ()
        host_keys: Tuple[str, ...] = ()
        if req.slot is not None:
            kv_committed = min(req.len_cached, len(tokens))
        if engine.prefix_cache is not None:
            device_keys, beyond = engine.prefix_cache.key_chain_tiered(
                tokens
            )
            trie_keys = tuple(device_keys)
            host_keys = tuple(beyond)
        recs.append(
            RequestSnapshot(
                req_id=req.req_id,
                prompt=tuple(req.prompt),
                generated=tuple(generated),
                max_new_tokens=req.params.max_new_tokens,
                temperature=req.params.temperature,
                seed=req.params.seed,
                stop_token=req.params.stop_token,
                deadline_s=req.params.deadline_s,
                metadata=req.metadata,
                preempt_count=req.preempt_count,
                age_s=max(0.0, now - req.submit_time),
                ttft_s=(
                    req.first_token_time - req.submit_time
                    if req.first_token_time is not None
                    else None
                ),
                kv_committed=kv_committed,
                trie_keys=trie_keys,
                host_keys=host_keys,
                tenant_id=req.tenant_id,
                # Delivery can never outrun commitment: the stream hands
                # out ``generated`` entries, and those are committed.
                delivered=min(req.delivered, len(generated)),
                stop_sequences=tuple(
                    tuple(int(t) for t in seq)
                    for seq in req.params.stop_sequences
                ),
                mods=(
                    req.mods.mods.to_spec() if req.mods is not None
                    else None
                ),
                trace_id=req.trace_id,
            )
        )
    return EngineSnapshot(
        version=SNAPSHOT_VERSION,
        page_size=engine.page_size,
        max_seq_len=engine.max_seq_len,
        top_k=engine._top_k,
        top_p=engine._top_p,
        speculative=engine.speculative,
        next_id=engine._next_id,
        requests=tuple(recs),
        mesh=engine.mesh_fingerprint,
        kv=engine.kv_fingerprint,
    )


def drain_engine(engine, reason: str = "drain") -> EngineSnapshot:
    """The SIGTERM-with-notice protocol, serving half: close the front door
    (submit -> :class:`~.admission.EngineDraining`), let the in-flight
    overlapped step land — one readback, no new dispatch, so whatever it
    finished is delivered rather than re-generated — then snapshot every
    still-live request."""
    engine.stop_admission()
    engine.finish_inflight()
    snap = snapshot_engine(engine)
    engine.drains += 1
    if engine.tracer.enabled:
        engine.tracer.instant(
            "drain", reason=reason, requests=len(snap.requests)
        )
    if engine.flight.enabled:
        engine.flight.record(
            "drain", reason=reason, requests=len(snap.requests)
        )
        engine._dump_postmortem(f"drain:{reason}")
    if engine.goodput is not None:
        # In-process downtime clock: closed again by restore_engine when
        # the same tracker survives (an in-process drain/restore cycle).
        engine.goodput.note_drain()
    return snap


# ------------------------------------------------------------------ restore


def restore_engine(
    engine, snapshot: EngineSnapshot, *, rebase_ids: bool = False
) -> List[int]:
    """Re-admit every snapshotted request into a fresh ``engine``,
    preserving ids (= priorities), sampling state, deadline clocks, and
    tenant metadata. Each request enters WAITING with
    ``tokens = prompt + generated``; the normal admission path then
    re-prefills through the prefix cache — exactly the preemption-resume
    machinery, so restored output is token-identical to an uninterrupted
    run. Returns the restored ids, oldest first.

    ``rebase_ids=True`` mints FRESH ids from the target's counter instead
    of preserving snapshot ids — the failover path for adopting several
    replicas' snapshots into one survivor, where two engines that counted
    ids from the same base would otherwise collide (preserving mode
    refuses such a duplicate with ``ValueError``). Snapshot order (oldest
    first) maps positionally onto the returned ids, so a router tracking
    shadow state can re-key its table; relative priority WITHIN the
    snapshot is preserved, but adopted requests rank behind the
    survivor's existing ones (fresh ids are higher = younger). Token
    streams are unaffected: sampling is keyed by per-request ``seed`` and
    fold index, never by req_id."""
    if snapshot.version != SNAPSHOT_VERSION:
        raise ValueError(
            f"snapshot version {snapshot.version} != {SNAPSHOT_VERSION}"
        )
    if (snapshot.top_k, snapshot.top_p) != (engine._top_k, engine._top_p):
        raise ValueError(
            f"snapshot was taken under top_k={snapshot.top_k} "
            f"top_p={snapshot.top_p}, engine compiled with "
            f"top_k={engine._top_k} top_p={engine._top_p} — sampled "
            "streams would diverge; restore onto a matching engine"
        )
    if snapshot.mesh != engine.mesh_fingerprint:
        raise ValueError(
            f"snapshot was taken on a {snapshot.mesh} mesh, restore "
            f"target is {engine.mesh_fingerprint} — sharded reductions "
            "reorder float accumulation, so recovered sampled streams "
            "could silently diverge; restore onto matching geometry"
        )
    if snapshot.kv != engine.kv_fingerprint:
        raise ValueError(
            f"snapshot was taken with {snapshot.kv} KV pages, restore "
            f"target uses {engine.kv_fingerprint} — int8 pages quantize "
            "every written K/V, so a request re-prefilled across the "
            "boundary could silently diverge; restore onto a matching "
            "KV configuration"
        )
    now = time.perf_counter()
    restored: List[int] = []
    tr = engine.tracer
    with tr.phase("restore"):
        for rec in snapshot.requests:
            if rebase_ids:
                req_id = engine._next_id
                engine._next_id += 1
            else:
                req_id = rec.req_id
                if req_id in engine.requests:
                    raise ValueError(
                        f"request id {req_id} already exists in the "
                        "restoring engine (restore with rebase_ids=True "
                        "to mint fresh ids on adoption)"
                    )
            total = len(rec.prompt) + rec.max_new_tokens
            if total > engine.max_seq_len:
                raise ValueError(
                    f"request {rec.req_id} needs {total} tokens; restore "
                    f"target caps at {engine.max_seq_len}"
                )
            params = SamplingParams(
                max_new_tokens=rec.max_new_tokens,
                temperature=rec.temperature,
                seed=rec.seed,
                stop_token=rec.stop_token,
                deadline_s=rec.deadline_s,
                stop_sequences=tuple(
                    tuple(int(t) for t in seq)
                    for seq in rec.stop_sequences
                ),
            )
            mod_state = None
            if rec.mods:
                mod_state = ModState(
                    Mods.from_spec(rec.mods), engine.vocab_size
                )
                # The DFA is pure: re-walking the committed tokens lands
                # the grammar state exactly where the dead engine left it.
                mod_state.replay(rec.generated)
            req = Request(
                req_id=req_id,
                prompt=list(rec.prompt),
                params=params,
                tokens=list(rec.prompt) + list(rec.generated),
                generated=list(rec.generated),
                submit_time=now - rec.age_s,
                preempt_count=rec.preempt_count,
                metadata=(
                    dict(rec.metadata) if rec.metadata is not None else None
                ),
                tenant_id=rec.tenant_id,
                delivered=rec.delivered,
                mods=mod_state,
                trace_id=rec.trace_id,
            )
            if rec.ttft_s is not None:
                req.first_token_time = req.submit_time + rec.ttft_s
            # Goodput: positions the dead engine had K/V for must be
            # re-prefilled here — charge them to restore_reprefill. A
            # prefix-cache re-match on re-admission shrinks the charge,
            # and when the snapshot's key_chain pages are host-resident
            # in the adopter, the host-tier fetch in _admit recovers
            # them without prefill at all.
            req.rework_until = rec.kv_committed
            req.rework_kind = "restore_reprefill"
            engine.requests[req_id] = req
            engine._keys[req_id] = jax.random.PRNGKey(params.seed)
            engine.scheduler.add(req)
            if tr.enabled:
                extra = (
                    {"trace_id": rec.trace_id}
                    if rec.trace_id is not None else {}
                )
                tr.request_begin(
                    req_id,
                    prompt_len=len(rec.prompt),
                    max_new_tokens=rec.max_new_tokens,
                    restored=True,
                    recovered_tokens=len(rec.generated),
                    **extra,
                )
                if rec.trace_id is not None:
                    # The survivor picks up the fleet flow arrow: the
                    # restored span joins the victim's trace_id even
                    # though its req_id was rebased.
                    tr.flow("t", rec.trace_id, _PID_REQUESTS)
            restored.append(req_id)
    if not rebase_ids:
        # Preserving mode keeps the id space: the target must not mint an
        # id that outranks a recovered request. Rebasing already advanced
        # the counter past every minted id.
        engine._next_id = max(engine._next_id, snapshot.next_id)
    engine.restores += 1
    engine.requests_recovered += len(restored)
    if tr.enabled:
        tr.instant("restore", requests=len(restored))
    if engine.flight.enabled:
        engine.flight.record("restore", requests=len(restored))
    if engine.goodput is not None:
        engine.goodput.note_restore()
    return restored


# --------------------------------------------------------- drain controller


class DrainController:
    """Wires reclaim notices into an engine's step loop.

    ``install_signal=True`` registers a SIGTERM handler that merely sets a
    flag — everything observable happens between steps, inside
    :meth:`drive`: on notice, the engine drains (admission closed,
    in-flight step finished, snapshot written) and ``drive`` returns early.
    ``snapshot_every=N`` additionally writes a ROLLING snapshot to
    ``snapshot_path`` every N steps, the recovery point for faults with no
    notice at all (SIGKILL, ``kill_mid_verify``). Usable as a context
    manager to restore the previous signal handler on exit."""

    def __init__(
        self,
        engine,
        *,
        snapshot_path: Optional[str] = None,
        install_signal: bool = False,
        signum: int = signal.SIGTERM,
    ):
        self.engine = engine
        self.snapshot_path = snapshot_path
        self.drain_requested = False
        self.drained = False
        self.snapshot: Optional[EngineSnapshot] = None
        self._signum = signum
        self._prev_handler = None
        if install_signal:
            self._prev_handler = signal.signal(signum, self._on_signal)

    def _on_signal(self, signum, frame) -> None:
        self.request_drain()

    def request_drain(self) -> None:
        self.drain_requested = True

    def uninstall(self) -> None:
        if self._prev_handler is not None:
            signal.signal(self._signum, self._prev_handler)
            self._prev_handler = None

    def __enter__(self) -> "DrainController":
        return self

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    def _write(self, snap: EngineSnapshot) -> None:
        self.snapshot = snap
        if self.snapshot_path is not None:
            snap.save(self.snapshot_path)

    def drain_now(self) -> EngineSnapshot:
        """Drain immediately (between steps) and record the snapshot."""
        snap = drain_engine(self.engine)
        self._write(snap)
        self.drained = True
        return snap

    def drive(
        self, max_steps: int = 10_000, snapshot_every: Optional[int] = None
    ) -> List[int]:
        """``engine.run()`` with the elastic hooks: checks the drain flag
        between steps (a notice mid-step drains after that step's device
        work lands) and writes rolling snapshots every ``snapshot_every``
        steps. Returns the ids finished before completion or drain."""
        eng = self.engine
        finished: List[int] = []
        steps = 0
        try:
            while eng.scheduler.has_work or eng._inflight is not None:
                if self.drain_requested:
                    self.drain_now()
                    return finished
                if steps >= max_steps:
                    raise RuntimeError(
                        f"engine did not drain within {max_steps} steps"
                    )
                finished.extend(eng.step())
                steps += 1
                if snapshot_every and steps % snapshot_every == 0:
                    self._write(snapshot_engine(eng))
        except BaseException as exc:
            # Same last-gasp postmortem as InferenceEngine.run(): crashes
            # escaping the drive loop leave a dump + trace behind.
            flush = getattr(eng, "_flush_on_crash", None)
            if flush is not None:
                flush("exception", exc)
            raise
        if self.drain_requested and not self.drained:
            # Notice arrived as the queue emptied: drain the (now idle)
            # engine so the caller still gets its snapshot + closed door.
            self.drain_now()
        return finished


# ------------------------------------------------------------ peer handoff


def publish_snapshot(store, key: str, snapshot: EngineSnapshot) -> None:
    """Hand a drained engine's queue to peers via the elastic KV store
    (:class:`~distributed_pytorch_tpu.elastic.store.KVStoreClient`)."""
    store.set(key, snapshot.to_json())


def fetch_snapshot_text(
    store, key: str, *, timeout_s: float,
    poll_interval_s: float = 0.02,
) -> str:
    """Poll ``store`` for ``key`` until it appears or ``timeout_s``
    elapses, sleeping a jittered exponential backoff between probes
    (capped at 0.25s so a snapshot published late in the window is still
    picked up promptly). The race this covers: a dying replica's final
    ``publish_snapshot`` can lose to the survivor's adoption attempt by
    milliseconds, and failing fast there turns a clean hand-off into an
    avoidable re-generation. Raises :class:`SnapshotUnavailable` on
    deadline."""
    import random

    deadline = time.monotonic() + max(0.0, timeout_s)
    sleep_s = max(1e-4, poll_interval_s)
    while True:
        text = store.get(key)
        if text is not None:
            return text
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise SnapshotUnavailable(
                f"no snapshot under {key!r} after {timeout_s:.3f}s"
            )
        # Full jitter on the backoff: many survivors polling one store for
        # one victim's key should not probe in lockstep.
        time.sleep(min(remaining, sleep_s * (0.5 + random.random() * 0.5)))
        sleep_s = min(sleep_s * 2.0, 0.25)


def adopt_snapshot(
    engine, store, key: str, *, delete: bool = True,
    rebase_ids: bool = False, timeout_s: Optional[float] = None,
) -> List[int]:
    """Fetch a published snapshot and restore it into ``engine``; deletes
    the key afterwards by default (adopt-once). Returns the restored ids,
    or ``[]`` when no snapshot is published under ``key``.
    ``rebase_ids=True`` mints fresh ids on adoption (see
    :func:`restore_engine`) — required when one survivor adopts snapshots
    from several peers whose id spaces overlap.

    ``timeout_s`` switches from fail-fast to a bounded poll with jittered
    backoff (see :func:`fetch_snapshot_text`): the adopter waits that long
    for a not-yet-published key before raising
    :class:`SnapshotUnavailable` — covering a publisher whose final write
    races its own death."""
    if timeout_s is None:
        text = store.get(key)
        if text is None:
            return []
    else:
        text = fetch_snapshot_text(store, key, timeout_s=timeout_s)
    ids = restore_engine(
        engine, EngineSnapshot.from_json(text), rebase_ids=rebase_ids
    )
    if delete:
        store.delete(key)
    return ids
