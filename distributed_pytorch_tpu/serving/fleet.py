"""Routed replica fleet: prefix-affinity routing, health-checked
token-identical failover, hedging with request-id dedup, and SLO-driven
scale-out/scale-in over N replicas — in-process engines or real worker
subprocesses, behind one :class:`~.replica.ReplicaClient` interface.

One engine replica is production-shaped — elastic, chaos-drilled,
observable — but a fleet needs three things no single replica provides:
something that *routes* to N of them, something that *notices* when one
dies, and something that *moves its work* without changing a single output
token. This module is that layer, built deliberately on machinery that
already exists rather than new device code:

* **Routing** is content-addressed: the router hashes the page-aligned
  prefix of each prompt with the exact ``PrefixCache.key_chain`` digest
  (``sha256(prev + "|" + tokens)`` per page, chained from ``"root"``), so
  the affinity key of a prompt IS the trie address of its first page(s) on
  any engine. Rendezvous hashing over the live replicas then sends
  shared-prefix traffic to the replica whose trie already holds those
  pages, with minimal reshuffling when the replica set changes. Prompts
  too short for a full page — and affinity targets over the spill
  threshold — fall back to the least-loaded replica, read live from each
  engine's registry gauges (``queue_depth + running_requests``).

* **Failover is token-identical by construction.** The router keeps a
  shadow :class:`~.elastic.RequestSnapshot` per in-flight request —
  prompt, sampling params (seed!), and the committed generated tokens
  observed via ``poll()`` after each step; the fold index is implied by
  their count. Because token *i* of a request is drawn with
  ``fold_in(PRNGKey(seed), i)`` independent of batch composition, slot,
  or engine identity, re-admitting ``prompt + generated`` on ANY
  same-fingerprint replica through :func:`~.elastic.restore_engine`
  regenerates the identical tail. A dead replica's uncommitted in-flight
  dispatch is simply re-issued elsewhere at the same fold index. Request
  ids are namespaced per replica at attach (``index * id_stride``), so
  two replicas' requests can land on one survivor without colliding.

* **Death is detected, not assumed**: ``/healthz``-style probes (over
  HTTP via :func:`~distributed_pytorch_tpu.obs.server.scrape` when a
  replica serves, else in-process ``engine.health()``) with a consecutive
  -failure threshold, plus a per-step liveness deadline for replicas that
  stop making progress while holding work. A probe answering 503
  *draining* is an answer, not a death: the replica leaves the admission
  rotation but stays in the route table, stepped and polled, until its
  in-flight requests stream to completion — and a SIGTERM-style
  :meth:`FleetRouter.drain_replica` hands its queue to a survivor via
  :func:`~.elastic.publish_snapshot` / :func:`~.elastic.adopt_snapshot`
  (or a direct restore) with zero token divergence.

* **Retries, hedging, dedup.** Admission failures are retried across
  replicas with bounded exponential backoff (``EngineDraining`` means
  "elsewhere, now" and costs no backoff; ``QueueFull`` means "later" and
  does). Optionally, a request with no first token after ``hedge_after_s``
  is duplicated on a second replica — determinism makes the copies
  token-identical, so whichever finishes first wins. The dedup rule: a
  fleet request emits exactly once, keyed by fleet id; the first copy to
  finish is recorded, every other copy is cancelled, and a twin that
  finishes anyway is counted ``duplicates_suppressed`` and never emitted.

* **The SRE loop closes at the fleet.** With an :class:`AutoscalePolicy`,
  a firing SLO burn-rate alert on any live replica (``obs/slo.py``) spins
  up a new replica from ``engine_factory``, and fleet-wide ``budget_idle``
  waste (``obs/goodput.py``) above the threshold drains the least-loaded
  replica down — both as observable route-table transitions, not
  orchestration outside the process.

The router holds every replica through a :class:`~.replica.ReplicaClient`
— :class:`~.replica.LocalReplicaClient` for an in-process engine
(behaviorally identical to the pre-interface router; ``replica.engine``
still exposes the real engine object), or
:class:`~.replica.ProcessReplicaClient` for a replica worker SUBPROCESS
that can genuinely crash. Cross-process robustness is breaker-shaped:
each client carries a :class:`~.replica.CircuitBreaker`, and a
breaker-open replica enters DEGRADED mode — excluded from rendezvous
hashing and skipped by the pump, but its shadow snapshots are retained
and it is NOT declared dead, so a hung (SIGSTOPped) replica costs the
fleet capacity instead of tail latency and rejoins after one successful
half-open probe. Death, for a process replica, means the process: the
client observed the child exit (``ReplicaDead``), or the liveness
deadline expired while it held work.

Chaos integration: the router calls :func:`chaos.on_fleet_step` once per
pump round; the armed plan's fleet faults come back as declarations and
the router applies the damage. In-process kinds (``kill_replica``,
``partition_replica``, ``slow_replica``) damage the route table —
abandoning the engine object mid-flight for a kill (the in-process
SIGKILL twin), refusing contact for a partition, sleeping before each
step for a straggler. Process kinds (``kill_replica_process``,
``hang_replica_process``, ``partition_replica_process``) deliver REAL
damage through the client — SIGKILL, SIGSTOP, a black-holed control
socket — and degrade to the in-process semantics when the target replica
is local. ``tests/test_serving_fleet.py`` drills a seeded SIGKILL of one
of three replicas mid-decode under Poisson load and asserts union token
parity against a single-engine reference; ``tests/test_fleet_procs.py``
runs the same drill against real worker processes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.metrics import ReservoirHistogram
from distributed_pytorch_tpu.obs.flight import NULL_FLIGHT_RECORDER
from distributed_pytorch_tpu.obs.registry import MetricsRegistry
from distributed_pytorch_tpu.obs.tracer import NULL_TRACER, _PID_ROUTER
from distributed_pytorch_tpu.serving.admission import (
    AdmissionError,
    EngineDraining,
    QueueFull,
)
from distributed_pytorch_tpu.serving.elastic import (
    SNAPSHOT_VERSION,
    EngineSnapshot,
    RequestSnapshot,
    params_from_doc,
    params_to_doc,
    publish_snapshot,
)
from distributed_pytorch_tpu.serving.engine import RequestStatus
from distributed_pytorch_tpu.serving.journal import (
    Journal,
    JournalState,
    pid_alive,
    read_worker_registry,
    replay_journal,
)
from distributed_pytorch_tpu.serving.mods import Mods
from distributed_pytorch_tpu.serving.replica import (
    LocalReplicaClient,
    ProcessReplicaClient,
    ReplicaClient,
    ReplicaDead,
    ReplicaError,
    ReplicaUnavailable,
)
from distributed_pytorch_tpu.serving.scheduler import SamplingParams

# Per-replica request-id namespace width. Replica k mints ids from
# k * ID_STRIDE, so any mix of replicas' requests can be adopted by one
# survivor without a req_id collision (restore_engine refuses duplicates).
ID_STRIDE = 1_000_000

_HEALTH_VALUE = {"live": 1.0, "draining": 0.5, "dead": 0.0, "removed": -1.0}


class NoLiveReplica(AdmissionError):
    """Every replica is dead, draining, or unreachable — the fleet-level
    twin of :class:`~.admission.EngineDraining`: there is no "elsewhere"
    left to retry."""


def prefix_affinity_key(
    prompt: Sequence[int], page_size: int, pages: int = 1
) -> Optional[str]:
    """The routing key: the content-addressed chain digest of the first
    ``pages`` full pages of ``prompt``, computed with the EXACT
    ``PrefixCache.key_chain`` recurrence — so the key a router derives
    from raw tokens equals the trie address any engine assigns those
    pages. Requests sharing a system prompt share their leading page(s)
    and therefore the key; ``None`` when the prompt has no full page
    (nothing page-aligned to share)."""
    if pages < 1:
        raise ValueError(f"pages must be >= 1, got {pages}")
    n = min(int(pages), len(prompt) // page_size)
    if n == 0:
        return None
    prev = "root"
    for i in range(n):
        chunk = prompt[i * page_size : (i + 1) * page_size]
        prev = hashlib.sha256(
            (prev + "|" + ",".join(str(int(t)) for t in chunk)).encode()
        ).hexdigest()[:16]
    return prev


def _rendezvous(key: str, names: Sequence[str]) -> str:
    """Highest-random-weight hashing: stable key->replica assignment that
    moves only the dead replica's keys when the live set changes."""
    return max(
        names,
        key=lambda name: hashlib.sha256(f"{key}|{name}".encode()).digest(),
    )


@dataclasses.dataclass
class Replica:
    """Route-table entry for one replica. ``state`` transitions:
    ``live -> draining`` (healthz 503 / drain notice; out of admission
    rotation, still stepped), ``-> dead`` (kill / probe threshold /
    liveness deadline; engine abandoned, work failed over), ``-> removed``
    (clean drain handoff; engine closed and leak-checked). Orthogonal to
    ``state``, the client's circuit breaker adds a DEGRADED overlay: a
    live replica whose breaker is open is skipped by routing and the pump
    but keeps its shadows — capacity lost, no work lost."""

    name: str
    client: ReplicaClient
    index: int
    state: str = "live"
    url: Optional[str] = None
    last_ok_s: float = 0.0
    probe_failures: int = 0
    dead_reason: Optional[str] = None
    # Chaos damage the router applies to itself (in-process fault kinds;
    # process kinds deliver real signals through the client instead):
    killed_at: Optional[float] = None
    partitioned_until: Optional[float] = None
    slow_delay_s: float = 0.0

    @property
    def engine(self):
        """The wrapped in-process engine (None for a process replica) —
        the pre-interface surface, kept so local-fleet tests and drills
        reach gauges and trackers exactly as before."""
        return self.client.engine


@dataclasses.dataclass
class ShadowRequest:
    """The router's recovery record for one fleet request: everything
    needed to rebuild a :class:`~.elastic.RequestSnapshot` without ever
    touching a dead engine. ``generated`` holds only COMMITTED tokens
    (observed through ``poll()`` after a completed step) — the fold index
    for the next token is implied by ``len(prompt) + len(generated)``, so
    re-admission regenerates the identical stream."""

    fid: int
    prompt: Tuple[int, ...]
    params: SamplingParams
    metadata: Optional[dict]
    submit_s: float
    replica: str
    req_id: int
    generated: List[int] = dataclasses.field(default_factory=list)
    hedge_replica: Optional[str] = None
    hedge_req_id: Optional[int] = None
    finished: bool = False
    tokens: Optional[List[int]] = None
    failovers: int = 0
    first_token_s: Optional[float] = None
    failover_pending_since: Optional[float] = None
    len_at_failover: int = 0
    # Front-door tenancy and mods ride the shadow so failover and hedging
    # preserve them (the rebuilt RequestSnapshot carries both).
    tenant_id: str = "anon"
    mods: Optional["Mods"] = None
    cancelled: bool = False
    # Streaming high-water mark: tokens the door already handed to the
    # client. Journaled (batched, once per pump) so a restarted router
    # resumes every stream at exactly the next undelivered token.
    delivered: int = 0
    # Fleet-wide trace identity: one string across the original replica,
    # hedge twins, and every failover re-admission. Minted by the front
    # door when present, else by the router at submit.
    trace_id: Optional[str] = None


@dataclasses.dataclass
class AutoscalePolicy:
    """When to grow and shrink the fleet. Scale-out fires on any live
    replica's SLO burn-rate alert (the multi-window monitor from
    ``obs/slo.py`` — page-worthy burn, not a point-in-time threshold);
    scale-in fires when the live replicas' mean ``budget_idle`` waste
    fraction (``obs/goodput.py``) says the fleet is paying for capacity
    the load no longer needs. ``cooldown_rounds`` debounces flapping."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_in_idle_fraction: float = 0.6
    cooldown_rounds: int = 0


class FleetRouter:
    """Routes, probes, fails over, and autoscales N in-process replicas.

    The public surface mirrors one engine — ``submit() -> fleet id``,
    ``step() -> finished fleet ids``, ``poll(fid)``, ``run()``,
    ``close()`` — so callers (and the bench) swap a fleet in where an
    engine was. All replicas must share the snapshot fingerprint
    (page_size, max_seq_len, top_k/top_p, speculative, mesh): failover
    restores refuse mismatched targets, so the router refuses them at
    attach instead of at the worst possible moment.
    """

    def __init__(
        self,
        engines: Sequence = (),
        *,
        engine_factory: Optional[Callable[[], object]] = None,
        replica_factory: Optional[Callable[[], ReplicaClient]] = None,
        affinity_pages: int = 1,
        spill_queue_depth: Optional[int] = None,
        probe_every: int = 4,
        probe_timeout_s: float = 1.0,
        probe_fail_threshold: int = 2,
        liveness_deadline_s: Optional[float] = None,
        max_retries: int = 2,
        retry_backoff_s: float = 0.02,
        hedge_after_s: Optional[float] = None,
        autoscale: Optional[AutoscalePolicy] = None,
        autoscale_every: int = 8,
        id_stride: int = ID_STRIDE,
        clock: Callable[[], float] = time.perf_counter,
        tracer=None,
        journal: Optional[Journal] = None,
        journal_dir: Optional[str] = None,
        journal_segment_records: int = 4096,
        flight=None,
    ):
        self.engine_factory = engine_factory
        # Scale-out factory returning a ready ReplicaClient (either kind:
        # a LocalReplicaClient, or a ProcessReplicaClient whose worker it
        # already spawned). Preferred over engine_factory when both are
        # given — the autoscaler graduates from constructing engines to
        # spawning processes without the policy changing shape.
        self.replica_factory = replica_factory
        # Router-level span lane (Perfetto pid 4): routing decisions,
        # hedge twin links, failover marks. NULL by default — the hot
        # path costs one attribute load when untraced.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.affinity_pages = int(affinity_pages)
        self.spill_queue_depth = spill_queue_depth
        self.probe_every = max(1, int(probe_every))
        self.probe_timeout_s = float(probe_timeout_s)
        self.probe_fail_threshold = max(1, int(probe_fail_threshold))
        self.liveness_deadline_s = liveness_deadline_s
        self.max_retries = max(0, int(max_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        self.hedge_after_s = hedge_after_s
        self.autoscale = autoscale
        self.autoscale_every = max(1, int(autoscale_every))
        self.id_stride = int(id_stride)
        self._clock = clock

        self._replicas: List[Replica] = []
        self._by_name: Dict[str, Replica] = {}
        self._fingerprint: Optional[dict] = None
        self._attached = 0

        self._shadows: Dict[int, ShadowRequest] = {}
        self._by_owner: Dict[Tuple[str, int], int] = {}
        self._next_fid = 0
        self._round = 0
        self._last_scale_round = -(10**9)

        # Durable control plane: the write-ahead journal (see journal.py)
        # records submits/assigns/marks/finishes/replica events as they
        # happen, so FleetRouter.recover can rebuild this router after a
        # SIGKILL. None = journaling off (zero-cost; every hook is one
        # `is not None` check). The flight recorder is the router-side
        # black box — recovery dumps it with the reconciliation summary.
        if journal is None and journal_dir is not None:
            journal = Journal(
                journal_dir, segment_max_records=journal_segment_records
            )
        self.journal = journal
        self.flight = flight if flight is not None else NULL_FLIGHT_RECORDER
        # Batched journal marks: delivered high-waters noted since the
        # last flush, and the committed-length each fid was last journaled
        # at (only growth is written).
        self._dirty_delivered: Dict[int, int] = {}
        self._progress_marked: Dict[int, int] = {}
        #: Reconciliation summary of the recovery that built this router
        #: (None for a first-incarnation router); surfaced in /statusz.
        self.last_recovery: Optional[dict] = None

        self.registry = MetricsRegistry(namespace="fleet")
        self._c = {
            name: self.registry.counter(name)
            for name in (
                "submitted_total",
                "routed_affinity_total",
                "routed_spill_total",
                "routed_least_loaded_total",
                "submit_retries_total",
                "submit_rejected_total",
                "hedges_total",
                "hedge_wins_total",
                "duplicates_suppressed_total",
                "replicas_dead_total",
                "requests_failed_over_total",
                "hedge_promotions_total",
                "drain_handoffs_total",
                "probe_failures_total",
                "scale_outs_total",
                "scale_ins_total",
            )
        }
        self.registry.gauge_fn(
            "replicas_live",
            lambda: sum(1 for r in self._replicas if r.state == "live"),
        )
        self.registry.gauge_fn(
            "replicas_draining",
            lambda: sum(1 for r in self._replicas if r.state == "draining"),
        )
        self.registry.gauge_fn(
            "replicas_dead",
            lambda: sum(1 for r in self._replicas if r.state == "dead"),
        )
        self._detect_gauge = self.registry.gauge(
            "dead_replica_detection_seconds"
        )
        self._detect_hist = ReservoirHistogram(256, seed=7)
        self.registry.reservoir(
            "detection_seconds", lambda: self._detect_hist
        )
        self._failover_ttft = ReservoirHistogram(256, seed=8)
        self.registry.reservoir(
            "failover_ttft_seconds", lambda: self._failover_ttft
        )

        for engine in engines:
            self.add_replica(engine)

    # ------------------------------------------------------------ replicas

    def add_replica(
        self,
        engine,
        *,
        name: Optional[str] = None,
        serve: bool = False,
        index: Optional[int] = None,
    ) -> Replica:
        """Attach one replica — a bare engine (wrapped in a
        :class:`~.replica.LocalReplicaClient`) or a ready
        :class:`~.replica.ReplicaClient` of either kind. Fingerprint-check
        it against the fleet, namespace its request ids
        (``index * id_stride`` — the collision guard for multi-snapshot
        adoption), register its health gauge, and put it in the admission
        rotation. ``serve=True`` starts a local replica's introspection
        server and probes ``/healthz`` over HTTP instead of in-process
        (process replicas always serve)."""
        client = (
            engine if isinstance(engine, ReplicaClient)
            else LocalReplicaClient(engine)
        )
        fp = client.fingerprint()
        if self._fingerprint is None:
            self._fingerprint = fp
        elif fp != self._fingerprint:
            raise ValueError(
                f"replica fingerprint {fp} != fleet {self._fingerprint} — "
                "token-identical failover requires identical geometry and "
                "sampling truncation on every replica"
            )
        # ``index`` pins the attach-order slot across a recovery: the id
        # namespace (index * id_stride) and chaos-plan targeting must mean
        # the same replica in both router incarnations.
        if index is None:
            index = self._attached
        self._attached = max(self._attached, int(index) + 1)
        if name is None:
            name = f"r{index}"
        if name in self._by_name:
            raise ValueError(f"replica name {name!r} already attached")
        client.reserve_ids(index * self.id_stride)
        replica = Replica(
            name=name,
            client=client,
            index=index,
            url=client.start_server() if serve else client.url,
            last_ok_s=self._clock(),
        )
        self._replicas.append(replica)
        self._by_name[name] = replica
        self.registry.gauge_fn(
            f"replica_{name}_health",
            lambda r=replica: _HEALTH_VALUE[r.state],
            help=f"1 live, 0.5 draining, 0 dead, -1 removed ({name})",
        )
        if self.journal is not None:
            self.journal.append_replica(
                "spawn", name,
                kind=client.kind,
                index=index,
                pid=getattr(client, "pid", None),
                control_url=getattr(client, "control_url", None),
                obs_url=getattr(client, "obs_url", None),
                fingerprint=fp,
            )
        return replica

    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    @property
    def page_size(self) -> int:
        if self._fingerprint is None:
            raise RuntimeError("no replica attached yet")
        return self._fingerprint["page_size"]

    def _unreachable(self, replica: Replica) -> bool:
        if replica.killed_at is not None:
            return True
        until = replica.partitioned_until
        return until is not None and self._clock() < until

    def _eligible(self) -> List[Replica]:
        """Replicas in the admission rotation: live, reachable, and
        breaker-CLOSED. Degraded-mode rule: a breaker-open (or probing
        half-open) replica is excluded from rendezvous hashing — its keys
        re-rendezvous onto the survivors exactly as a dead replica's
        would — but its shadows are retained and it is not failed over;
        when the breaker closes, the same keys snap back."""
        return [
            r
            for r in self._replicas
            if r.state == "live"
            and not self._unreachable(r)
            and r.client.breaker.state == "closed"
        ]

    def _load(self, replica: Replica) -> float:
        """Least-loaded signal, read from the replica's own registry
        gauges for a local replica and from the last step response's
        piggybacked load for a process replica (no extra round-trip on
        the routing hot path)."""
        return replica.client.load()

    def _queue_depth(self, replica: Replica) -> float:
        return replica.client.queue_depth()

    # ------------------------------------------------------------- routing

    def _route_order(
        self, key: Optional[str]
    ) -> Tuple[List[Replica], str]:
        """Candidate replicas, best first, plus how the head was chosen
        (``affinity`` / ``spill`` / ``least_loaded``)."""
        eligible = self._eligible()
        by_load = sorted(eligible, key=lambda r: (self._load(r), r.index))
        if key is None or not eligible:
            return by_load, "least_loaded"
        target = self._by_name[
            _rendezvous(key, [r.name for r in eligible])
        ]
        if (
            self.spill_queue_depth is not None
            and self._queue_depth(target) >= self.spill_queue_depth
        ):
            # The affinity replica is backed up past the point where a
            # cached prefix is worth waiting for: spill to load order.
            return by_load, "spill"
        rest = [r for r in by_load if r is not target]
        return [target] + rest, "affinity"

    def submit(
        self,
        prompt: Sequence[int],
        params: Optional[SamplingParams] = None,
        metadata: Optional[dict] = None,
        *,
        tenant_id: str = "anon",
        mods=None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Route one request; returns its FLEET id (stable across
        failover and hedging — engine-level ids are an implementation
        detail the shadow table tracks). Raises
        :class:`~.admission.RequestTooLong` unretried (deterministic),
        retries :class:`~.admission.QueueFull` with backoff and
        :class:`~.admission.EngineDraining` immediately-elsewhere, up to
        ``max_retries`` extra attempts; then re-raises the last error
        (or :class:`NoLiveReplica`). ``trace_id`` is the fleet trace
        identity — passed through from the front door, or minted here
        (``r%06x`` from the fleet-id counter) for bare router traffic —
        and propagated to the owning engine, any hedge twin, and every
        failover re-admission."""
        params = params or SamplingParams()
        prompt = [int(t) for t in prompt]
        minted_here = trace_id is None
        if minted_here:
            trace_id = f"r{self._next_fid:06x}"
        key = prefix_affinity_key(
            prompt, self.page_size, self.affinity_pages
        )
        order, routed_by = self._route_order(key)
        if not order:
            self._c["submit_rejected_total"].inc()
            raise NoLiveReplica("no live replica to admit to")
        last_exc: Optional[Exception] = None
        attempts = 0
        for pos, replica in enumerate(order):
            if attempts > self.max_retries:
                break
            try:
                req_id = replica.client.submit(
                    prompt, params, metadata,
                    tenant_id=tenant_id, mods=mods, trace_id=trace_id,
                )
            except EngineDraining as exc:
                # "Retry ELSEWHERE, now": the draining flag beat our last
                # probe; update the table and go straight to the next.
                if replica.state == "live":
                    replica.state = "draining"
                last_exc = exc
                continue
            except QueueFull as exc:
                # "Retry later": bounded backoff, then the next-best.
                last_exc = exc
                attempts += 1
                self._c["submit_retries_total"].inc()
                if attempts <= self.max_retries:
                    time.sleep(
                        self.retry_backoff_s * (2 ** (attempts - 1))
                    )
                continue
            except ReplicaError:
                # Transport-level: the replica timed out, partitioned, or
                # its process just exited. No admission answer was given
                # (the client's own request-id dedup guarantees a retried
                # submit never double-admits) — go straight to the next
                # candidate; death, if that's what this was, is declared
                # by the next pump round, not mid-submit.
                continue
            fid = self._next_fid
            self._next_fid += 1
            shadow = ShadowRequest(
                fid=fid,
                prompt=tuple(prompt),
                params=params,
                metadata=metadata,
                submit_s=self._clock(),
                replica=replica.name,
                req_id=req_id,
                tenant_id=tenant_id,
                mods=mods,
                trace_id=trace_id,
            )
            self._shadows[fid] = shadow
            self._by_owner[(replica.name, req_id)] = fid
            if self.journal is not None:
                # Journal AFTER the worker admitted (a refused submit
                # needs no recovery) but before the caller learns the
                # fid — the crash window between admit and this append
                # loses only a request the caller never got a handle to.
                self.journal.append_submit(
                    fid,
                    prompt=prompt,
                    params=params_to_doc(params),
                    metadata=metadata,
                    tenant=tenant_id,
                    mods=mods.to_spec() if mods is not None else None,
                    trace_id=trace_id,
                    replica=replica.name,
                    req_id=req_id,
                )
            self._c["submitted_total"].inc()
            routed_via = (
                "affinity" if pos == 0 and routed_by == "affinity"
                else routed_by if routed_by == "spill"
                else "least_loaded"
            )
            if routed_via == "affinity":
                self._c["routed_affinity_total"].inc()
            elif routed_via == "spill":
                self._c["routed_spill_total"].inc()
            else:
                self._c["routed_least_loaded_total"].inc()
            if self.tracer.enabled:
                self.tracer.span_begin(
                    _PID_ROUTER, fid, "route",
                    trace_id=trace_id,
                    replica=replica.name,
                    routed_by=routed_via,
                    tenant=tenant_id,
                )
                # The flow arrow: minted here starts it; handed down from
                # the door, this is the first downstream hop.
                self.tracer.flow(
                    "s" if minted_here else "t", trace_id, _PID_ROUTER
                )
            return fid
        self._c["submit_rejected_total"].inc()
        raise last_exc if last_exc is not None else NoLiveReplica(
            "no live replica accepted the request"
        )

    # ------------------------------------------------------------- serving

    def step(self) -> List[int]:
        """One fleet pump round: apply due chaos faults, step every
        reachable replica once (failing over the ones that die), refresh
        shadows from committed tokens, probe health on schedule, hedge
        stragglers, and autoscale. Returns fleet ids finished this
        round."""
        self._round += 1
        # Flush delivered marks noted since the last round BEFORE chaos
        # can kill this process: the pump boundary is the journal's
        # consistency point, so a router_kill fault finds every token the
        # door handed out already journaled (exactly-once across the
        # crash). The inflight count feeds restart_router_under_load's
        # min_queue condition — and a hard router fault never returns
        # from on_fleet_step.
        self._flush_journal_marks()
        inflight = sum(
            1 for s in self._shadows.values() if not s.finished
        )
        for fault in chaos.on_fleet_step(inflight=inflight):
            self._apply_fault(fault)
        finished: List[int] = []
        for replica in list(self._replicas):
            if replica.state in ("dead", "removed"):
                continue
            now = self._clock()
            if replica.killed_at is not None:
                # First contact with a SIGKILLed process: the step "call"
                # fails instantly, which IS the detection event.
                self._mark_dead(
                    replica, "kill_replica", died_at=replica.killed_at
                )
                continue
            if replica.partitioned_until is not None:
                if now >= replica.partitioned_until:
                    # Healed within the detection window: a blip. The
                    # replica kept its state; nothing diverged.
                    replica.partitioned_until = None
                    replica.probe_failures = 0
                    replica.last_ok_s = now
                else:
                    continue  # unreachable: no step lands
            if replica.slow_delay_s > 0:
                time.sleep(replica.slow_delay_s)
            if replica.client.breaker.state == "open":
                # Degraded mode: a breaker-open replica is not contacted
                # at all (fast-fail costs zero deadline budget). Its
                # shadows stay; the half-open probe below re-admits it.
                continue
            try:
                # When the breaker is half-open this step call IS the
                # probe: success closes the breaker, failure re-opens it.
                step_finished = replica.client.step()
            except chaos.InjectedFault:
                self._mark_dead(replica, "injected_fault", died_at=now)
                continue
            except ReplicaDead as exc:
                died_at = (
                    replica.client.killed_at
                    if replica.client.killed_at is not None
                    else replica.last_ok_s
                )
                self._mark_dead(replica, exc.reason, died_at=died_at)
                continue
            except ReplicaUnavailable:
                # Timed out / partitioned / breaker refused mid-call: no
                # step landed, nothing to finalize. The breaker has done
                # its bookkeeping; a hung replica degrades here instead
                # of being declared dead.
                continue
            replica.last_ok_s = self._clock()
            replica.probe_failures = 0
            for req_id in step_finished:
                fid = self._finalize(replica, req_id)
                if fid is not None:
                    finished.append(fid)
            self._update_shadows(replica)
        if self._round % self.probe_every == 0:
            self.probe_health()
        self._maybe_hedge()
        if (
            self.autoscale is not None
            and self._round % self.autoscale_every == 0
        ):
            self.maybe_autoscale()
        self._flush_journal_marks()
        return finished

    def run(self, max_steps: int = 10_000) -> List[int]:
        """Pump until every submitted request has finished (surviving any
        chaos the armed plan throws). Returns finished fleet ids in
        completion order."""
        finished: List[int] = []
        steps = 0
        while any(not s.finished for s in self._shadows.values()):
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not finish within {max_steps} rounds"
                )
            finished.extend(self.step())
            steps += 1
        return finished

    def poll(self, fid: int) -> RequestStatus:
        """Fleet-level request status. ``generated`` reflects committed
        tokens (the shadow view — what failover would preserve);
        ``preempt_count`` reports the request's failover count."""
        shadow = self._shadows[fid]
        if shadow.finished:
            return RequestStatus(
                req_id=fid,
                state="cancelled" if shadow.cancelled else "finished",
                prompt_len=len(shadow.prompt),
                generated=list(shadow.tokens[len(shadow.prompt):]),
                finished=True,
                preempt_count=shadow.failovers,
            )
        state = "running"
        replica = self._by_name.get(shadow.replica)
        if replica is not None and replica.state not in ("dead", "removed"):
            try:
                state = replica.client.poll(shadow.req_id).state
            except KeyError:
                state = "recovering"
            except ReplicaError:
                pass  # unreachable right now: the shadow view stands
        return RequestStatus(
            req_id=fid,
            state=state,
            prompt_len=len(shadow.prompt),
            generated=list(shadow.generated),
            finished=False,
            preempt_count=shadow.failovers,
        )

    def cancel(self, fid: int) -> None:
        """Client cancellation, fleet half: cancel the owning engine's
        copy AND any hedge twin, freeze the shadow at its committed
        tokens, and mark it cancelled (``poll`` reports the terminal
        state; a later failover will not resurrect it). Idempotent on
        already-finished requests."""
        shadow = self._shadows[fid]
        if shadow.finished:
            return
        targets = [(shadow.replica, shadow.req_id)]
        if shadow.hedge_replica is not None:
            targets.append((shadow.hedge_replica, shadow.hedge_req_id))
        for name, rid in targets:
            replica = self._by_name.get(name)
            if replica is None or replica.state in ("dead", "removed"):
                continue
            try:
                replica.client.cancel(rid)
            except (KeyError, ReplicaError):
                pass
        shadow.finished = True
        shadow.cancelled = True
        shadow.tokens = list(shadow.prompt) + list(shadow.generated)
        if self.journal is not None:
            self.journal.append_cancel(fid)

    def note_delivered(self, fid: int, n: int) -> None:
        """The door's streaming high-water mark for fleet request ``fid``:
        ``n`` tokens have been handed to the client. Recorded on the
        shadow and queued for the next batched journal flush; propagated
        to the owning in-process engine (when there is one) so drain
        snapshots carry it too."""
        shadow = self._shadows.get(fid)
        if shadow is None:
            return
        n = int(n)
        if n > shadow.delivered:
            shadow.delivered = n
            if self.journal is not None:
                self._dirty_delivered[fid] = n
        replica = self._by_name.get(shadow.replica)
        if (
            replica is not None
            and replica.engine is not None
            and not shadow.finished
        ):
            req = replica.engine.requests.get(shadow.req_id)
            if req is not None:
                req.delivered = n

    def _flush_journal_marks(self) -> None:
        """Write the batched deliver/progress high-water marks. Called at
        the pump boundaries — once per router step, not per token — so
        journaling costs two records a round regardless of stream count."""
        if self.journal is None:
            return
        if self._dirty_delivered:
            self.journal.append_deliver(self._dirty_delivered)
            self._dirty_delivered = {}
        marks: Dict[int, int] = {}
        for fid, shadow in self._shadows.items():
            if shadow.finished:
                self._progress_marked.pop(fid, None)
                continue
            n = len(shadow.generated)
            if n > self._progress_marked.get(fid, 0):
                marks[fid] = n
                self._progress_marked[fid] = n
        if marks:
            self.journal.append_progress(marks)

    def _finalize(self, replica: Replica, req_id: int) -> Optional[int]:
        """One engine-level completion. The dedup rule lives here: the
        FIRST copy to finish records the result under the fleet id and
        cancels its twin; a twin finishing anyway is suppressed."""
        fid = self._by_owner.get((replica.name, req_id))
        if fid is None:
            return None
        try:
            status = replica.client.poll(req_id)
        except (KeyError, ReplicaError):
            # The completion is real (the replica reported the id) but
            # its status is briefly unreadable; the next round's
            # re-delivery (process clients ack at-least-once) retries.
            return None
        if status.state == "cancelled":
            return None  # a cancelled twin retires through finished ids too
        shadow = self._shadows[fid]
        if shadow.finished:
            self._c["duplicates_suppressed_total"].inc()
            return None
        shadow.finished = True
        shadow.generated = list(status.generated)
        shadow.tokens = list(shadow.prompt) + list(status.generated)
        if self.journal is not None:
            # Finish records carry the FULL generated list: a finished-
            # but-undelivered tail must drain after recovery even if this
            # worker is gone by then (no engine can regenerate it once
            # the journal forgets it).
            self.journal.append_finish(fid, status.generated)
        if shadow.first_token_s is None and status.generated:
            shadow.first_token_s = self._clock()
        won_by_hedge = (replica.name, req_id) == (
            shadow.hedge_replica,
            shadow.hedge_req_id,
        )
        twin: Optional[Tuple[str, int]] = None
        if won_by_hedge:
            twin = (shadow.replica, shadow.req_id)
            self._c["hedge_wins_total"].inc()
        elif shadow.hedge_replica is not None:
            twin = (shadow.hedge_replica, shadow.hedge_req_id)
        if twin is not None:
            other = self._by_name.get(twin[0])
            if other is not None and other.state not in ("dead", "removed"):
                try:
                    other.client.cancel(twin[1])
                except ReplicaError:
                    pass  # twin replica unreachable: its copy is moot
        if self.tracer.enabled:
            self.tracer.span_end(
                _PID_ROUTER, fid, "route",
                trace_id=shadow.trace_id,
                tokens=len(shadow.generated),
                failovers=shadow.failovers,
                won_by_hedge=won_by_hedge,
            )
        return fid

    def _update_shadows(self, replica: Replica) -> None:
        """Refresh committed-token shadows from ``replica`` after a step.
        This is the failover state: tokens recorded here survive the
        replica; anything newer is re-generated identically."""
        now = self._clock()
        for shadow in self._shadows.values():
            if shadow.finished:
                continue
            if shadow.replica == replica.name:
                req_id = shadow.req_id
            elif shadow.hedge_replica == replica.name:
                req_id = shadow.hedge_req_id
            else:
                continue
            try:
                status = replica.client.poll(req_id)
            except (KeyError, ReplicaError):
                continue
            if len(status.generated) > len(shadow.generated):
                shadow.generated = list(status.generated)
                if shadow.first_token_s is None:
                    shadow.first_token_s = now
                if (
                    shadow.failover_pending_since is not None
                    and len(shadow.generated) > shadow.len_at_failover
                ):
                    self._failover_ttft.record(
                        now - shadow.failover_pending_since
                    )
                    shadow.failover_pending_since = None

    # ------------------------------------------------------ health / death

    def probe_health(self) -> None:
        """One probe sweep. Consecutive failures past the threshold — or
        a liveness deadline expiring on a replica that holds work but
        stopped completing steps — declare death and trigger failover.
        A 503 *draining* verdict keeps the replica in the table (no
        premature eviction): it leaves the admission rotation but its
        in-flight requests keep streaming."""
        for replica in list(self._replicas):
            if replica.state in ("dead", "removed"):
                continue
            now = self._clock()
            if (
                self.liveness_deadline_s is not None
                and self._has_work(replica)
                and now - replica.last_ok_s > self.liveness_deadline_s
            ):
                self._mark_dead(
                    replica, "liveness_deadline", died_at=replica.last_ok_s
                )
                continue
            verdict: Optional[str] = None
            if self._unreachable(replica):
                pass  # probe cannot land; counts as a failure below
            else:
                try:
                    verdict = replica.client.health(
                        timeout_s=self.probe_timeout_s
                    )
                except ReplicaDead as exc:
                    died_at = (
                        replica.client.killed_at
                        if replica.client.killed_at is not None
                        else replica.last_ok_s
                    )
                    self._mark_dead(replica, exc.reason, died_at=died_at)
                    continue
                except Exception:
                    verdict = None
            if verdict is None:
                replica.probe_failures += 1
                self._c["probe_failures_total"].inc()
                if (
                    not replica.client.is_process
                    and replica.probe_failures >= self.probe_fail_threshold
                ):
                    # In-process replicas have no other death signal, so
                    # the probe threshold declares it. A PROCESS replica
                    # that merely stops answering is DEGRADED, not dead —
                    # its breaker excludes it, its shadows stay — because
                    # the unambiguous death signal (the process exiting)
                    # is observable directly; only the liveness deadline
                    # above escalates a silent replica that holds work.
                    self._mark_dead(
                        replica, "probe_failures", died_at=replica.last_ok_s
                    )
                continue
            replica.probe_failures = 0
            replica.last_ok_s = now
            if verdict == "draining" and replica.state == "live":
                replica.state = "draining"
            elif verdict == "live" and replica.state == "draining":
                replica.state = "live"  # drain was cancelled / reopened
            elif verdict == "closed":
                # A closed engine finishes nothing: recover its work.
                if self._has_work(replica):
                    self._mark_dead(replica, "closed", died_at=now)
                else:
                    replica.state = "removed"

    def _has_work(self, replica: Replica) -> bool:
        return any(
            not s.finished
            and replica.name in (s.replica, s.hedge_replica)
            for s in self._shadows.values()
        )

    def _mark_dead(
        self, replica: Replica, reason: str, *, died_at: float
    ) -> None:
        """Declare ``replica`` dead, record detection latency (death to
        declaration), and fail its work over. The engine object is
        abandoned exactly as a SIGKILLed process abandons its memory —
        nothing is read from it again."""
        if replica.state in ("dead", "removed"):
            return
        now = self._clock()
        replica.state = "dead"
        replica.dead_reason = reason
        detection = max(0.0, now - died_at)
        self._detect_gauge.set(detection)
        self._detect_hist.record(detection)
        self._c["replicas_dead_total"].inc()
        print(
            f"[fleet] replica {replica.name} dead ({reason}); "
            f"detection {detection * 1e3:.1f}ms",
            flush=True,
        )
        if self.journal is not None:
            # Journaled deaths are final: recovery never re-adopts a
            # replica this incarnation already declared dead, even if
            # its registry entry still points at a live pid.
            self.journal.append_replica("dead", replica.name, reason=reason)
        self.flight.record(
            "replica_dead", name=replica.name, reason=reason,
            detection_s=detection,
        )
        self._failover_from(replica)

    def _failover_from(self, dead: Replica) -> None:
        """Token-identical failover: promote hedge twins where one exists
        (an identical stream already running elsewhere), re-admit the
        rest through ``restore_engine``'s re-prefill path from the shadow
        snapshots — grouped by the same affinity routing as fresh
        traffic, so shared prefixes regroup on the survivor that caches
        them."""
        moved: List[ShadowRequest] = []
        for shadow in self._shadows.values():
            if shadow.finished:
                continue
            if shadow.hedge_replica == dead.name:
                self._by_owner.pop((dead.name, shadow.hedge_req_id), None)
                shadow.hedge_replica = None
                shadow.hedge_req_id = None
                continue
            if shadow.replica != dead.name:
                continue
            self._by_owner.pop((dead.name, shadow.req_id), None)
            if shadow.hedge_replica is not None:
                hedge = self._by_name.get(shadow.hedge_replica)
                if hedge is not None and hedge.state in (
                    "live",
                    "draining",
                ):
                    shadow.replica = shadow.hedge_replica
                    shadow.req_id = shadow.hedge_req_id
                    shadow.hedge_replica = None
                    shadow.hedge_req_id = None
                    self._c["hedge_promotions_total"].inc()
                    if self.journal is not None:
                        self.journal.append_assign(
                            shadow.fid, shadow.replica, shadow.req_id
                        )
                    continue
                shadow.hedge_replica = None
                shadow.hedge_req_id = None
            moved.append(shadow)
        if not moved:
            return
        self._rehome(moved, from_name=dead.name)

    def _rehome(
        self, moved: List[ShadowRequest], *, from_name: str
    ) -> None:
        """Re-admit ``moved`` shadows on live replicas through
        ``restore_engine``'s re-prefill path, grouped by the same
        affinity routing as fresh traffic."""
        if not moved:
            return
        now = self._clock()
        groups: Dict[str, List[ShadowRequest]] = {}
        for shadow in moved:
            key = prefix_affinity_key(
                shadow.prompt, self.page_size, self.affinity_pages
            )
            order, _ = self._route_order(key)
            if not order:
                raise NoLiveReplica(
                    f"replica {from_name} died holding {len(moved)} "
                    "requests and no live replica remains to adopt them"
                )
            groups.setdefault(order[0].name, []).append(shadow)
        for name, shadows in groups.items():
            target = self._by_name[name]
            if self.tracer.enabled:
                # Mark the failover BEFORE the restore lands: the
                # waterfall retro-assigns the silence since the victim's
                # last sign of life to ``failover_gap`` at this event.
                for shadow in shadows:
                    self.tracer.span_event(
                        _PID_ROUTER, shadow.fid, "failover",
                        trace_id=shadow.trace_id,
                        from_replica=from_name,
                        to_replica=name,
                        committed_tokens=len(shadow.generated),
                    )
            target.client.restore(self._snapshot_for(shadows, now))
            for shadow in shadows:
                shadow.replica = name
                self._by_owner[(name, shadow.req_id)] = shadow.fid
                shadow.failovers += 1
                shadow.failover_pending_since = now
                shadow.len_at_failover = len(shadow.generated)
                if self.journal is not None:
                    self.journal.append_assign(
                        shadow.fid, name, shadow.req_id
                    )
            self._c["requests_failed_over_total"].inc(len(shadows))

    def _snapshot_for(
        self, shadows: Sequence[ShadowRequest], now: float
    ) -> EngineSnapshot:
        """Build an :class:`~.elastic.EngineSnapshot` purely from router
        shadows — the dead engine contributes nothing. ``next_id=0`` so
        adoption never moves the survivor's id counter (per-replica
        namespacing already guarantees uniqueness)."""
        fp = self._fingerprint
        recs = []
        for shadow in sorted(shadows, key=lambda s: s.req_id):
            p = shadow.params
            recs.append(
                RequestSnapshot(
                    req_id=shadow.req_id,
                    prompt=shadow.prompt,
                    generated=tuple(shadow.generated),
                    max_new_tokens=p.max_new_tokens,
                    temperature=p.temperature,
                    seed=p.seed,
                    stop_token=p.stop_token,
                    deadline_s=p.deadline_s,
                    metadata=shadow.metadata,
                    preempt_count=0,
                    age_s=max(0.0, now - shadow.submit_s),
                    ttft_s=(
                        shadow.first_token_s - shadow.submit_s
                        if shadow.first_token_s is not None
                        else None
                    ),
                    # Upper bound on KV lost with the replica: everything
                    # committed must re-prefill (goodput charges it to
                    # restore_reprefill; a prefix-cache hit shrinks it).
                    kv_committed=len(shadow.prompt) + len(shadow.generated),
                    trie_keys=(),
                    tenant_id=shadow.tenant_id,
                    delivered=min(shadow.delivered, len(shadow.generated)),
                    stop_sequences=tuple(
                        tuple(int(t) for t in seq)
                        for seq in p.stop_sequences
                    ),
                    mods=(
                        shadow.mods.to_spec()
                        if shadow.mods is not None
                        else None
                    ),
                    trace_id=shadow.trace_id,
                )
            )
        return EngineSnapshot(
            version=SNAPSHOT_VERSION,
            page_size=fp["page_size"],
            max_seq_len=fp["max_seq_len"],
            top_k=fp["top_k"],
            top_p=fp["top_p"],
            speculative=fp["speculative"],
            next_id=0,
            requests=tuple(recs),
            mesh=fp["mesh"],
            kv=fp.get("kv", "fp"),
        )

    # ------------------------------------------------------------- hedging

    def _maybe_hedge(self) -> None:
        """Tail-latency hedging: a request with no first token after
        ``hedge_after_s`` gets an identical twin (same seed — determinism
        makes the copies interchangeable) on the least-loaded OTHER live
        replica. First to finish wins; see :meth:`_finalize` for dedup."""
        if self.hedge_after_s is None:
            return
        now = self._clock()
        for shadow in self._shadows.values():
            if (
                shadow.finished
                or shadow.hedge_replica is not None
                or shadow.first_token_s is not None
                or now - shadow.submit_s < self.hedge_after_s
            ):
                continue
            others = [
                r for r in self._eligible() if r.name != shadow.replica
            ]
            if not others:
                continue
            target = min(others, key=lambda r: (self._load(r), r.index))
            try:
                req_id = target.client.submit(
                    list(shadow.prompt), shadow.params, shadow.metadata,
                    tenant_id=shadow.tenant_id, mods=shadow.mods,
                    trace_id=shadow.trace_id,
                )
            except (AdmissionError, ReplicaError):
                continue
            shadow.hedge_replica = target.name
            shadow.hedge_req_id = req_id
            self._by_owner[(target.name, req_id)] = shadow.fid
            self._c["hedges_total"].inc()
            if self.tracer.enabled:
                # The twin shares the trace_id: its engine span joins the
                # same waterfall, linked by this mark and the flow arrow
                # the twin's submit emitted on the target engine.
                self.tracer.span_event(
                    _PID_ROUTER, shadow.fid, "hedge",
                    trace_id=shadow.trace_id,
                    twin_replica=target.name,
                    twin_req_id=req_id,
                )

    # ------------------------------------------------- drain / autoscaling

    def drain_replica(
        self, name: str, *, store=None, key: Optional[str] = None
    ) -> int:
        """The SIGTERM-with-notice handoff, fleet half: drain ``name``
        (front door closed, in-flight step lands, snapshot taken), move
        its queue to the least-loaded live survivor — through the elastic
        KV store via :func:`publish_snapshot`/:func:`adopt_snapshot` when
        ``store`` is given, else a direct restore — then close and retire
        the engine (leak-checked). Zero token divergence: the snapshot
        path is the same re-prefill machinery as failover, minus the lost
        in-flight step (a clean drain finishes it first). Returns the
        number of requests handed off."""
        replica = self._by_name[name]
        if replica.state in ("dead", "removed"):
            raise ValueError(f"replica {name} is {replica.state}")
        replica.state = "draining"
        # Hedge twins hosted here are redundant copies, not primary work:
        # cancel them rather than migrating a duplicate.
        for shadow in self._shadows.values():
            if not shadow.finished and shadow.hedge_replica == name:
                replica.client.cancel(shadow.hedge_req_id)
                self._by_owner.pop((name, shadow.hedge_req_id), None)
                shadow.hedge_replica = None
                shadow.hedge_req_id = None
        snap = replica.client.drain(reason="fleet_drain")
        # finish_inflight may have completed requests whose final readback
        # was in flight: deliver them before re-homing the remainder.
        for shadow in list(self._shadows.values()):
            if shadow.finished or shadow.replica != name:
                continue
            if replica.client.poll(shadow.req_id).finished:
                self._finalize(replica, shadow.req_id)
        if snap.requests:
            survivors = [
                r for r in self._eligible() if r.name != name
            ]
            if not survivors:
                raise NoLiveReplica(
                    f"cannot drain {name}: {len(snap.requests)} requests "
                    "and no live survivor to adopt them"
                )
            target = min(survivors, key=lambda r: (self._load(r), r.index))
            if store is not None:
                handoff_key = key or f"fleet/handoff/{name}"
                publish_snapshot(store, handoff_key, snap)
                target.client.adopt(store, handoff_key)
            else:
                target.client.restore(snap)
            for shadow in self._shadows.values():
                if shadow.finished or shadow.replica != name:
                    continue
                self._by_owner.pop((name, shadow.req_id), None)
                shadow.replica = target.name
                self._by_owner[(target.name, shadow.req_id)] = shadow.fid
                if self.journal is not None:
                    self.journal.append_assign(
                        shadow.fid, target.name, shadow.req_id
                    )
        replica.client.close()
        replica.state = "removed"
        if self.journal is not None:
            self.journal.append_replica("dead", name, reason="drained")
        self._c["drain_handoffs_total"].inc()
        return len(snap.requests)

    def maybe_autoscale(self) -> Optional[Tuple[str, str]]:
        """One autoscaler evaluation (also called from :meth:`step` every
        ``autoscale_every`` rounds). Returns ``("out", name)`` /
        ``("in", name)`` when it acted, else None."""
        policy = self.autoscale
        if policy is None:
            return None
        if self._round - self._last_scale_round < policy.cooldown_rounds:
            return None
        live = [r for r in self._replicas if r.state == "live"]
        # Scale OUT: any live replica's SLO burn-rate alert is firing.
        firing = []
        for replica in live:
            firing.extend(replica.client.slo_firing())
        factory = self.replica_factory or self.engine_factory
        if (
            firing
            and len(live) < policy.max_replicas
            and factory is not None
        ):
            replica = self.add_replica(factory())
            self._c["scale_outs_total"].inc()
            self._last_scale_round = self._round
            print(
                f"[fleet] scale-out -> {replica.name} "
                f"(slo firing: {sorted(set(firing))})",
                flush=True,
            )
            return ("out", replica.name)
        # Scale IN: the fleet is paying for idle budget.
        if len(live) > policy.min_replicas:
            idle_fractions = []
            for replica in live:
                fraction = replica.client.idle_fraction()
                if fraction is not None:
                    idle_fractions.append(fraction)
            if idle_fractions and (
                sum(idle_fractions) / len(idle_fractions)
                >= policy.scale_in_idle_fraction
            ):
                victim = min(
                    live, key=lambda r: (self._load(r), -r.index)
                )
                self.drain_replica(victim.name)
                self._c["scale_ins_total"].inc()
                self._last_scale_round = self._round
                print(
                    f"[fleet] scale-in <- {victim.name} (mean budget-idle "
                    f"{sum(idle_fractions) / len(idle_fractions):.0%})",
                    flush=True,
                )
                return ("in", victim.name)
        return None

    # --------------------------------------------------------------- chaos

    def _apply_fault(self, fault) -> None:
        """Apply one declared fleet fault (see ``chaos._FLEET_KINDS``).
        ``fault.replica`` indexes attach order; a fault naming a replica
        that is already dead/removed (or never attached) is a no-op —
        the drill's kill landed on an empty chamber."""
        if fault.replica is None or fault.replica >= len(self._replicas):
            return
        replica = self._replicas[fault.replica]
        if replica.state in ("dead", "removed"):
            return
        now = self._clock()
        is_proc = replica.client.is_process
        if fault.kind in ("kill_replica", "kill_replica_process"):
            if is_proc:
                # REAL damage: SIGKILL the worker. Detection stays the
                # router's job — the next contact fails, exactly like the
                # in-process twin's first touch of killed_at.
                replica.client.kill(chaos_kind=fault.kind)
            else:
                replica.killed_at = now
        elif fault.kind in ("partition_replica",
                            "partition_replica_process"):
            if is_proc and fault.kind == "partition_replica_process":
                replica.client.partition(fault.duration)
            else:
                replica.partitioned_until = (
                    now + fault.duration
                    if fault.duration > 0 else float("inf")
                )
        elif fault.kind == "hang_replica_process":
            if is_proc:
                # SIGSTOP: sockets stay open, reads stall to the call
                # deadline — the fault the circuit breaker exists for.
                replica.client.suspend(fault.duration)
            else:
                # Nearest in-process semantics: unreachable for the
                # window (an in-process engine cannot "hang" mid-call).
                replica.partitioned_until = (
                    now + fault.duration
                    if fault.duration > 0 else float("inf")
                )
        elif fault.kind == "slow_replica":
            replica.slow_delay_s = max(0.0, float(fault.duration))

    # --------------------------------------------------------------- admin

    def fleet_snapshot(self, include_dead: bool = False) -> dict:
        """Exact cross-replica metrics union: the router's own registry
        merged with every attached replica's — same payload shape as
        ``MetricsRegistry.merge_remote`` over served replicas."""
        snaps = [self.registry.snapshot(include_state=True)]
        for replica in self._replicas:
            if replica.state == "removed":
                continue
            if replica.state == "dead" and not include_dead:
                continue
            snap = replica.client.metrics_snapshot()
            if snap is not None:
                snaps.append(snap)
        return MetricsRegistry.merge(snaps)

    def trace_documents(self) -> List[dict]:
        """Every Perfetto document the fleet can produce: the router's own
        lane plus each attached replica's — INCLUDING dead replicas (the
        in-process tracer object survives the simulated SIGKILL; a real
        deployment would substitute the scraped ``/trace`` or the
        postmortem replay). This is what ``merge_traces`` assembles into
        the one fleet trace where a failed-over request reads as a single
        ``trace_id`` across door, router, victim, and survivor."""
        docs: List[dict] = []
        if self.tracer.enabled:
            docs.append(self.tracer.to_perfetto())
        for replica in self._replicas:
            if replica.state == "removed":
                continue
            docs.extend(replica.client.trace_documents())
        return docs

    def describe(self) -> dict:
        """The fleet ``/statusz`` block: route table + shadow census."""
        shadows = list(self._shadows.values())
        return {
            "round": self._round,
            "replicas": [
                {
                    "name": r.name,
                    "state": r.state,
                    "kind": r.client.kind,
                    "breaker": r.client.breaker.state,
                    "index": r.index,
                    "url": r.url,
                    "dead_reason": r.dead_reason,
                    "load": (
                        self._load(r)
                        if r.state in ("live", "draining")
                        else None
                    ),
                    "owned": sum(
                        1
                        for s in shadows
                        if not s.finished
                        and r.name in (s.replica, s.hedge_replica)
                    ),
                }
                for r in self._replicas
            ],
            "requests": {
                "total": len(shadows),
                "finished": sum(1 for s in shadows if s.finished),
                "failed_over": sum(1 for s in shadows if s.failovers),
                "hedged": sum(
                    1
                    for s in shadows
                    if s.hedge_replica is not None
                ),
            },
            "recovery": self.last_recovery,
        }

    # ----------------------------------------------------------- recovery

    def _journal_state(self) -> JournalState:
        """Condense this router's live truth into a
        :class:`~.journal.JournalState` — the seed for the post-recovery
        journal's compaction base (the old incarnation's segments are
        fully captured by it and deleted)."""
        state = JournalState()
        for replica in self._replicas:
            if replica.state not in ("live", "draining"):
                continue
            client = replica.client
            state.replicas[replica.name] = {
                "kind": client.kind,
                "index": replica.index,
                "pid": getattr(client, "pid", None),
                "control_url": getattr(client, "control_url", None),
                "obs_url": getattr(client, "obs_url", None),
                "fingerprint": self._fingerprint,
                "alive": True,
            }
        for fid, shadow in self._shadows.items():
            state.requests[fid] = {
                "prompt": list(shadow.prompt),
                "params": params_to_doc(shadow.params),
                "metadata": shadow.metadata,
                "tenant": shadow.tenant_id,
                "mods": (
                    shadow.mods.to_spec()
                    if shadow.mods is not None
                    else None
                ),
                "trace_id": shadow.trace_id,
                "replica": shadow.replica,
                "req_id": shadow.req_id,
                "delivered": int(shadow.delivered),
                "committed": len(shadow.generated),
                "finished": shadow.finished,
                "gen": list(shadow.generated) if shadow.finished else None,
                "cancelled": shadow.cancelled,
            }
        state.next_fid = self._next_fid
        return state

    @classmethod
    def recover(
        cls,
        journal_dir: str,
        *,
        replicas: Optional[Dict[str, ReplicaClient]] = None,
        attach_kwargs: Optional[dict] = None,
        segment_max_records: int = 4096,
        **kwargs,
    ) -> "FleetRouter":
        """Rebuild a router after a crash from its write-ahead journal.

        Reconciliation rules (in order):

        - **The journal wins on request existence.** Every journaled
          open request gets a shadow; nothing a worker reports that the
          journal never saw is resurrected.
        - **The worker wins on committed tokens.** Each unfinished
          request's owning worker is polled; its engine state replaces
          the journal's progress marks (which are a lower bound — the
          batched flush lags by up to one pump round).
        - **Journal-dead replicas are never re-adopted**, even if their
          registry entry still points at a live pid (PID reuse, or a
          worker this incarnation already failed over away from).

        Workers come from ``replicas`` (name -> ready client, the
        in-process drill path) or the run-dir worker registry
        (``ProcessReplicaClient.attach`` on live pids — the real-crash
        path). Orphaned requests whose worker is gone are re-admitted
        through the same token-identical re-prefill machinery as
        failover; finished-but-undelivered tails drain straight from the
        journal (no engine needed). Streams resume at the journaled
        delivered high-water, so across the restart every client sees
        each token exactly once.

        ``kwargs`` are forwarded to the constructor and must not include
        ``journal``/``journal_dir`` — the recovered router always writes
        a fresh compacted journal into ``journal_dir``.
        """
        if "journal" in kwargs or "journal_dir" in kwargs:
            raise ValueError(
                "recover() owns the journal; pass journal_dir positionally"
            )
        state = replay_journal(journal_dir)
        router = cls(**kwargs)
        registry = read_worker_registry(journal_dir)
        provided = dict(replicas or {})
        summary: dict = {
            "re_adopted": 0,
            "re_admitted": 0,
            "lost": 0,
            "finished_tails": 0,
            "re_adopted_workers": [],
            "lost_workers": [],
            "corrupt_segments": list(state.corrupt),
            "records_replayed": state.records,
        }
        for name, doc in sorted(
            state.replicas.items(),
            key=lambda kv: (kv[1].get("index") or 0, kv[0]),
        ):
            if not doc.get("alive"):
                continue  # journal-dead: never re-adopt
            client = provided.pop(name, None)
            if client is None:
                entry = registry.get(name)
                if entry is None or not pid_alive(entry.get("pid")):
                    summary["lost_workers"].append(name)
                    continue
                try:
                    client = ProcessReplicaClient.attach(
                        entry, run_dir=journal_dir,
                        **(attach_kwargs or {}),
                    )
                except (ReplicaError, ValueError, KeyError, OSError) as exc:
                    print(
                        f"[fleet] recovery: worker {name} not "
                        f"re-adoptable ({exc})",
                        flush=True,
                    )
                    summary["lost_workers"].append(name)
                    continue
            router.add_replica(client, name=name, index=doc.get("index"))
            summary["re_adopted_workers"].append(name)
        router._next_fid = max(router._next_fid, state.next_fid)
        now = router._clock()
        open_docs = state.open_requests()
        orphans: List[ShadowRequest] = []
        for fid in sorted(open_docs):
            doc = open_docs[fid]
            shadow = ShadowRequest(
                fid=fid,
                prompt=tuple(int(t) for t in doc["prompt"]),
                params=params_from_doc(doc["params"]),
                metadata=doc["metadata"],
                submit_s=now,
                replica=doc.get("replica") or "",
                req_id=(
                    int(doc["req_id"])
                    if doc.get("req_id") is not None
                    else fid
                ),
                tenant_id=doc.get("tenant") or "anon",
                mods=(
                    Mods.from_spec(doc["mods"])
                    if doc.get("mods")
                    else None
                ),
                trace_id=doc.get("trace_id"),
                delivered=int(doc.get("delivered", 0)),
            )
            router._shadows[fid] = shadow
            if doc["finished"]:
                # Finished-but-undelivered tail: the finish record holds
                # the full stream, so it drains with no engine at all.
                shadow.finished = True
                shadow.generated = list(doc["gen"] or [])
                shadow.tokens = (
                    list(shadow.prompt) + list(shadow.generated)
                )
                summary["finished_tails"] += 1
                continue
            replica = router._by_name.get(shadow.replica)
            adopted = False
            if replica is not None and replica.state == "live":
                try:
                    status = replica.client.poll(shadow.req_id)
                except (KeyError, ReplicaError):
                    status = None
                if status is not None:
                    # Worker wins on committed tokens.
                    adopted = True
                    shadow.generated = list(status.generated)
                    router._by_owner[(replica.name, shadow.req_id)] = fid
                    if status.finished:
                        shadow.finished = True
                        shadow.tokens = (
                            list(shadow.prompt) + list(shadow.generated)
                        )
                    summary["re_adopted"] += 1
            if not adopted:
                # Dead worker: journal progress marks are only a lower
                # bound, and regeneration is token-identical from the
                # fold index — re-admit from scratch.
                shadow.generated = []
                orphans.append(shadow)
        if orphans:
            if router._eligible():
                router._rehome(orphans, from_name="<crashed router>")
                summary["re_admitted"] = len(orphans)
            else:
                for shadow in orphans:
                    shadow.finished = True
                    shadow.cancelled = True
                    shadow.tokens = (
                        list(shadow.prompt) + list(shadow.generated)
                    )
                summary["lost"] = len(orphans)
        if router.tracer.enabled:
            # Re-open the router span for every in-flight request so the
            # old incarnation's trace ids thread through this one and
            # _finalize's span_end balances.
            for shadow in router._shadows.values():
                if shadow.finished:
                    continue
                router.tracer.span_begin(
                    _PID_ROUTER, shadow.fid, "route",
                    trace_id=shadow.trace_id,
                    replica=shadow.replica,
                    routed_by="recovered",
                    tenant=shadow.tenant_id,
                )
        router.last_recovery = summary
        router.flight.record(
            "router_recover",
            re_adopted=summary["re_adopted"],
            re_admitted=summary["re_admitted"],
            lost=summary["lost"],
            finished_tails=summary["finished_tails"],
            workers=list(summary["re_adopted_workers"]),
        )
        if router.flight.enabled:
            router.flight.dump(
                "router_recovery",
                path=os.path.join(
                    journal_dir, "router_recovery_flight.json"
                ),
                extra={"reconciliation": summary},
            )
        # The recovered truth becomes the new journal's compaction base;
        # the dead incarnation's segments are deleted once captured.
        router.journal = Journal(
            journal_dir,
            segment_max_records=segment_max_records,
            state=router._journal_state(),
        )
        router._progress_marked = {
            fid: len(s.generated)
            for fid, s in router._shadows.items()
            if not s.finished
        }
        router.journal.append_recovery(summary)
        return router

    def close(self) -> None:
        """Close every live/draining replica (leak-checked, like a single
        engine — a process replica runs its leak asserts INSIDE the
        worker and a failure surfaces here as a
        :class:`~.replica.ReplicaError`). Dead replicas are NOT closed —
        a SIGKILLed process never runs its destructors; survivors are the
        ones whose quiescence the drill asserts — but their residue
        (router-side server threads, child pipes, an unreaped zombie) is
        torn down via :meth:`~.replica.ReplicaClient.abandon`."""
        if self.journal is not None:
            self._flush_journal_marks()
        for replica in self._replicas:
            if replica.state in ("live", "draining"):
                replica.client.close()
                replica.state = "removed"
            elif replica.state == "dead":
                replica.client.abandon()
        if self.journal is not None:
            self.journal.close()


__all__ = [
    "AutoscalePolicy",
    "FleetRouter",
    "ID_STRIDE",
    "NoLiveReplica",
    "Replica",
    "ShadowRequest",
    "prefix_affinity_key",
]
