"""The continuous-batching inference engine: host orchestration around a
fixed-shape jit decode step.

``submit(prompt, params) -> request_id`` / ``step()`` / ``poll(request_id)``.
Every ``step()``:

1. asks the :class:`~.scheduler.Scheduler` for a plan (admission with
   prefix-cache lookup, copy-on-write page copies, chunked prefill under
   the token budget, the batched decode set, preemption);
2. executes the CoW copies — one compiled page-copy program per shared page
   a writer is about to extend;
3. executes the prefill chunks — each a ``[1, C]`` jit call writing K/V into
   the request's pages (logits dead-code-eliminated), compiled once per
   power-of-two chunk size, starting at the first token the prefix cache
   did not already cover;
4. dispatches ONE batched decode step over all ``max_slots`` slots —
   inactive slots are padded (null block table, length 0) and masked, so
   the decode program compiles exactly once regardless of which requests
   are live;
5. resolves the PREVIOUS step's decode readback (overlapped stepping: the
   blocking ``np.asarray`` lands while the device chews on the decode just
   dispatched), retires finished requests, records TTFT/TPOT/e2e.

Overlap mechanics: the sampled-token vector from step N is fed back into
step N+1 as a device-resident ``prev`` argument — each slot's input token
is ``where(use_prev, prev[slot], host_token)`` — so a decoding sequence's
next input never round-trips through the host. Host bookkeeping tracks the
dispatch with a PENDING placeholder that :meth:`Scheduler.resolve_decoded`
fills in one step later. ``overlap=False`` resolves synchronously (same
compiled program; ``use_prev`` is simply always 0), which is also the
behavior under a scheduler that never redispatches an unresolved slot.

The decode math is :func:`~distributed_pytorch_tpu.generation
.decode_token_step` — the SAME single-token step ``generate()``'s offline
loop runs — against the paged cache, so continuous batching is
token-for-token identical to offline decode (pinned by
``tests/test_serving.py`` on CPU), with or without prefix caching and
overlap.

Sampling determinism: each request gets ``PRNGKey(seed)`` and token i is
drawn with ``fold_in(key, i)`` — independent of batch composition, slot
assignment, and preemption, so a preempted-then-resumed request reproduces
its exact stream. Under overlap the fold index is the DISPATCH count
(``n_issued``), which equals the generated count at the same point of the
synchronous schedule.

Speculative serving (``draft_model``): each scheduled decode becomes one
draft+verify ROUND — gamma single-token draft steps propose a chunk, one
gamma-wide chunked target forward verifies it, and every row emits its
accepted prefix plus a correction (1..gamma tokens, per-row, no
minimum-across-batch stall). The draft model keeps its own paged pool with
the SAME (num_pages, page_size) geometry, governed by the same allocator
and block tables, so one physical page id names the same token span in
both pools and every allocation / refcount / CoW / eviction decision is
made once; prefill chunks and CoW copies simply run against both pools.
Rejected-token rollback is O(1) in both pools: ``len_cached`` stops at the
emitted count and K/V written past it is dead by construction (attention
masks positions >= seq_len, and the real continuation overwrites them
write-then-attend next round). Rounds resolve synchronously — the host
needs each row's accepted count to plan the next round — so ``overlap``
composes differently here: the round is dispatched BEFORE the step's
prefill chunks and its readback lands while they compute. Greedy rows emit
exactly the target's argmax at every position (the chunked verify logits
match the single-token path bitwise at f32), so a speculative engine is
token-identical to the plain engine; sampled rows follow Leviathan et
al.'s residual-resampling rule, keeping every emitted token exactly
target-distributed.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.generation import (
    decode_chunk_step,
    decode_token_step,
    make_row_sampler,
    truncate_logits,
)
from distributed_pytorch_tpu.obs import MetricsRegistry, Tracer
from distributed_pytorch_tpu.obs.flight import (
    NULL_FLIGHT_RECORDER,
    FlightRecorder,
)
from distributed_pytorch_tpu.obs.goodput import (
    GoodputTracker,
    count_params,
    peak_flops_per_chip,
    transformer_decode_flops_per_token,
)
from distributed_pytorch_tpu.obs.regress import RegressionDetector
from distributed_pytorch_tpu.obs.roofline import RooflineModel
from distributed_pytorch_tpu.obs.slo import SLOMonitor, SLObjective
from distributed_pytorch_tpu.obs.timeseries import TimeSeriesDB
from distributed_pytorch_tpu.obs.tracer import NULL_TRACER, _PID_REQUESTS
from distributed_pytorch_tpu.obs.xla import ProgramLedger, RecompileSentinel
from distributed_pytorch_tpu.serving.admission import (
    AdmissionController,
    ServingMetrics,
)
from distributed_pytorch_tpu.serving.hostkv import HostPageTier
from distributed_pytorch_tpu.serving.kv_cache import (
    NULL_PAGE,
    PagedBlockAllocator,
    PagePoolGroup,
    PrefixCache,
)
from distributed_pytorch_tpu.serving.mods import AdapterStore, Mods, ModState
from distributed_pytorch_tpu.serving.mesh import (
    axis_sizes,
    kv_pool_shardings,
    mesh_fingerprint,
    replicated,
    serving_param_shardings,
    validate_kv_heads,
)
from distributed_pytorch_tpu.serving.scheduler import (
    PENDING_TOKEN,
    Request,
    SamplingParams,
    Scheduler,
)


class _PhaseSpan:
    """Accounted step-phase context: enters the tracer's phase slice,
    applies any chaos ``slow_program`` stall inside it, and accumulates
    the phase's wall time into the engine's per-step ``_acct["phases"]``
    scratch — the per-phase series the TSDB records and the regression
    detector attributes blame with. Built by ``InferenceEngine._phase``
    only when accounting or a perf fault is active."""

    __slots__ = ("engine", "name", "stall", "_ctx", "_t0")

    def __init__(self, engine, name: str, stall: float):
        self.engine = engine
        self.name = name
        self.stall = stall

    def __enter__(self):
        self._ctx = self.engine.tracer.phase(self.name)
        self._ctx.__enter__()
        self._t0 = time.perf_counter()
        if self.stall > 0.0:
            time.sleep(self.stall)
        return self

    def __exit__(self, *exc):
        acct = self.engine._acct
        if acct is not None:
            phases = acct["phases"]
            phases[self.name] = (
                phases.get(self.name, 0.0)
                + (time.perf_counter() - self._t0)
            )
        return self._ctx.__exit__(*exc)


@dataclasses.dataclass(frozen=True)
class RequestStatus:
    """Snapshot returned by :meth:`InferenceEngine.poll`."""

    req_id: int
    state: str
    prompt_len: int
    generated: List[int]
    finished: bool
    preempt_count: int


class InferenceEngine:
    """Continuous-batching engine over a paged KV cache.

    ``model`` is the TRAINING-mode module (same contract as ``generate``);
    it is cloned with ``decode=True, page_size, num_pages`` internally.
    ``num_pages`` defaults to exactly enough pages for every slot to hold
    ``max_seq_len`` tokens (+1 for the reserved null page) — i.e. no
    overcommit; pass a smaller value to exercise preemption and cache
    eviction.

    ``prefix_cache=True`` shares page-aligned K/V across requests with a
    common prompt prefix (retired pages idle on an LRU instead of freeing);
    ``overlap=True`` defers each decode readback by one step so host
    scheduling hides under device compute. Both default on — outputs are
    bitwise-identical either way. ``debug=True`` re-enables the
    O(num_pages) allocator invariant sweep after every schedule.

    ``top_k``/``top_p`` are engine-static (compiled into the decode step);
    temperature and seed are per-request (:class:`SamplingParams`).

    ``draft_model``/``draft_params`` switch every decode to speculative
    draft+verify rounds of ``gamma`` proposals (see module doc); the draft
    must share the target's vocabulary and gets its own paged pool with
    identical page geometry, moved in lockstep by the shared allocator.
    Greedy requests stay token-identical to the plain engine; sampled
    requests stay exactly target-distributed (but draw a different stream
    than the plain engine — one uniform per proposal, not per token).

    ``mesh`` (a ``("data", "model")`` mesh from
    :func:`~distributed_pytorch_tpu.serving.mesh.make_serving_mesh`)
    shards the whole device side: weights follow the Megatron rules
    rebound onto ``model``, every per-layer KV page pool splits its
    KV-head dim over ``model``, and all five compiled programs become
    pjit-style sharded programs with explicit in/out shardings — the SPMD
    partitioner inserts the collectives while the host-side allocator,
    block tables, scheduler, and prefix trie stay byte-for-byte unchanged
    (pages are metadata to them). ``mesh=None`` (default) keeps today's
    single-device jit path untouched; a ``(1, 1)`` mesh is
    bitwise-identical to it, larger meshes are greedy-token-identical
    (sharded reductions reorder float accumulation).
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        max_seq_len: int = 256,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        token_budget: int = 64,
        max_prefill_chunk: int = 32,
        max_queue: int = 128,
        max_queue_tokens: Optional[int] = None,
        top_k: int = 0,
        top_p: float = 0.0,
        prefix_cache: bool = True,
        overlap: bool = True,
        draft_model=None,
        draft_params=None,
        gamma: int = 4,
        mesh: Optional[Mesh] = None,
        debug: bool = False,
        tracer: Optional[Tracer] = None,
        trace_path: Optional[str] = None,
        flight: Optional[FlightRecorder] = None,
        slo: Optional[Sequence[SLObjective]] = None,
        goodput=None,
        xla_ledger=None,
        timeseries=None,
        max_live_adapters: int = 4,
        host_pages: Optional[int] = None,
        paged_kernel=False,
        kv_quant: Optional[str] = None,
    ):
        if max_seq_len % page_size:
            raise ValueError(
                f"max_seq_len {max_seq_len} must be a multiple of "
                f"page_size {page_size}"
            )
        self.pages_per_seq = max_seq_len // page_size
        if num_pages is None:
            num_pages = max_slots * self.pages_per_seq + 1
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.params = params
        self.overlap = overlap
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self.speculative = draft_model is not None
        if self.speculative:
            if draft_params is None:
                raise ValueError("draft_model requires draft_params")
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            if getattr(draft_model, "vocab_size", None) != getattr(
                model, "vocab_size", None
            ):
                raise ValueError(
                    f"draft vocab {getattr(draft_model, 'vocab_size', None)}"
                    f" != target vocab {getattr(model, 'vocab_size', None)}"
                    " — draft proposals index the target's distribution"
                )
        self.gamma = int(gamma) if self.speculative else 0
        self.draft_params = draft_params

        # Mesh geometry is engine-static, like top_k/top_p: it is compiled
        # into every program and fingerprinted into elastic snapshots.
        # Head-divisibility is refused HERE (readable head counts), before
        # the per-kernel divisibility pass in make_param_specs.
        self.mesh = mesh
        self.mesh_fingerprint = mesh_fingerprint(mesh)
        self._data_size, self._model_size = axis_sizes(mesh)
        self._sharded_programs = 0
        if mesh is not None:
            validate_kv_heads(model, mesh, role="target")
            if self.speculative:
                validate_kv_heads(draft_model, mesh, role="draft")

        # Fused paged-attention read path + int8 KV pages (ops/
        # paged_attention.py). ``paged_kernel`` accepts False/None (off),
        # True/"auto" (Pallas on TPU, XLA reference elsewhere), or an
        # explicit mode ("pallas" | "interpret" | "xla"). ``kv_quant``
        # accepts None/"" (fp pages) or "int8". Both are engine-static like
        # the mesh: compiled into every program and fingerprinted into
        # elastic snapshots (kv_fingerprint). The clone kwargs are added
        # ONLY when set so the kernel-off engine's decode model — and its
        # compiled programs — stay byte-identical to before.
        if kv_quant not in (None, "", "int8"):
            raise ValueError(
                f"unknown kv_quant {kv_quant!r} (expected None or 'int8')"
            )
        self.kv_quant = kv_quant or ""
        self.kv_fingerprint = "int8" if self.kv_quant else "fp"
        self.paged_kernel = (
            "" if not paged_kernel
            else ("auto" if paged_kernel is True else str(paged_kernel))
        )
        clone_kw = {}
        if self.paged_kernel:
            clone_kw["paged_kernel"] = self.paged_kernel
            if mesh is not None:
                # The kernel shard_maps its head dim over the mesh's
                # "model" axis — the same split KV_POOL_SPEC already gives
                # the pools — so it runs per-shard under the pjit programs.
                clone_kw["mesh"] = mesh
        if self.kv_quant:
            clone_kw["kv_quant"] = self.kv_quant
        self.decode_model = model.clone(
            decode=True, page_size=page_size, num_pages=num_pages, **clone_kw
        )
        # Size the paged pool from abstract shapes only (eval_shape traces
        # init without running it); token length 1 — pool shapes depend only
        # on (num_pages, page_size), never on the init input.
        def _zero_cache(decode_model):
            abstract = jax.eval_shape(
                decode_model.init,
                jax.random.PRNGKey(0),
                jnp.zeros((max_slots, 1), jnp.int32),
            )["cache"]
            return jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), abstract
            )

        # The draft pool shares (num_pages, page_size) with the target pool
        # — same page ids, same block tables, one allocator — so every page
        # lifecycle decision moves both pools in lockstep. Head/width can
        # differ freely; only the page GEOMETRY must match.
        pools = {"target": _zero_cache(self.decode_model)}
        if self.speculative:
            self.draft_decode_model = draft_model.clone(
                decode=True, page_size=page_size, num_pages=num_pages,
                **clone_kw,
            )
            pools["draft"] = _zero_cache(self.draft_decode_model)
        self.pools = PagePoolGroup(**pools)

        # Place the device state ONCE at init: params under the Megatron
        # rules (rebound to "model"), every KV pool with heads split over
        # "model", and one shared replicated sharding for the host-staged
        # program inputs. The compiled programs' donated-cache out
        # shardings keep the pools in place steady-state, so no resharding
        # ever happens on the hot path.
        if mesh is not None:
            self._replicated = replicated(mesh)
            self._param_shardings = serving_param_shardings(mesh, params)
            self.params = jax.device_put(params, self._param_shardings)
            if self.speculative:
                self._draft_param_shardings = serving_param_shardings(
                    mesh, draft_params
                )
                self.draft_params = jax.device_put(
                    draft_params, self._draft_param_shardings
                )
            self._pool_shardings = {
                name: kv_pool_shardings(mesh, self.pools[name])
                for name in self.pools.names
            }
            for name in self.pools.names:
                self.pools[name] = jax.device_put(
                    self.pools[name], self._pool_shardings[name]
                )

        # Zero-cost-when-disabled observability handle: one shared null
        # object serves every untraced engine — no timestamps, no dicts,
        # bitwise-identical outputs (pinned by tests).
        self.tracer = tracer if tracer is not None else NULL_TRACER
        if mesh is not None and self.tracer.enabled:
            # Unsharded traces stay byte-identical: the label is only set
            # (and only serialized) for meshed engines.
            self.tracer.set_engine_label(f"mesh {self.mesh_fingerprint}")
        self.flight = flight if flight is not None else NULL_FLIGHT_RECORDER
        self.allocator = PagedBlockAllocator(num_pages)
        self.allocator.tracer = self.tracer
        self.allocator.flight = self.flight
        self.allocator.pool_names = self.pools.names
        self.prefix_cache = (
            PrefixCache(self.allocator, page_size) if prefix_cache else None
        )
        # Host-memory page tier (serving/hostkv.py): ``host_pages`` > 0
        # preallocates that many host pages per pool and attaches the
        # tier behind the prefix trie — evicted full pages spill d2h
        # instead of being lost, and a later prefix hit on a spilled
        # chain fetches h2d during admission, overlapped with decode.
        # Token outputs are bitwise-identical tier on or off (the
        # fetched K/V is the same content a re-prefill would recompute).
        if host_pages:
            if self.prefix_cache is None:
                raise ValueError(
                    "host_pages requires prefix_cache=True — host pages "
                    "are named by the prefix trie's content-addressed "
                    "key chain"
                )
            self.hostkv = HostPageTier(
                {name: self.pools[name] for name in self.pools.names},
                num_host_pages=int(host_pages),
                page_size=page_size,
                gather_fn=self._gather_page,
            )
            self.prefix_cache.host = self.hostkv
        else:
            self.hostkv = None
        self.scheduler = Scheduler(
            self.allocator,
            max_slots=max_slots,
            page_size=page_size,
            pages_per_seq=self.pages_per_seq,
            token_budget=token_budget,
            max_prefill_chunk=max_prefill_chunk,
            prefix_cache=self.prefix_cache,
            gamma=self.gamma,
            debug=debug,
            tracer=self.tracer,
            flight=self.flight,
        )
        self.admission = AdmissionController(
            max_queue=max_queue,
            max_request_tokens=max_seq_len,
            max_queue_tokens=max_queue_tokens,
        )
        self.metrics = ServingMetrics(speculative=self.speculative)
        self.vocab_size = int(getattr(model, "vocab_size", 0))
        # Per-request LoRA adapters: merged-weight trees are full model
        # copies, so they get the KV-page treatment — an LRU device cache
        # capped at ``max_live_adapters``. Unsharded engines only (a
        # merged tree would need re-placement under the param shardings);
        # submit() refuses adapter mods on meshed/speculative engines.
        self.adapters = AdapterStore(self.params, max_live=max_live_adapters)
        # Elastic lifecycle counters (serving/elastic.py increments the
        # first three; close() flips _closed). Surfaced via the registry so
        # a drill can cross-check them against ground truth.
        self.drains = 0
        self.restores = 0
        self.requests_recovered = 0
        self.trace_path = trace_path
        self._closed = False
        # Goodput accounting: ``goodput=True`` builds a tracker configured
        # from the model's own dims (decode FLOPs-per-token at half the max
        # context, peak FLOPs from the local device kind); pass a
        # pre-configured GoodputTracker for full control. ``_acct`` is the
        # per-step scratch dict the accounting wrapper threads through
        # ``_step_impl`` — None whenever no step is being accounted.
        if goodput is True:
            self.goodput = self._default_goodput(model)
        else:
            self.goodput = goodput if goodput else None
        self._acct: Optional[dict] = None
        # Device-truth accounting (obs/xla.py). ``xla_ledger=True`` (or a
        # pre-built ProgramLedger) wraps every compiled program: first call
        # per signature runs an analysis-only AOT compile recording wall
        # time / memory_analysis HBM / cost-analysis FLOPs, and the engine
        # counts host<->device staging/readback bytes per step. Execution
        # always goes through the original jit callable, so tokens are
        # bitwise-identical ledger-on vs -off. The paired RecompileSentinel
        # (``arm_recompile_sentinel()`` after warmup) turns any later
        # compilation into a counted, flight-recorded alert. Must be chosen
        # at construction — programs are wrapped as they are built.
        if xla_ledger:
            self.xla = (
                xla_ledger
                if isinstance(xla_ledger, ProgramLedger)
                else ProgramLedger()
            )
            self.sentinel = RecompileSentinel(
                self.xla, tracer=self.tracer, flight=self.flight
            )
        else:
            self.xla = None
            self.sentinel = None
        # The performance observatory (obs/timeseries.py + obs/regress.py
        # + obs/roofline.py). ``timeseries=True`` builds a default TSDB;
        # pass a TimeSeriesDB for custom resolutions. Every registry
        # counter/gauge plus the derived per-step series is sampled each
        # accounted step; the CUSUM regression detector rides the same
        # feed, and — when the XLA ledger is also on — a RooflineModel
        # joins ledger bytes/FLOPs with the chip peaks. Pure host-side
        # bookkeeping off the device path: tokens are bitwise-identical
        # observatory-on vs -off (pinned in tests and the perfwatch bench).
        if timeseries:
            self.timeseries = (
                timeseries
                if isinstance(timeseries, TimeSeriesDB)
                else TimeSeriesDB()
            )
            self.regress = RegressionDetector(
                flight=self.flight, tracer=self.tracer
            )
        else:
            self.timeseries = None
            self.regress = None
        if self.timeseries is not None and self.xla is not None:
            self.roofline = RooflineModel(
                self.xla,
                self.timeseries,
                device=jax.devices()[0],
                fallback_flops_fn=self._analytic_program_flops(model),
            )
        else:
            self.roofline = None
        # Introspection server handle (serve()/close()); while attached,
        # step()/submit() run under the registry lock so scrapes observe
        # step boundaries only.
        self._server = None
        self.registry = self._build_registry()
        if self.timeseries is not None:
            self.timeseries.track_registry(self.registry)
        # SLO burn-rate monitoring reads the registry it writes its
        # verdicts into, so one snapshot carries metrics AND alerts.
        self.slo = (
            SLOMonitor(
                self.registry, slo, tracer=self.tracer, flight=self.flight
            )
            if slo
            else None
        )
        # Flight-recorder postmortems must be written BEFORE an injected
        # fault SIGKILLs the process: chaos notifies observers first.
        if self.flight.enabled:
            chaos.add_fault_observer(self._on_chaos_fault)
        self.requests: Dict[int, Request] = {}
        self._next_id = 0
        self._keys: Dict[int, jax.Array] = {}

        # Reusable host staging buffers for the batched decode inputs —
        # refilled in place every step instead of reallocated. Rows for
        # inactive slots MUST be re-zeroed each step (a stale block-table
        # row would scatter the masked write into a page some other request
        # now owns); jnp.asarray copies host->device, so mutating these
        # after dispatch is safe.
        self._stage_tokens = np.zeros((max_slots,), np.int32)
        self._stage_tables = np.zeros(
            (max_slots, self.pages_per_seq), np.int32
        )
        self._stage_lens = np.zeros((max_slots,), np.int32)
        self._stage_temps = np.zeros((max_slots,), np.float32)
        self._stage_keys = np.zeros((max_slots, 2), np.uint32)
        self._stage_use_prev = np.zeros((max_slots,), np.int32)
        self._zero_prev = jnp.zeros((max_slots,), jnp.int32)
        # Fixed-shape additive-logit operand for per-request mods. The
        # all-zeros device constant serves every dispatch with no modded
        # rows (no extra host->device bytes on the mods-off path); the
        # host buffer is filled per group only when some row carries a
        # bias/grammar row.
        self._stage_bias = np.zeros(
            (max_slots, self.vocab_size), np.float32
        )
        self._zero_bias = jnp.zeros(
            (max_slots, self.vocab_size), jnp.float32
        )
        if mesh is not None:
            self._zero_prev = jax.device_put(
                self._zero_prev, self._replicated
            )
            self._zero_bias = jax.device_put(
                self._zero_bias, self._replicated
            )
        # (sampled-token device array, decode slots, their requests) of the
        # not-yet-resolved dispatch, or None.
        self._inflight: Optional[
            Tuple[jax.Array, List[int], List[Request]]
        ] = None

    def _default_goodput(self, model) -> GoodputTracker:
        """A :class:`GoodputTracker` configured from the engine's own
        geometry: decode FLOPs-per-token from the analytic transformer
        model at half the max context (the mean context of a sequence
        decoded to the limit), peak FLOPs from the local device kind, and
        the mesh's device count."""
        n_params = count_params(self.params)
        embed = getattr(model, "vocab_size", 0) * getattr(
            model, "d_model", 0
        )
        n_heads = max(1, getattr(model, "n_heads", 1))
        head_dim = getattr(model, "d_model", 0) // n_heads
        fpt = transformer_decode_flops_per_token(
            n_params=n_params,
            embed_params=min(embed, n_params),
            n_layers=getattr(model, "n_layers", 0),
            n_heads=n_heads,
            head_dim=head_dim,
            context_len=self.max_seq_len // 2,
        )
        return GoodputTracker(
            flops_per_token=fpt,
            peak_flops_per_device=peak_flops_per_chip(jax.devices()[0]),
            n_devices=max(1, self._data_size * self._model_size),
        )

    def _analytic_program_flops(self, model):
        """Fallback FLOPs-per-call estimator for the roofline model, used
        when a ledgered program's ``cost_analysis`` reports 0 (the CPU
        backend omits flops — the same gap the goodput MFU path fills with
        the analytic transformer model). Maps each engine program to the
        decode FLOPs-per-token model by its token count per call."""
        n_params = count_params(self.params)
        embed = getattr(model, "vocab_size", 0) * getattr(
            model, "d_model", 0
        )
        n_heads = max(1, getattr(model, "n_heads", 1))
        head_dim = getattr(model, "d_model", 0) // n_heads
        fpt = transformer_decode_flops_per_token(
            n_params=n_params,
            embed_params=min(embed, n_params),
            n_layers=getattr(model, "n_layers", 0),
            n_heads=n_heads,
            head_dim=head_dim,
            context_len=self.max_seq_len // 2,
        )
        max_slots, gamma = self.max_slots, self.gamma

        def flops_for(record) -> float:
            name = record.name
            if "prefill_step_c" in name:
                try:
                    return fpt * int(name.rsplit("c", 1)[1])
                except ValueError:
                    return fpt
            if name.startswith("decode_step"):
                return fpt * max_slots
            if name.startswith("spec_step"):
                # gamma draft steps + one gamma-wide verify per slot.
                return fpt * max_slots * (2 * gamma)
            return 0.0  # copy_page and friends move bytes, not FLOPs

        return flops_for

    def _build_registry(self) -> MetricsRegistry:
        """Every serving metric registered into one ``serving_``-namespaced
        :class:`MetricsRegistry`: the :class:`ServingMetrics` counters and
        latency reservoirs (resolved through ``self.metrics`` at snapshot
        time, so swapping the metrics object — bench's warm-up reset —
        stays correct), admission counters, scheduler pressure, and the
        allocator's O(1) page-state gauges. Pull-based: the owning objects
        keep their plain attributes as the single source of truth."""
        reg = MetricsRegistry(namespace="serving")
        ServingMetrics.register_into(reg, lambda: self.metrics)
        self.admission.register_into(reg)
        reg.counter_fn(
            "preemptions_total", lambda: self.scheduler.preemptions
        )
        reg.counter_fn("drains_total", lambda: self.drains)
        reg.counter_fn("restores_total", lambda: self.restores)
        reg.counter_fn(
            "requests_recovered_total", lambda: self.requests_recovered
        )
        reg.counter_fn(
            "requests_expired_total", lambda: self.scheduler.expired
        )
        reg.counter_fn(
            "requests_cancelled_total", lambda: self.scheduler.cancelled
        )
        reg.counter_fn(
            "adapter_cache_hits_total", lambda: self.adapters.hits
        )
        reg.counter_fn(
            "adapter_cache_misses_total", lambda: self.adapters.misses
        )
        reg.counter_fn(
            "adapter_evictions_total", lambda: self.adapters.evictions
        )
        reg.gauge_fn(
            "adapters_live", lambda: len(self.adapters.live)
        )
        reg.counter_fn(
            "cow_copies_total", lambda: self.allocator.cow_copies
        )
        reg.counter_fn(
            "page_evictions_total", lambda: self.allocator.evictions
        )
        reg.gauge_fn(
            "pages_free", lambda: self.allocator.counters()["pages_free"]
        )
        reg.gauge_fn(
            "pages_referenced", lambda: self.allocator.num_allocated
        )
        reg.gauge_fn("pages_cached_idle", lambda: self.allocator.num_idle)
        reg.gauge_fn("queue_depth", lambda: self.scheduler.num_waiting)
        reg.gauge_fn(
            "running_requests", lambda: len(self.scheduler.running)
        )
        if self.prefix_cache is not None:
            pc = self.prefix_cache
            reg.counter_fn("prefix_lookups_total", lambda: pc.lookups)
            reg.counter_fn("prefix_hits_total", lambda: pc.hits)
            reg.counter_fn("prefix_tokens_hit_total", lambda: pc.tokens_hit)
            reg.counter_fn(
                "prefix_tokens_missed_total", lambda: pc.tokens_missed
            )
            reg.counter_fn(
                "prefix_tokens_hit_host_total", lambda: pc.tokens_hit_host
            )
            reg.gauge_fn("prefix_nodes", lambda: pc.num_nodes)
        if self.hostkv is not None:
            hk = self.hostkv
            reg.counter_fn("hostkv_spills_total", lambda: hk.spills)
            reg.counter_fn("hostkv_fetches_total", lambda: hk.fetches)
            reg.counter_fn(
                "hostkv_spill_bytes_total", lambda: hk.spill_bytes_total
            )
            reg.counter_fn(
                "hostkv_fetch_bytes_total", lambda: hk.fetch_bytes_total
            )
            reg.counter_fn(
                "hostkv_evictions_total", lambda: hk.host_evictions
            )
            reg.gauge_fn(
                "hostkv_pages_resident", lambda: hk.pages_resident
            )
            reg.gauge_fn("hostkv_pages_capacity", lambda: hk.capacity)
        # Mesh geometry. The registry has no label support, so the shape
        # label rides an info-style gauge (value pinned to 1.0, shape in
        # the name) next to the numeric per-axis gauges; an unsharded
        # engine reports 1/1/0 under serving_mesh_1x1_info.
        reg.gauge_fn("data_axis_size", lambda: self._data_size)
        reg.gauge_fn("model_axis_size", lambda: self._model_size)
        reg.gauge_fn(
            "sharded_program_count", lambda: self._sharded_programs
        )
        reg.gauge_fn(f"mesh_{self.mesh_fingerprint}_info", lambda: 1.0)
        if self.goodput is not None:
            self.goodput.register_into(reg)
        if self.xla is not None:
            self.xla.register_into(reg)
        if self.sentinel is not None:
            self.sentinel.register_into(reg)
        if self.timeseries is not None:
            ts = self.timeseries
            reg.gauge_fn(
                "timeseries_series",
                lambda: float(len(ts.series_names())),
                help="Series tracked by the in-process TSDB",
            )
            reg.gauge_fn(
                "timeseries_memory_bytes",
                lambda: float(ts.memory_bytes()),
                help="Bounded TSDB retained-sample memory estimate",
            )
        if self.regress is not None:
            # Late-bound through the engine attribute (not the instance)
            # so a bench/test can swap in a differently-tuned detector
            # before the first step without orphaning the metrics.
            reg.counter_fn(
                "perf_regressions_total",
                lambda: float(self.regress.alerts),
                help="Sustained perf-level shifts detected by CUSUM",
            )
            reg.gauge_fn(
                "perf_regression_firing",
                lambda: float(self.regress.firing),
                help="1 after a perf regression until acknowledged",
            )
        if self.roofline is not None:
            self.roofline.register_into(reg)
        if self.flight.enabled:
            fl = self.flight
            reg.counter_fn(
                "flight_events_recorded_total",
                lambda: fl.recorded,
                help="Events appended to the flight-recorder ring",
            )
            reg.counter_fn(
                "flight_events_dropped_total",
                lambda: fl.dropped,
                help="Events that fell off the back of the ring",
            )
            reg.counter_fn(
                "flight_dumps_total",
                lambda: fl.dumps,
                help="Postmortem dumps written",
            )
        return reg

    # Pool accessors: the target pool keeps its historical ``self.cache``
    # name (the plain-engine hot path reads/writes it directly); the draft
    # pool exists only on speculative engines.

    @property
    def cache(self):
        return self.pools["target"]

    @cache.setter
    def cache(self, value):
        self.pools["target"] = value

    @property
    def draft_cache(self):
        return self.pools["draft"]

    @draft_cache.setter
    def draft_cache(self, value):
        self.pools["draft"] = value

    # ------------------------------------------------------------- compiled
    #
    # Every factory below branches once on ``self.mesh``: unsharded engines
    # get the EXACT jit call they always had (the bitwise guarantee is the
    # absence of any new annotation, not a (1,1) fast path), meshed engines
    # get the same trace wrapped in explicit in/out shardings — params
    # under SERVING_PARAM_RULES, pools under KV_POOL_SPEC, every
    # host-staged operand and sampled output replicated. Donated caches
    # keep their sharding on the way out, so device state never migrates
    # after init. Each sharded compile bumps ``_sharded_programs`` (a
    # registry gauge): lazily-built programs surface in obs exactly when
    # they start existing.

    def _ledgered(self, name, fn):
        """Route one compiled program through the XLA ledger when device
        accounting is on; the identity otherwise (the bitwise/fast-path
        guarantee is the absence of any wrapper, not a cheap wrapper)."""
        if self.xla is None:
            return fn
        return self.xla.wrap(name, fn)

    def _sharded_jit(self, run, *, donate, in_shardings, out_shardings):
        self._sharded_programs += 1
        return jax.jit(
            run,
            donate_argnums=donate,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
        )

    @functools.cached_property
    def _decode_step(self):
        """THE batched decode program: one compile for the engine's
        lifetime. Greedy and sampled rows coexist via a per-slot temperature
        vector (0 = greedy); ``prev``/``use_prev`` splice the previous
        step's device-resident samples in as inputs so overlapped slots
        never wait on a host readback. ``bias`` is the fixed-shape
        ``[max_slots, vocab]`` additive logit operand carrying
        per-request logit-bias and grammar-mask rows — always present
        (all-zeros when no row has mods, a cached device constant so the
        common path stages no extra bytes), so mods arrive as data and
        the program NEVER recompiles for them."""
        row_sample = make_row_sampler(self._top_k, self._top_p)

        def run(params, cache, tokens, prev, use_prev, tables, lens, temps,
                keys, bias):
            tok = jnp.where(use_prev > 0, prev, tokens)
            last_logits, cache = decode_token_step(
                self.decode_model, params, cache, tok[:, None],
                block_tables=tables, seq_lens=lens,
            )
            nxt = row_sample(last_logits, temps, keys, bias)
            return nxt, cache

        # The fused-kernel decode compiles under its own ledger name so the
        # roofline attributes the before/after to two distinct programs
        # (both keep the "decode_step" prefix the analytic FLOPs model and
        # roofline tagging key on).
        name = "decode_step_paged" if self.paged_kernel else "decode_step"
        if self.mesh is None:
            return self._ledgered(
                name, jax.jit(run, donate_argnums=(1,))
            )
        rep = self._replicated
        pool = self._pool_shardings["target"]
        # prev is device-resident feedback: it comes back replicated (out
        # sharding below) and is consumed replicated, so the overlapped
        # splice never adds a collective.
        return self._ledgered(
            name,
            self._sharded_jit(
                run,
                donate=(1,),
                in_shardings=(
                    self._param_shardings, pool, rep, rep, rep, rep, rep,
                    rep, rep, rep,
                ),
                out_shardings=(rep, pool),
            ),
        )

    @functools.lru_cache(maxsize=16)
    def _prefill_step(self, chunk: int):
        """One compile per power-of-two chunk length; returns only the
        updated cache, so XLA prunes the LM head from the program."""

        def run(params, cache, tokens, table, length):
            _, cache = decode_token_step(
                self.decode_model, params, cache, tokens,
                block_tables=table, seq_lens=length,
            )
            return cache

        name = f"prefill_step_c{chunk}"
        if self.mesh is None:
            return self._ledgered(name, jax.jit(run, donate_argnums=(1,)))
        rep = self._replicated
        pool = self._pool_shardings["target"]
        return self._ledgered(
            name,
            self._sharded_jit(
                run,
                donate=(1,),
                in_shardings=(self._param_shardings, pool, rep, rep, rep),
                out_shardings=pool,
            ),
        )

    @functools.cached_property
    def _copy_page(self):
        """Copy one physical page across every layer's K/V pool — the
        device half of copy-on-write. Page ids are traced scalars, so this
        compiles exactly once (per pool when meshed: pools differ in
        sharding pytree, so the mesh path returns a pool-name -> program
        mapping, which :meth:`PagePoolGroup.copy_page` accepts)."""

        def run(cache, src, dst):
            return jax.tree_util.tree_map(
                lambda pool: pool.at[dst].set(pool[src]), cache
            )

        if self.mesh is None:
            return self._ledgered(
                "copy_page", jax.jit(run, donate_argnums=(0,))
            )
        rep = self._replicated
        return {
            name: self._ledgered(
                f"copy_page_{name}",
                self._sharded_jit(
                    run,
                    donate=(0,),
                    in_shardings=(self._pool_shardings[name], rep, rep),
                    out_shardings=self._pool_shardings[name],
                ),
            )
            for name in self.pools.names
        }

    @functools.cached_property
    def _spill_page(self):
        """Gather one physical page across every layer of a pool — the
        device half of a host-tier spill. The cache is NOT donated (the
        pools live on); the gathered page materializes host-side later,
        in :meth:`HostPageTier.drain_spills`, so eviction never blocks
        on a d2h sync. Meshed engines replicate the gathered page so the
        host drain reads one contiguous buffer per leaf."""

        def run(cache, src):
            return jax.tree_util.tree_map(lambda pool: pool[src], cache)

        if self.mesh is None:
            return self._ledgered("spill_page", jax.jit(run))
        rep = self._replicated
        return {
            name: self._ledgered(
                f"spill_page_{name}",
                self._sharded_jit(
                    run,
                    donate=(),
                    in_shardings=(self._pool_shardings[name], rep),
                    out_shardings=rep,
                ),
            )
            for name in self.pools.names
        }

    @functools.cached_property
    def _fetch_pages(self):
        """Write a BATCH of spilled pages' host K/V back into every
        layer of a pool — ONE program dispatch per pool per step, never
        per page (per-page dispatch overhead would eat the saved
        prefill on small pages). Same device-resident dispatch trick as
        the overlapped step loop: the write is dispatched before the
        step's prefill/decode, and the cache data dependency orders it
        ahead of any program that reads the destination pages, so the
        fetch overlaps ongoing decode instead of stalling it. Callers
        pad the batch to power-of-two buckets with NULL-page writes
        (zeros to page 0, which no real sequence reads) so jit retraces
        stay bounded."""

        def run(cache, chunks, dsts):
            return jax.tree_util.tree_map(
                lambda pool, c: pool.at[dsts].set(c), cache, chunks
            )

        if self.mesh is None:
            return self._ledgered(
                "fetch_pages", jax.jit(run, donate_argnums=(0,))
            )
        rep = self._replicated
        return {
            name: self._ledgered(
                f"fetch_pages_{name}",
                self._sharded_jit(
                    run,
                    donate=(0,),
                    in_shardings=(self._pool_shardings[name], rep, rep),
                    out_shardings=self._pool_shardings[name],
                ),
            )
            for name in self.pools.names
        }

    def _gather_page(self, page: int):
        """HostPageTier's gather hook: slice ``page`` out of every pool
        as device arrays (async — materialized at drain time)."""
        src = jnp.asarray(page, jnp.int32)
        fn = self._spill_page
        per_pool = isinstance(fn, dict)
        return {
            name: (fn[name] if per_pool else fn)(self.pools[name], src)
            for name in self.pools.names
        }

    def _execute_fetches(self, fetches) -> None:
        """Stage every planned host-tier fetch h2d — batched into one
        program dispatch per pool — and unpin the host entries. Byte
        accounting mirrors the spill side: the tier counts the REAL
        fetched bytes in :meth:`HostPageTier.chunks` (bucket padding is
        excluded), and the same sum lands in the transfer ledger under
        the ``hostkv_fetch`` tag, so the two ledgers cross-check
        exactly."""
        tier = self.hostkv
        fn = self._fetch_pages
        per_pool = isinstance(fn, dict)
        staged = 0
        dsts: list = []
        per_pool_chunks = {name: [] for name in self.pools.names}
        for key, page, _parent, _tokens, _node in fetches:
            chunks = tier.chunks(key)
            dsts.append(page)
            for name, chunk in chunks.items():
                staged += sum(
                    c.nbytes
                    for c in jax.tree_util.tree_leaves(chunk)
                )
                per_pool_chunks[name].append(chunk)
            tier.unpin(key)
            self.prefix_cache.fetch_pending.discard(page)
        # Pad to the next power-of-two bucket: the padding rows write
        # zeros to the NULL page (reserved, never read by a live
        # sequence), so every batch size in a bucket shares one compile.
        bucket = 1
        while bucket < len(dsts):
            bucket *= 2
        pad = bucket - len(dsts)
        dst_arr = jnp.asarray(dsts + [NULL_PAGE] * pad, jnp.int32)
        for name in self.pools.names:
            stacked = jax.tree_util.tree_map(
                lambda *leaves: np.stack(leaves),
                *per_pool_chunks[name],
            )
            if pad:
                stacked = jax.tree_util.tree_map(
                    lambda s: np.concatenate(
                        [s, np.zeros((pad,) + s.shape[1:], s.dtype)]
                    ),
                    stacked,
                )
            run = fn[name] if per_pool else fn
            self.pools[name] = run(self.pools[name], stacked, dst_arr)
        if staged and self.xla is not None:
            self.xla.count_h2d(staged, tag="hostkv_fetch")

    @functools.lru_cache(maxsize=16)
    def _draft_prefill_step(self, chunk: int):
        """Draft-pool twin of :meth:`_prefill_step`: every prefill chunk
        runs through BOTH models so the draft pool holds valid K/V for
        exactly the positions the target pool does — including
        trie-adopted pages, which were prefilled by both models when first
        written and so stay adoptable in lockstep."""

        def run(draft_params, draft_cache, tokens, table, length):
            _, draft_cache = decode_token_step(
                self.draft_decode_model, draft_params, draft_cache, tokens,
                block_tables=table, seq_lens=length,
            )
            return draft_cache

        name = f"draft_prefill_step_c{chunk}"
        if self.mesh is None:
            return self._ledgered(name, jax.jit(run, donate_argnums=(1,)))
        rep = self._replicated
        pool = self._pool_shardings["draft"]
        return self._ledgered(
            name,
            self._sharded_jit(
                run,
                donate=(1,),
                in_shardings=(
                    self._draft_param_shardings, pool, rep, rep, rep
                ),
                out_shardings=pool,
            ),
        )

    @functools.cached_property
    def _spec_step(self):
        """THE speculative round program — one compile for the engine's
        lifetime, batched over all slots like :meth:`_decode_step`:

        1. gamma single-token DRAFT steps (``fori_loop``) sample/argmax a
           proposal chunk per row, writing draft K/V at positions
           ``lens..lens+gamma-1`` and recording each step's filtered draft
           distribution q for the acceptance ratio;
        2. ONE gamma-wide chunked TARGET forward over
           ``[x_t, d_0..d_{gamma-2}]`` at the same positions scores every
           proposal (logits[:, j] decides position ``lens+j+1``);
        3. per-row acceptance: greedy rows keep proposals matching the
           target argmax; sampled rows accept d_i iff
           ``u_i * q(d_i) < p(d_i)`` and resample the first rejection from
           the residual ``max(p - q, 0)`` (exact target law, same rule as
           offline ``speculative_generate``).

        Returns ``(emitted [S, gamma], n_accepted [S])`` plus both updated
        pools; row s's round contributes ``min(n_accepted[s]+1, gamma)``
        tokens, ``emitted[s, :that]``. K/V past a row's emitted count is
        rejected garbage in BOTH pools and needs no cleanup: reads mask
        positions >= seq_len and the next round overwrites before
        attending. Per-round sub-draws derive from the staged per-request
        key: draft step i folds i, acceptance uniforms fold gamma, the
        residual draw folds gamma+1 — batch-composition independent, like
        everything else about sampling here."""
        top_k, top_p = self._top_k, self._top_p
        gamma = self.gamma
        n_slots = self.max_slots
        vocab = self.decode_model.vocab_size

        def filtered(logits, temps):
            # The distribution actually sampled from, f32 for the
            # acceptance-ratio arithmetic (mirrors offline speculative.py).
            safe_t = jnp.where(temps > 0, temps, 1.0)
            shaped = safe_t.reshape((-1,) + (1,) * (logits.ndim - 1))
            return jax.nn.softmax(
                truncate_logits(logits / shaped, top_k, top_p).astype(
                    jnp.float32
                ),
                axis=-1,
            )

        def run(params, draft_params, cache, draft_cache, tokens, tables,
                lens, temps, keys):
            rows = jnp.arange(n_slots)

            def fold_all(i):
                return jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    keys, i
                )

            # --- draft phase: propose gamma tokens per row -------------
            buf = jnp.zeros((n_slots, gamma + 1), jnp.int32)
            buf = buf.at[:, 0].set(tokens)
            qbuf = jnp.zeros((n_slots, gamma, vocab), jnp.float32)

            def draft_body(i, carry):
                buf, qbuf, dcache = carry
                cur = jax.lax.dynamic_slice_in_dim(buf, i, 1, axis=1)
                logits, dcache = decode_token_step(
                    self.draft_decode_model, draft_params, dcache, cur,
                    block_tables=tables, seq_lens=lens + i,
                )
                q = filtered(logits, temps)  # [S, V]
                sampled = jax.vmap(jax.random.categorical)(
                    fold_all(i), jnp.log(q)
                ).astype(jnp.int32)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                nxt = jnp.where(temps > 0, sampled, greedy)
                buf = buf.at[:, i + 1].set(nxt)
                qbuf = jax.lax.dynamic_update_slice_in_dim(
                    qbuf, q[:, None, :], i, axis=1
                )
                return buf, qbuf, dcache

            buf, qbuf, draft_cache = jax.lax.fori_loop(
                0, gamma, draft_body, (buf, qbuf, draft_cache)
            )

            # --- verify phase: one chunked target forward --------------
            chunk = buf[:, :gamma]       # [x_t, d_0 .. d_{gamma-2}]
            proposals = buf[:, 1:]       # [d_0 .. d_{gamma-1}]
            t_logits, cache = decode_chunk_step(
                self.decode_model, params, cache, chunk,
                block_tables=tables, seq_lens=lens,
            )
            greedy_t = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            p = filtered(t_logits, temps)  # [S, gamma, V]
            px = jnp.take_along_axis(
                p, proposals[..., None], axis=-1
            )[..., 0]
            qx = jnp.take_along_axis(
                qbuf, proposals[..., None], axis=-1
            )[..., 0]
            u = jax.vmap(lambda k: jax.random.uniform(k, (gamma,)))(
                fold_all(gamma)
            )
            # u < min(1, px/qx)  <=>  u*qx < px (q(x) > 0 a.s.).
            accept = jnp.where(
                temps[:, None] > 0, u * qx < px, proposals == greedy_t
            )
            n_acc = jnp.sum(
                jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
            )
            # Correction at column ni = min(n_acc, gamma-1). Fully
            # accepted rows route back to their own last proposal via the
            # n_acc > ni select (no bonus token — matches offline).
            ni = jnp.minimum(n_acc, gamma - 1)
            p_n = jnp.take_along_axis(p, ni[:, None, None], axis=1)[:, 0]
            q_n = jnp.take_along_axis(qbuf, ni[:, None, None], axis=1)[:, 0]
            residual = jnp.maximum(p_n - q_n, 0.0)
            has_mass = jnp.sum(residual, axis=-1, keepdims=True) > 0
            res_dist = jnp.where(has_mass, residual, p_n)
            resampled = jax.vmap(jax.random.categorical)(
                fold_all(gamma + 1), jnp.log(res_dist)
            ).astype(jnp.int32)
            greedy_repl = jnp.take_along_axis(
                greedy_t, ni[:, None], axis=1
            )[:, 0]
            replacement = jnp.where(temps > 0, resampled, greedy_repl)
            kept = jnp.take_along_axis(proposals, ni[:, None], axis=1)[:, 0]
            corrected = jnp.where(n_acc > ni, kept, replacement)
            emitted = proposals.at[rows, ni].set(corrected)
            return emitted, n_acc, cache, draft_cache

        if self.mesh is None:
            return self._ledgered(
                "spec_step", jax.jit(run, donate_argnums=(2, 3))
            )
        rep = self._replicated
        pool = self._pool_shardings["target"]
        draft_pool = self._pool_shardings["draft"]
        return self._ledgered(
            "spec_step",
            self._sharded_jit(
                run,
                donate=(2, 3),
                in_shardings=(
                    self._param_shardings, self._draft_param_shardings,
                    pool, draft_pool, rep, rep, rep, rep, rep,
                ),
                out_shardings=(rep, rep, pool, draft_pool),
            ),
        )

    # ----------------------------------------------------------------- API

    def register_adapter(
        self,
        name: str,
        adapters,
        *,
        rank: int,
        alpha: Optional[float] = None,
    ) -> None:
        """Register a named LoRA adapter (a ``training/lora.py`` low-rank
        tree) for per-request multiplexing. Merging happens eagerly here
        — the merge jit compiles NOW, so register every adapter before
        ``arm_recompile_sentinel()`` and the sentinel stays zero at
        steady state no matter how requests mix adapters."""
        if self.mesh is not None:
            raise ValueError(
                "adapter mods are not supported on meshed engines"
            )
        self.adapters.register(name, adapters, rank=rank, alpha=alpha)

    def submit(
        self,
        prompt: Sequence[int],
        params: Optional[SamplingParams] = None,
        metadata: Optional[dict] = None,
        *,
        tenant_id: str = "anon",
        mods: Optional[Mods] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Queue one request; returns its id. Raises
        :class:`~.admission.QueueFull` (backpressure),
        :class:`~.admission.RequestTooLong` (can never fit), or
        :class:`~.admission.EngineDraining` (drain/close in progress) —
        admission is decided NOW, not at first schedule, and counts the
        currently-cached prefix: a shared-prompt request costs only its
        uncached tail of prefill work against the queue-token budget.
        ``tenant_id`` is the typed tenancy key (fair-share, quotas,
        per-tenant SLOs, preserved across drain/restore); ``metadata``
        remains a tenant-opaque JSON-serializable dict carried through
        scheduling (and the elastic snapshot) untouched. ``mods`` is an
        optional :class:`~.mods.Mods` spec (logit bias / grammar /
        adapter); device mods are refused on speculative engines (the
        fused verify program has no bias operand) and adapter mods on
        meshed engines (merged trees are placed unsharded). ``trace_id``
        is the fleet-wide trace identity a layer above minted (front door
        / router) — stamped into the request span and flight events so
        the engine's slice of work joins the merged fleet trace."""
        if self._server is None:
            return self._submit_impl(
                prompt, params, metadata, tenant_id, mods, trace_id
            )
        with self.registry.lock:
            return self._submit_impl(
                prompt, params, metadata, tenant_id, mods, trace_id
            )

    def _submit_impl(
        self,
        prompt: Sequence[int],
        params: Optional[SamplingParams],
        metadata: Optional[dict],
        tenant_id: str = "anon",
        mods: Optional[Mods] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        params = params or SamplingParams()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        mod_state: Optional[ModState] = None
        if mods is not None and mods.device_mods:
            if self.speculative:
                raise ValueError(
                    "logit-bias/grammar/adapter mods are not supported "
                    "on speculative engines (stop_sequences are)"
                )
            if mods.adapter is not None:
                if self.mesh is not None:
                    raise ValueError(
                        "adapter mods are not supported on meshed engines"
                    )
                if mods.adapter not in self.adapters:
                    raise KeyError(
                        f"unknown adapter {mods.adapter!r} — call "
                        "register_adapter() first"
                    )
            mod_state = ModState(mods, self.vocab_size)
        cached = 0
        if self.prefix_cache is not None and prompt:
            cached = self.prefix_cache.peek(prompt)
        self.admission.check(
            len(prompt), params, self.scheduler.num_waiting,
            cached_tokens=cached,
            queued_uncached_tokens=sum(
                r.est_uncached for r in self.scheduler.waiting
            ),
            tenant_id=tenant_id,
            trace_id=trace_id,
        )
        req = Request(
            req_id=self._next_id,
            prompt=prompt,
            params=params,
            submit_time=time.perf_counter(),
            est_uncached=max(0, len(prompt) - 1 - cached),
            metadata=metadata,
            tenant_id=tenant_id,
            mods=mod_state,
            trace_id=trace_id,
        )
        self._next_id += 1
        self.requests[req.req_id] = req
        self._keys[req.req_id] = jax.random.PRNGKey(params.seed)
        if self.tracer.enabled:
            extra = {"trace_id": trace_id} if trace_id is not None else {}
            self.tracer.request_begin(
                req.req_id,
                prompt_len=len(prompt),
                max_new_tokens=params.max_new_tokens,
                cached_tokens_at_submit=cached,
                **extra,
            )
            if trace_id is not None:
                # Receive the fleet flow arrow on the engine's request lane.
                self.tracer.flow("t", trace_id, _PID_REQUESTS)
        self.scheduler.add(req)
        return req.req_id

    def _resolve_inflight(self) -> List[int]:
        """Read back the outstanding decode dispatch (the ONE blocking
        device sync — under overlap it lands while the next step computes),
        fill in sampled tokens, retire what finished."""
        nxt, slots, reqs = self._inflight
        self._inflight = None
        return self._resolve_rows(nxt, slots, reqs)

    def _resolve_rows(
        self, nxt, slots: List[int], reqs: List[Request]
    ) -> List[int]:
        """Resolve one decode dispatch's sampled tokens (async inflight
        or an in-step sync mod group): fill values, retire finishers."""
        nxt_host = np.asarray(nxt)
        if self.xla is not None:
            self.xla.count_d2h(nxt_host.nbytes)
        now = time.perf_counter()
        finished: List[int] = []
        for slot, req in zip(slots, reqs):
            done = self.scheduler.resolve_decoded(
                req, int(nxt_host[slot]), now=now
            )
            if self.tracer.enabled:
                self.tracer.request_event(
                    req.req_id, "decode_token", n_generated=req.n_generated
                )
            if done is not None:
                self.scheduler.retire(done, now=now)
                self.metrics.observe_finished(done)
                self._keys.pop(done.req_id, None)
                finished.append(done.req_id)
        return finished

    def _dispatch_decode(self, slots: List[int], params, prev):
        """Stage and run THE decode program for ``slots``. Rows outside
        the group stage a zeroed block table and length, so their masked
        K/V writes land in the null page — per-group dispatch commits
        state for its own rows only, which is what lets one step issue
        the base program plus per-adapter groups against one cache."""
        self._stage_tables.fill(0)
        self._stage_lens.fill(0)
        self._stage_use_prev.fill(0)
        bias = None
        for slot in slots:
            req = self.scheduler.slots[slot]
            pos = req.len_cached
            tok = req.tokens[pos]
            if tok == PENDING_TOKEN:
                # Input is last step's still-in-flight sample: select it
                # device-side from ``prev``.
                self._stage_use_prev[slot] = 1
                self._stage_tokens[slot] = 0
            else:
                self._stage_tokens[slot] = tok
            self._stage_tables[slot] = req.table.as_row(self.pages_per_seq)
            self._stage_lens[slot] = pos
            self._stage_temps[slot] = req.params.temperature
            self._stage_keys[slot] = np.asarray(
                jax.random.fold_in(self._keys[req.req_id], req.n_issued),
                np.uint32,
            )
            row = req.mods.bias_row() if req.mods is not None else None
            if row is not None:
                if bias is None:
                    bias = self._stage_bias
                    bias.fill(0.0)
                bias[slot] = row
        if self.xla is not None:
            staged = (
                self._stage_tokens.nbytes
                + self._stage_use_prev.nbytes
                + self._stage_tables.nbytes
                + self._stage_lens.nbytes
                + self._stage_temps.nbytes
                + self._stage_keys.nbytes
            )
            if bias is not None:
                staged += bias.nbytes
            self.xla.count_h2d(staged)
        # No modded rows: reuse the zeros device constant — the bias
        # operand costs the common path nothing.
        bias_arr = self._zero_bias if bias is None else jnp.asarray(bias)
        nxt, self.cache = self._decode_step(
            params, self.cache,
            jnp.asarray(self._stage_tokens), prev,
            jnp.asarray(self._stage_use_prev),
            jnp.asarray(self._stage_tables),
            jnp.asarray(self._stage_lens),
            jnp.asarray(self._stage_temps),
            jnp.asarray(self._stage_keys),
            bias_arr,
        )
        return nxt

    def _end_step_trace(self, plan) -> None:
        """Close the tracer's step slice with the per-step gauges: batch
        composition, token-budget utilization, page states, queue pressure.
        Gauge computation happens ONLY here, behind ``tracer.enabled`` — a
        disabled engine never takes this branch."""
        cost = self.gamma if self.speculative else 1
        used = sum(chunk for _s, chunk in plan.prefill) + (
            len(plan.decode_slots) * cost
        )
        pages = self.allocator.counters()
        extra = {}
        if self.goodput is not None:
            # One counter track per trace: the goodput fraction as of the
            # PREVIOUS step's accounting (this step's feed lands after the
            # slice closes).
            extra["goodput_fraction"] = self.goodput.fraction()
        if self.xla is not None:
            # Host<->device transfer ledger as counter tracks: bytes staged
            # up / read back since the previous step's slice closed.
            dh2d, dd2h = self.xla.step_transfer_deltas()
            extra["bytes_h2d"] = dh2d
            extra["bytes_d2h"] = dd2h
            extra["live_buffer_bytes"] = self.xla.live_bytes
        self.tracer.end_step(
            decode_rows=len(plan.decode_slots),
            prefill_chunks=len(plan.prefill),
            prefill_tokens=sum(chunk for _s, chunk in plan.prefill),
            budget_utilization=used / self.scheduler.token_budget,
            queue_depth=self.scheduler.num_waiting,
            running_requests=len(self.scheduler.running),
            pages_free=pages["pages_free"],
            pages_referenced=pages["pages_referenced"],
            pages_cached_idle=pages["pages_cached_idle"],
            **extra,
        )

    def step(self) -> List[int]:
        """Run one engine iteration; returns ids of requests that FINISHED
        during it (under overlap, a finish surfaces on the step after its
        token was dispatched). A no-op (empty list) when nothing is queued,
        running, or in flight.

        With goodput accounting, an SLO monitor, a flight recorder, an XLA
        ledger, or an introspection server attached, the step is wrapped
        in wall-clock attribution (see :meth:`_account_step`) and — when a
        server is live — the registry lock, so scrapes only ever observe
        step boundaries; none of it touches device work or scheduling
        decisions, so outputs stay bitwise-identical (pinned by the
        obs-parity bench gate and the server parity test)."""
        if (
            self.goodput is None
            and self.slo is None
            and not self.flight.enabled
            and self.xla is None
            and self.timeseries is None
            and self._server is None
        ):
            return self._step_impl()
        with self.registry.lock:
            t0 = time.perf_counter()
            self._acct = {
                "plan": None, "rework": None, "emitted": 0, "proposed": 0,
                "phases": {},
            }
            try:
                finished = self._step_impl()
            finally:
                acct, self._acct = self._acct, None
            self._account_step(acct, time.perf_counter() - t0, finished)
            if self.xla is not None:
                self.xla.update_live_bytes()
            return finished

    def _account_step(self, acct, dt_s: float, finished: List[int]) -> None:
        """Post-step bookkeeping: feed the goodput tracker, append the
        flight-recorder step record, tick the SLO monitor."""
        plan = acct["plan"]
        prefill_tokens = decode_rows = 0
        if plan is not None:
            prefill_tokens = sum(chunk for _s, chunk in plan.prefill)
            decode_rows = len(plan.decode_slots)
        if self.speculative:
            decode_positions = acct["proposed"]
            emitted = acct["emitted"]
        else:
            decode_positions = emitted = decode_rows
        queue_depth = self.scheduler.num_waiting
        if self.goodput is not None:
            self.goodput.note_step(
                dt_s,
                prefill_tokens=prefill_tokens,
                decode_positions=decode_positions,
                emitted_tokens=emitted,
                spec_proposed=acct["proposed"],
                rework=acct["rework"],
                budget_used=prefill_tokens + decode_positions,
                token_budget=self.scheduler.token_budget,
                queue_depth=queue_depth,
            )
        if self.flight.enabled:
            self.flight.record(
                "step",
                step=self.metrics.engine_steps,
                dur_s=dt_s,
                prefill_tokens=prefill_tokens,
                decode_rows=decode_rows,
                emitted_tokens=emitted,
                queue_depth=queue_depth,
                running=len(self.scheduler.running),
                finished=len(finished),
            )
        if self.slo is not None:
            self.slo.tick()
        if self.timeseries is not None:
            tpot = dt_s / emitted if emitted > 0 else None
            derived = {
                "step_wall_seconds": dt_s,
                "decode_rows": float(decode_rows),
                "prefill_tokens": float(prefill_tokens),
                "tokens_per_sec": (emitted / dt_s) if dt_s > 0 else 0.0,
            }
            if tpot is not None:
                derived["tpot_step_seconds"] = tpot
            phases = acct.get("phases") or {}
            for name, spent in phases.items():
                derived[f"phase_{name}_seconds"] = spent
            # One tick samples every tracked registry counter/gauge (the
            # goodput fractions ride along as registry gauges) plus the
            # derived serving series above.
            self.timeseries.sample(**derived)
            if self.regress is not None:
                self.regress.observe(
                    step_wall_seconds=dt_s,
                    tpot_step_seconds=tpot,
                    decode_rows=decode_rows,
                    prefill_tokens=prefill_tokens,
                    phases=phases,
                )

    def _note_rework(self, req, start: int, chunk: int) -> None:
        """Charge the prefill positions below ``req.rework_until`` — K/V
        the engine had already computed before a preemption or restore —
        to the request's waste bucket. Called only while accounting."""
        rw = min(start + chunk, req.rework_until) - start
        if rw <= 0:
            return
        rework = self._acct["rework"]
        if rework is None:
            rework = self._acct["rework"] = {}
        rework[req.rework_kind] = rework.get(req.rework_kind, 0) + rw

    def _phase(self, name: str):
        """Step-phase span: the tracer's phase slice, plus (when the
        accounting wrapper is active) per-phase wall-time accumulation
        into ``_acct["phases"]`` — the series the regression detector
        blames — and (when a chaos ``slow_program`` fault is armed) the
        injected stall, slept INSIDE the span so traces, phase series,
        and detector attribution all see the slowdown where it was
        injected. With no accounting and no armed perf fault this returns
        the tracer's own context, so the all-obs-off fast path stays one
        attribute lookup away from the original code."""
        plan = chaos.get_plan()
        stall = (
            plan.serving_stall(name)
            if plan is not None and plan.has_perf_faults()
            else 0.0
        )
        if self._acct is None and stall <= 0.0:
            return self.tracer.phase(name)
        return _PhaseSpan(self, name, stall)

    def _step_impl(self) -> List[int]:
        chaos.on_serving_phase(
            "step", queue_depth=self.scheduler.num_waiting
        )
        tr = self.tracer
        tr.begin_step()
        with self._phase("schedule"):
            plan = self.scheduler.schedule()
        if self._acct is not None:
            self._acct["plan"] = plan

        if plan.copies:
            if self.xla is not None:
                # Two staged int32 page-id scalars per CoW copy.
                self.xla.count_h2d(8 * len(plan.copies))
            with self._phase("cow"):
                for _slot, src, dst in plan.copies:
                    # Copy-on-write fans out to every pool: the draft pool
                    # shares page ids with the target pool, so a page that
                    # splits, splits everywhere.
                    self.pools.copy_page(
                        self._copy_page,
                        jnp.asarray(src, jnp.int32),
                        jnp.asarray(dst, jnp.int32),
                    )

        if self.hostkv is not None:
            # Drain spills the schedule phase dispatched (evictions under
            # allocation pressure) into the host buffers, then stage this
            # plan's host-tier fetches. Both run BEFORE the empty-plan
            # early return: a fetch whose request was preempted in the
            # same schedule must still land (its trie entry is live), and
            # fetched pages must be written before any prefill/decode
            # below reads them — the cache data dependency orders that.
            if self.hostkv.pending_spills:
                with self._phase("spill"):
                    spilled = self.hostkv.drain_spills()
                if spilled and self.xla is not None:
                    self.xla.count_d2h(spilled, tag="hostkv_spill")
            if plan.fetches:
                with self._phase("fetch"):
                    self._execute_fetches(plan.fetches)

        if plan.empty:
            # Nothing to dispatch — drain the outstanding readback (e.g.
            # the final token of the last request) before reporting idle.
            if self._inflight is not None:
                with self._phase("readback"):
                    finished = self._resolve_inflight()
            else:
                finished = []
            if tr.enabled:
                self._end_step_trace(plan)
            return finished

        if self.speculative:
            return self._step_spec(plan)

        if plan.prefill:
            chaos.on_serving_phase("mid_prefill")
            with self._phase("prefill"):
                for slot, chunk in plan.prefill:
                    req = self.scheduler.slots[slot]
                    start = req.len_cached
                    if self._acct is not None and req.rework_until > start:
                        self._note_rework(req, start, chunk)
                    tok = np.asarray(
                        [req.tokens[start : start + chunk]], np.int32
                    )
                    table = req.table.as_row(self.pages_per_seq)[None]
                    if self.xla is not None:
                        self.xla.count_h2d(tok.nbytes + table.nbytes + 4)
                    # Adapter rows prefill under their merged weights —
                    # K/V written under base params would poison every
                    # decode step that attends to it.
                    ms = req.mods
                    chunk_params = (
                        self.adapters.params_for(ms.adapter)
                        if ms is not None and ms.adapter is not None
                        else self.params
                    )
                    self.cache = self._prefill_step(chunk)(
                        chunk_params, self.cache, jnp.asarray(tok),
                        jnp.asarray(table),
                        jnp.asarray([start], jnp.int32),
                    )
                    self.scheduler.note_prefilled(slot, chunk)

        finished: List[int] = []
        dispatched = None
        if plan.decode_slots:
            with self._phase("dispatch"):
                # Partition this step's decode rows. Async rows (no mods,
                # or bias-only — their bias row is request-constant) keep
                # the classic one-dispatch overlap via ``prev``/
                # ``use_prev``. Grammar rows (the next mask depends on
                # this step's token) and each adapter's rows (their group
                # swaps merged params into the SAME compiled program — a
                # jit cache hit, never a recompile) dispatch as separate
                # SYNC groups resolved in-step: the "mods tax" is losing
                # dispatch/readback overlap for those rows only.
                async_slots: List[int] = []
                sync_groups: Dict[Optional[str], List[int]] = {}
                for slot in plan.decode_slots:
                    ms = self.scheduler.slots[slot].mods
                    if ms is not None and ms.needs_sync:
                        sync_groups.setdefault(ms.adapter, []).append(slot)
                    else:
                        async_slots.append(slot)
                if async_slots:
                    prev = (
                        self._inflight[0] if self._inflight is not None
                        else self._zero_prev
                    )
                    nxt = self._dispatch_decode(
                        async_slots, self.params, prev
                    )
                    dispatched = (
                        nxt,
                        async_slots,
                        [
                            self.scheduler.note_decode_dispatched(s)
                            for s in async_slots
                        ],
                    )
                sync_rounds = []
                for adapter, slots in sorted(
                    sync_groups.items(),
                    key=lambda kv: (kv[0] is not None, kv[0] or ""),
                ):
                    group_params = (
                        self.params if adapter is None
                        else self.adapters.params_for(adapter)
                    )
                    nxt = self._dispatch_decode(
                        slots, group_params, self._zero_prev
                    )
                    sync_rounds.append((
                        nxt,
                        slots,
                        [
                            self.scheduler.note_decode_dispatched(s)
                            for s in slots
                        ],
                    ))
                for nxt, slots, reqs in sync_rounds:
                    finished.extend(self._resolve_rows(nxt, slots, reqs))
        if dispatched is not None:
            # The dispatched decode is in flight, its readback not taken:
            # the window a kill_mid_verify drill targets.
            chaos.on_serving_phase("mid_verify")
        # Resolve LAST step's tokens now — the np.asarray sync overlaps
        # with the decode dispatched above.
        if self._inflight is not None:
            with self._phase("readback"):
                finished.extend(self._resolve_inflight())
        self._inflight = dispatched
        if not self.overlap and self._inflight is not None:
            with self._phase("readback"):
                finished.extend(self._resolve_inflight())
        self.metrics.observe_step(new_tokens=len(plan.decode_slots))
        if tr.enabled:
            self._end_step_trace(plan)
        return finished

    def _step_spec(self, plan) -> List[int]:
        """Execute one speculative plan. The draft+verify round is
        dispatched FIRST (device-async), the step's prefill chunks run
        through both models while it computes, and only then does the host
        block on the round's readback — speculative rounds must resolve
        within their own step (the next schedule needs each row's accepted
        count), so overlap here means hiding the sync under prefill rather
        than deferring it a step like the plain path."""
        tr = self.tracer
        dispatched = None
        if plan.decode_slots:
            with self._phase("dispatch"):
                self._stage_tables.fill(0)
                self._stage_lens.fill(0)
                for slot in plan.decode_slots:
                    req = self.scheduler.slots[slot]
                    pos = req.len_cached
                    # Synchronous resolution means no PENDING placeholders:
                    # the row's input is always a real token.
                    self._stage_tokens[slot] = req.tokens[pos]
                    self._stage_tables[slot] = req.table.as_row(
                        self.pages_per_seq
                    )
                    self._stage_lens[slot] = pos
                    self._stage_temps[slot] = req.params.temperature
                    self._stage_keys[slot] = np.asarray(
                        jax.random.fold_in(
                            self._keys[req.req_id], req.n_issued
                        ),
                        np.uint32,
                    )
                if self.xla is not None:
                    self.xla.count_h2d(
                        self._stage_tokens.nbytes
                        + self._stage_tables.nbytes
                        + self._stage_lens.nbytes
                        + self._stage_temps.nbytes
                        + self._stage_keys.nbytes
                    )
                emitted, n_acc, self.cache, self.draft_cache = (
                    self._spec_step(
                        self.params, self.draft_params,
                        self.cache, self.draft_cache,
                        jnp.asarray(self._stage_tokens),
                        jnp.asarray(self._stage_tables),
                        jnp.asarray(self._stage_lens),
                        jnp.asarray(self._stage_temps),
                        jnp.asarray(self._stage_keys),
                    )
                )
                dispatched = (
                    emitted,
                    n_acc,
                    [
                        (s, self.scheduler.slots[s])
                        for s in plan.decode_slots
                    ],
                )
        if dispatched is not None:
            # Draft+verify round in flight, per-row acceptance unknown to
            # the host — the state a kill_mid_verify drill interrupts.
            chaos.on_serving_phase("mid_verify")

        if plan.prefill:
            chaos.on_serving_phase("mid_prefill")
            with self._phase("prefill"):
                for slot, chunk in plan.prefill:
                    req = self.scheduler.slots[slot]
                    start = req.len_cached
                    if self._acct is not None and req.rework_until > start:
                        self._note_rework(req, start, chunk)
                    tok = np.asarray(
                        [req.tokens[start : start + chunk]], np.int32
                    )
                    table = req.table.as_row(self.pages_per_seq)[None]
                    if self.xla is not None:
                        # Chunk + table + start staged into BOTH pools.
                        self.xla.count_h2d(
                            2 * (tok.nbytes + table.nbytes + 4)
                        )
                    self.cache = self._prefill_step(chunk)(
                        self.params, self.cache, jnp.asarray(tok),
                        jnp.asarray(table),
                        jnp.asarray([start], jnp.int32),
                    )
                    self.draft_cache = self._draft_prefill_step(chunk)(
                        self.draft_params, self.draft_cache,
                        jnp.asarray(tok), jnp.asarray(table),
                        jnp.asarray([start], jnp.int32),
                    )
                    self.scheduler.note_prefilled(slot, chunk)

        finished: List[int] = []
        new_tokens = 0
        if dispatched is not None:
            with self._phase("readback"):
                emitted, n_acc, slot_reqs = dispatched
                emitted_host = np.asarray(emitted)  # the ONE blocking sync
                n_acc_host = np.asarray(n_acc)
                if self.xla is not None:
                    self.xla.count_d2h(
                        emitted_host.nbytes + n_acc_host.nbytes
                    )
                now = time.perf_counter()
                for slot, req in slot_reqs:
                    accepted = int(n_acc_host[slot])
                    n_emit = min(accepted + 1, self.gamma)
                    if self._acct is not None:
                        self._acct["emitted"] += n_emit
                        self._acct["proposed"] += self.gamma
                    toks = [int(t) for t in emitted_host[slot, :n_emit]]
                    before = req.n_generated
                    done = self.scheduler.resolve_spec(req, toks, now=now)
                    self.metrics.observe_verify(
                        accepted=accepted, emitted=n_emit, gamma=self.gamma
                    )
                    if tr.enabled:
                        tr.request_event(
                            req.req_id, "verify_round",
                            accepted=accepted, emitted=n_emit,
                            n_generated=req.n_generated,
                        )
                    new_tokens += req.n_generated - before
                    if done is not None:
                        self.scheduler.retire(done, now=now)
                        self.metrics.observe_finished(done)
                        self._keys.pop(done.req_id, None)
                        finished.append(done.req_id)
        self.metrics.observe_step(new_tokens=new_tokens)
        if tr.enabled:
            self._end_step_trace(plan)
        return finished

    def poll(self, req_id: int) -> RequestStatus:
        req = self.requests[req_id]
        return RequestStatus(
            req_id=req_id,
            state=req.state.value,
            prompt_len=len(req.prompt),
            generated=list(req.generated),
            finished=req.done,
            preempt_count=req.preempt_count,
        )

    def cancel(self, req_id: int) -> bool:
        """Client-side cancellation: retire ``req_id`` mid-flight with the
        CANCELLED terminal state and free its pages immediately. Partial
        output stays pollable. Returns False when the request is unknown
        or already terminal."""
        req = self.requests.get(req_id)
        if req is None:
            return False
        return self.scheduler.cancel(req)

    # -------------------------------------------------- observability wire

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Start the HTTP introspection server for this engine (see
        ``obs/server.py``): ``/metrics``, ``/healthz``, ``/statusz``,
        ``/snapshot``, ``/trace``, ``/postmortem``. ``port=0`` binds an
        ephemeral port; read it from the returned server's ``.url``.
        Idempotent; stopped automatically by :meth:`close`. While a server
        is attached, :meth:`step` and :meth:`submit` run under the
        registry lock so scrapes observe step boundaries only — device
        work and tokens are untouched."""
        if self._server is None:
            from distributed_pytorch_tpu.obs.server import (
                IntrospectionServer,
            )

            self._server = IntrospectionServer(
                self, host=host, port=port
            ).start()
        return self._server

    def health(self) -> str:
        """``"live"`` / ``"draining"`` / ``"closed"`` — the ``/healthz``
        verdict (only ``"live"`` answers 200)."""
        if self._closed:
            return "closed"
        if self.admission.draining:
            return "draining"
        return "live"

    def trace_documents(self) -> List[dict]:
        """Every Perfetto trace document this component can vouch for —
        for a bare engine, its own tracer's. The ``/requestz`` handler
        merges these (via ``obs.disttrace.merge_traces``) to build
        per-request waterfalls; the front door overrides the same hook to
        add its own and its backend's lanes. Empty when untraced."""
        if not self.tracer.enabled:
            return []
        with self.registry.lock:
            return [self.tracer.to_perfetto()]

    def status(self) -> dict:
        """The ``/statusz`` document: one JSON-serializable dict of engine
        live-state — queue/slot occupancy with per-request phase, age and
        token counts, page-state counts, admission verdicts, SLO firing
        set, goodput split, the XLA program ledger, and recompile-sentinel
        state. Taken under the registry lock, so a server-thread caller
        sees a step-boundary-consistent view."""
        with self.registry.lock:
            now = time.perf_counter()
            out = {
                "health": self.health(),
                "engine": {
                    "speculative": self.speculative,
                    "mesh": self.mesh_fingerprint,
                    "max_slots": self.max_slots,
                    "overlap": self.overlap,
                    "steps": self.metrics.engine_steps,
                    "closed": self._closed,
                },
                "queue_depth": self.scheduler.num_waiting,
                "running_requests": len(self.scheduler.running),
                "inflight_dispatch": self._inflight is not None,
                "requests": self.scheduler.describe_requests(now=now),
                "pages": self.allocator.counters(),
                "admission": self.admission.status(),
                "latency": {
                    "ttft_p50_s": self.registry.read_quantile(
                        "ttft_seconds", 0.5
                    ),
                    "ttft_p95_s": self.registry.read_quantile(
                        "ttft_seconds", 0.95
                    ),
                    "tpot_p50_s": self.registry.read_quantile(
                        "tpot_seconds", 0.5
                    ),
                    "tpot_p95_s": self.registry.read_quantile(
                        "tpot_seconds", 0.95
                    ),
                    "tokens_per_sec": self.metrics.snapshot()[
                        "tokens_per_sec"
                    ],
                },
            }
            if self.prefix_cache is not None:
                out["prefix_cache"] = self.prefix_cache.stats()
            if self.hostkv is not None:
                out["hostkv"] = self.hostkv.status()
            if self.slo is not None:
                slo_state = self.slo.state()
                out["slo"] = {
                    "firing": sorted(
                        name
                        for name, st in slo_state.items()
                        if st["firing"]
                    ),
                    "objectives": slo_state,
                }
            if self.goodput is not None:
                out["goodput"] = self.goodput.report()
            if self.xla is not None:
                out["xla"] = self.xla.metadata()
            if self.sentinel is not None:
                out["recompile_sentinel"] = self.sentinel.status()
            if self.timeseries is not None:
                out["timeseries"] = self.timeseries.status()
            if self.regress is not None:
                out["perf_regress"] = self.regress.state()
            if self.roofline is not None:
                out["roofline"] = self.roofline.report()
            return out

    def arm_recompile_sentinel(self) -> RecompileSentinel:
        """Declare warmup over: from here on, every new XLA compilation —
        a ledger signature miss or an unattributed backend-compile event —
        bumps ``serving_engine_recompiles_total``, records a ``recompile``
        flight event with the program name + shapes, and latches the
        firing gauge. Requires ``xla_ledger`` (programs must have been
        wrapped at construction)."""
        if self.sentinel is None:
            raise RuntimeError(
                "recompile sentinel requires the XLA ledger; construct "
                "with InferenceEngine(..., xla_ledger=True)"
            )
        self.sentinel.arm()
        return self.sentinel

    # ------------------------------------------------------- elastic hooks

    def stop_admission(self) -> None:
        """First act of the drain protocol: submit() rejects with
        :class:`~.admission.EngineDraining` from now on. Idempotent."""
        self.admission.close()

    def resume_admission(self) -> None:
        self.admission.reopen()

    def finish_inflight(self) -> List[int]:
        """Resolve the outstanding overlapped decode dispatch, if any (the
        one blocking readback), retiring whatever it finished. After this
        no request holds a PENDING placeholder — the quiescent point the
        snapshot codec and close() both need. Returns finished ids."""
        if self._inflight is None:
            return []
        return self._resolve_inflight()

    def drain(self):
        """Stop admission, finish the in-flight step, and return an
        :class:`~distributed_pytorch_tpu.serving.elastic.EngineSnapshot`
        of every still-live request — the SIGTERM-with-notice protocol.
        Convenience delegate; see ``serving/elastic.py`` for the pieces."""
        from distributed_pytorch_tpu.serving.elastic import drain_engine

        return drain_engine(self)

    # --------------------------------------------------------- postmortems

    def _dump_postmortem(self, reason: str):
        """Write the flight-recorder ring (plus a goodput report and a
        registry snapshot) as a postmortem document. No-op without a
        recorder; never raises — a failed postmortem must not mask the
        failure being documented."""
        if not self.flight.enabled:
            return None
        try:
            extra = {}
            if self.goodput is not None:
                extra["goodput"] = self.goodput.report()
            extra["registry"] = self.registry.snapshot()
            return self.flight.dump(reason, extra=extra)
        except Exception:
            return None

    def _on_chaos_fault(self, kind: str, step: int, mode: str) -> None:
        """Chaos fault observer — runs BEFORE the fault signal/raise, so
        the dump survives even a SIGKILL drill."""
        self.flight.record(
            "chaos_fault", fault_kind=kind, step=step, mode=mode
        )
        self._dump_postmortem(f"chaos:{kind}")

    def _flush_on_crash(self, reason: str, exc: BaseException) -> None:
        """Last-gasp flush for unhandled exceptions escaping the engine
        loop: record the exception, dump the postmortem, save the trace.
        Every step is best-effort — the original exception re-raises."""
        if self.flight.enabled:
            self.flight.record(
                "exception", reason=reason, error=repr(exc)
            )
        self._dump_postmortem(reason)
        if self.tracer.enabled and self.trace_path:
            try:
                self.tracer.save(self.trace_path)
            except Exception:
                pass

    def close(self) -> None:
        """Deterministic teardown: resolve the in-flight overlapped
        dispatch (no dangling device readback), stop admission, cancel
        every non-terminal request (pages back to the allocator), assert
        via the allocator gauges that zero pages leaked, dump the flight
        recorder, and flush the tracer to ``trace_path`` when one was
        configured. Idempotent; runs automatically on
        ``with InferenceEngine(...) as eng:`` exit."""
        if self._closed:
            return
        with self.registry.lock:
            self.finish_inflight()
            self.stop_admission()
            for req in (
                list(self.scheduler.waiting) + self.scheduler.running
            ):
                self.scheduler.cancel(req)
            self._closed = True
            if self.hostkv is not None:
                # Spills dispatched by the cancellation sweep above (or a
                # final step) must reach the host buffers and the ledger
                # before the leak gates run.
                spilled = self.hostkv.drain_spills()
                if spilled and self.xla is not None:
                    self.xla.count_d2h(spilled, tag="hostkv_spill")
            self.allocator.assert_quiescent()
            if self.hostkv is not None:
                self.hostkv.assert_quiescent()
            if self.flight.enabled:
                chaos.remove_fault_observer(self._on_chaos_fault)
                self._dump_postmortem("close")
            if self.tracer.enabled and self.trace_path:
                self.tracer.save(self.trace_path)
        if self.sentinel is not None:
            self.sentinel.disarm()
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def run(self, max_steps: int = 10_000) -> List[int]:
        """Drive :meth:`step` until the engine drains; returns every
        request id finished along the way. ``max_steps`` bounds a scheduling
        bug to a loud failure instead of a hang. An exception escaping the
        loop flushes the tracer and dumps the flight recorder before
        re-raising — crashes leave a postmortem, not just a traceback."""
        finished: List[int] = []
        steps = 0
        try:
            while self.scheduler.has_work or self._inflight is not None:
                if steps >= max_steps:
                    raise RuntimeError(
                        f"engine did not drain within {max_steps} steps "
                        f"({self.scheduler.num_waiting} waiting, "
                        f"{len(self.scheduler.running)} running)"
                    )
                finished.extend(self.step())
                steps += 1
        except BaseException as exc:
            self._flush_on_crash("exception", exc)
            raise
        return finished

    def stats(self) -> Dict[str, float]:
        """Metrics snapshot + admission counters + cache pressure +
        prefix-cache hit rates."""
        out = self.metrics.snapshot()
        out.update(self.admission.counters())
        out["preemptions"] = self.scheduler.preemptions
        out["expired"] = self.scheduler.expired
        out["cancelled"] = self.scheduler.cancelled
        out["drains"] = self.drains
        out["restores"] = self.restores
        out["requests_recovered"] = self.requests_recovered
        out["cow_copies"] = self.scheduler.cow_copies
        out["pages_free"] = self.allocator.num_free
        out["pages_allocated"] = self.allocator.num_allocated
        out["pages_idle"] = self.allocator.num_idle
        out["page_evictions"] = self.allocator.evictions
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.stats())
        if self.hostkv is not None:
            out.update(self.hostkv.counters())
        if self.goodput is not None:
            gp = self.goodput.report()
            out["goodput_fraction"] = gp["goodput_fraction"]
            out["goodput_productive_s"] = gp["productive_s"]
            out["goodput_wasted_s"] = gp["wasted_total_s"]
            out["goodput_mfu"] = gp["mfu"]
            out["goodput_tokens_per_sec_per_device"] = gp[
                "tokens_per_sec_per_device"
            ]
        return out

    def save_trace(self, path: str) -> str:
        """Write the Perfetto trace to ``path`` (see
        :meth:`~distributed_pytorch_tpu.obs.Tracer.save`). Raises unless
        the engine was constructed with a :class:`Tracer`."""
        if not self.tracer.enabled:
            raise RuntimeError(
                "engine has no tracer; construct with "
                "InferenceEngine(..., tracer=Tracer()) to record"
            )
        return self.tracer.save(path)
