"""The continuous-batching inference engine: host orchestration around a
fixed-shape jit decode step.

``submit(prompt, params) -> request_id`` / ``step()`` / ``poll(request_id)``.
Every ``step()``:

1. asks the :class:`~.scheduler.Scheduler` for a plan (admission, chunked
   prefill under the token budget, the batched decode set, preemption);
2. executes the prefill chunks — each a ``[1, C]`` jit call writing K/V into
   the request's pages (logits dead-code-eliminated), compiled once per
   power-of-two chunk size;
3. executes ONE batched decode step over all ``max_slots`` slots — inactive
   slots are padded (null block table, length 0) and masked, so the decode
   program compiles exactly once regardless of which requests are live;
4. harvests sampled tokens host-side, retires finished requests, records
   TTFT/TPOT/e2e.

The decode math is :func:`~distributed_pytorch_tpu.generation
.decode_token_step` — the SAME single-token step ``generate()``'s offline
loop runs — against the paged cache, so continuous batching is
token-for-token identical to offline decode (pinned by
``tests/test_serving.py`` on CPU).

Sampling determinism: each request gets ``PRNGKey(seed)`` and token i is
drawn with ``fold_in(key, i)`` — independent of batch composition, slot
assignment, and preemption, so a preempted-then-resumed request reproduces
its exact stream.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.generation import (
    decode_token_step,
    truncate_logits,
)
from distributed_pytorch_tpu.serving.admission import (
    AdmissionController,
    ServingMetrics,
)
from distributed_pytorch_tpu.serving.kv_cache import PagedBlockAllocator
from distributed_pytorch_tpu.serving.scheduler import (
    Request,
    SamplingParams,
    Scheduler,
)


@dataclasses.dataclass(frozen=True)
class RequestStatus:
    """Snapshot returned by :meth:`InferenceEngine.poll`."""

    req_id: int
    state: str
    prompt_len: int
    generated: List[int]
    finished: bool
    preempt_count: int


class InferenceEngine:
    """Continuous-batching engine over a paged KV cache.

    ``model`` is the TRAINING-mode module (same contract as ``generate``);
    it is cloned with ``decode=True, page_size, num_pages`` internally.
    ``num_pages`` defaults to exactly enough pages for every slot to hold
    ``max_seq_len`` tokens (+1 for the reserved null page) — i.e. no
    overcommit; pass a smaller value to exercise preemption.

    ``top_k``/``top_p`` are engine-static (compiled into the decode step);
    temperature and seed are per-request (:class:`SamplingParams`).
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        max_seq_len: int = 256,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        token_budget: int = 64,
        max_prefill_chunk: int = 32,
        max_queue: int = 128,
        top_k: int = 0,
        top_p: float = 0.0,
    ):
        if max_seq_len % page_size:
            raise ValueError(
                f"max_seq_len {max_seq_len} must be a multiple of "
                f"page_size {page_size}"
            )
        self.pages_per_seq = max_seq_len // page_size
        if num_pages is None:
            num_pages = max_slots * self.pages_per_seq + 1
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.params = params
        self._top_k = int(top_k)
        self._top_p = float(top_p)

        self.decode_model = model.clone(
            decode=True, page_size=page_size, num_pages=num_pages
        )
        # Size the paged pool from abstract shapes only (eval_shape traces
        # init without running it); token length 1 — pool shapes depend only
        # on (num_pages, page_size), never on the init input.
        abstract = jax.eval_shape(
            self.decode_model.init,
            jax.random.PRNGKey(0),
            jnp.zeros((max_slots, 1), jnp.int32),
        )["cache"]
        self.cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), abstract
        )

        self.allocator = PagedBlockAllocator(num_pages)
        self.scheduler = Scheduler(
            self.allocator,
            max_slots=max_slots,
            page_size=page_size,
            pages_per_seq=self.pages_per_seq,
            token_budget=token_budget,
            max_prefill_chunk=max_prefill_chunk,
        )
        self.admission = AdmissionController(
            max_queue=max_queue, max_request_tokens=max_seq_len
        )
        self.metrics = ServingMetrics()
        self.requests: Dict[int, Request] = {}
        self._next_id = 0
        self._keys: Dict[int, jax.Array] = {}

    # ------------------------------------------------------------- compiled

    @functools.cached_property
    def _decode_step(self):
        """THE batched decode program: one compile for the engine's
        lifetime. Greedy and sampled rows coexist via a per-slot temperature
        vector (0 = greedy) so slot composition never re-specializes it."""
        top_k, top_p = self._top_k, self._top_p

        def run(params, cache, tokens, tables, lens, temps, keys):
            last_logits, cache = decode_token_step(
                self.decode_model, params, cache, tokens[:, None],
                block_tables=tables, seq_lens=lens,
            )
            greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            safe_t = jnp.where(temps > 0, temps, 1.0)
            scaled = truncate_logits(
                last_logits / safe_t[:, None], top_k, top_p
            )
            sampled = jax.vmap(jax.random.categorical)(keys, scaled)
            nxt = jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)
            return nxt, cache

        return jax.jit(run, donate_argnums=(1,))

    @functools.lru_cache(maxsize=16)
    def _prefill_step(self, chunk: int):
        """One compile per power-of-two chunk length; returns only the
        updated cache, so XLA prunes the LM head from the program."""

        def run(params, cache, tokens, table, length):
            _, cache = decode_token_step(
                self.decode_model, params, cache, tokens,
                block_tables=table, seq_lens=length,
            )
            return cache

        return jax.jit(run, donate_argnums=(1,))

    # ----------------------------------------------------------------- API

    def submit(
        self,
        prompt: Sequence[int],
        params: Optional[SamplingParams] = None,
    ) -> int:
        """Queue one request; returns its id. Raises
        :class:`~.admission.QueueFull` (backpressure) or
        :class:`~.admission.RequestTooLong` (can never fit) — admission is
        decided NOW, not at first schedule."""
        params = params or SamplingParams()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.admission.check(len(prompt), params, self.scheduler.num_waiting)
        req = Request(
            req_id=self._next_id,
            prompt=prompt,
            params=params,
            submit_time=time.perf_counter(),
        )
        self._next_id += 1
        self.requests[req.req_id] = req
        self._keys[req.req_id] = jax.random.PRNGKey(params.seed)
        self.scheduler.add(req)
        return req.req_id

    def step(self) -> List[int]:
        """Run one engine iteration; returns ids of requests that FINISHED
        during it. A no-op (empty list) when nothing is queued or running."""
        plan = self.scheduler.schedule()
        if plan.empty:
            return []

        for slot, chunk in plan.prefill:
            req = self.scheduler.slots[slot]
            start = req.len_cached
            tok = np.asarray(
                [req.tokens[start : start + chunk]], np.int32
            )
            table = req.table.as_row(self.pages_per_seq)[None]
            self.cache = self._prefill_step(chunk)(
                self.params, self.cache, jnp.asarray(tok),
                jnp.asarray(table), jnp.asarray([start], jnp.int32),
            )
            self.scheduler.note_prefilled(slot, chunk)

        finished: List[int] = []
        if plan.decode_slots:
            tokens = np.zeros((self.max_slots,), np.int32)
            tables = np.zeros(
                (self.max_slots, self.pages_per_seq), np.int32
            )
            lens = np.zeros((self.max_slots,), np.int32)
            temps = np.zeros((self.max_slots,), np.float32)
            keys = np.zeros((self.max_slots, 2), np.uint32)
            for slot in plan.decode_slots:
                req = self.scheduler.slots[slot]
                tokens[slot] = req.tokens[req.len_cached]
                tables[slot] = req.table.as_row(self.pages_per_seq)
                lens[slot] = req.len_cached
                temps[slot] = req.params.temperature
                keys[slot] = np.asarray(
                    jax.random.fold_in(
                        self._keys[req.req_id], req.n_generated
                    ),
                    np.uint32,
                )
            nxt, self.cache = self._decode_step(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(tables), jnp.asarray(lens),
                jnp.asarray(temps), jnp.asarray(keys),
            )
            nxt_host = np.asarray(nxt)  # device sync point
            now = time.perf_counter()
            for slot in plan.decode_slots:
                done = self.scheduler.note_decoded(
                    slot, int(nxt_host[slot]), now=now
                )
                if done is not None:
                    self.scheduler.retire(done, now=now)
                    self.metrics.observe_finished(done)
                    self._keys.pop(done.req_id, None)
                    finished.append(done.req_id)
        self.metrics.observe_step(new_tokens=len(plan.decode_slots))
        return finished

    def poll(self, req_id: int) -> RequestStatus:
        req = self.requests[req_id]
        return RequestStatus(
            req_id=req_id,
            state=req.state.value,
            prompt_len=len(req.prompt),
            generated=list(req.generated),
            finished=req.done,
            preempt_count=req.preempt_count,
        )

    def run(self, max_steps: int = 10_000) -> List[int]:
        """Drive :meth:`step` until the engine drains; returns every
        request id finished along the way. ``max_steps`` bounds a scheduling
        bug to a loud failure instead of a hang."""
        finished: List[int] = []
        steps = 0
        while self.scheduler.has_work:
            if steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps "
                    f"({self.scheduler.num_waiting} waiting, "
                    f"{len(self.scheduler.running)} running)"
                )
            finished.extend(self.step())
            steps += 1
        return finished

    def stats(self) -> Dict[str, float]:
        """Metrics snapshot + admission counters + cache pressure."""
        out = self.metrics.snapshot()
        out.update(self.admission.counters())
        out["preemptions"] = self.scheduler.preemptions
        out["pages_free"] = self.allocator.num_free
        out["pages_allocated"] = self.allocator.num_allocated
        return out
