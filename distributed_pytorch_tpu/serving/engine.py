"""The continuous-batching inference engine: host orchestration around a
fixed-shape jit decode step.

``submit(prompt, params) -> request_id`` / ``step()`` / ``poll(request_id)``.
Every ``step()``:

1. asks the :class:`~.scheduler.Scheduler` for a plan (admission with
   prefix-cache lookup, copy-on-write page copies, chunked prefill under
   the token budget, the batched decode set, preemption);
2. executes the CoW copies — one compiled page-copy program per shared page
   a writer is about to extend;
3. executes the prefill chunks — each a ``[1, C]`` jit call writing K/V into
   the request's pages (logits dead-code-eliminated), compiled once per
   power-of-two chunk size, starting at the first token the prefix cache
   did not already cover;
4. dispatches ONE batched decode step over all ``max_slots`` slots —
   inactive slots are padded (null block table, length 0) and masked, so
   the decode program compiles exactly once regardless of which requests
   are live;
5. resolves the PREVIOUS step's decode readback (overlapped stepping: the
   blocking ``np.asarray`` lands while the device chews on the decode just
   dispatched), retires finished requests, records TTFT/TPOT/e2e.

Overlap mechanics: the sampled-token vector from step N is fed back into
step N+1 as a device-resident ``prev`` argument — each slot's input token
is ``where(use_prev, prev[slot], host_token)`` — so a decoding sequence's
next input never round-trips through the host. Host bookkeeping tracks the
dispatch with a PENDING placeholder that :meth:`Scheduler.resolve_decoded`
fills in one step later. ``overlap=False`` resolves synchronously (same
compiled program; ``use_prev`` is simply always 0), which is also the
behavior under a scheduler that never redispatches an unresolved slot.

The decode math is :func:`~distributed_pytorch_tpu.generation
.decode_token_step` — the SAME single-token step ``generate()``'s offline
loop runs — against the paged cache, so continuous batching is
token-for-token identical to offline decode (pinned by
``tests/test_serving.py`` on CPU), with or without prefix caching and
overlap.

Sampling determinism: each request gets ``PRNGKey(seed)`` and token i is
drawn with ``fold_in(key, i)`` — independent of batch composition, slot
assignment, and preemption, so a preempted-then-resumed request reproduces
its exact stream. Under overlap the fold index is the DISPATCH count
(``n_issued``), which equals the generated count at the same point of the
synchronous schedule.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu.generation import (
    decode_token_step,
    truncate_logits,
)
from distributed_pytorch_tpu.serving.admission import (
    AdmissionController,
    ServingMetrics,
)
from distributed_pytorch_tpu.serving.kv_cache import (
    PagedBlockAllocator,
    PrefixCache,
)
from distributed_pytorch_tpu.serving.scheduler import (
    PENDING_TOKEN,
    Request,
    SamplingParams,
    Scheduler,
)


@dataclasses.dataclass(frozen=True)
class RequestStatus:
    """Snapshot returned by :meth:`InferenceEngine.poll`."""

    req_id: int
    state: str
    prompt_len: int
    generated: List[int]
    finished: bool
    preempt_count: int


class InferenceEngine:
    """Continuous-batching engine over a paged KV cache.

    ``model`` is the TRAINING-mode module (same contract as ``generate``);
    it is cloned with ``decode=True, page_size, num_pages`` internally.
    ``num_pages`` defaults to exactly enough pages for every slot to hold
    ``max_seq_len`` tokens (+1 for the reserved null page) — i.e. no
    overcommit; pass a smaller value to exercise preemption and cache
    eviction.

    ``prefix_cache=True`` shares page-aligned K/V across requests with a
    common prompt prefix (retired pages idle on an LRU instead of freeing);
    ``overlap=True`` defers each decode readback by one step so host
    scheduling hides under device compute. Both default on — outputs are
    bitwise-identical either way. ``debug=True`` re-enables the
    O(num_pages) allocator invariant sweep after every schedule.

    ``top_k``/``top_p`` are engine-static (compiled into the decode step);
    temperature and seed are per-request (:class:`SamplingParams`).
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        max_seq_len: int = 256,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        token_budget: int = 64,
        max_prefill_chunk: int = 32,
        max_queue: int = 128,
        max_queue_tokens: Optional[int] = None,
        top_k: int = 0,
        top_p: float = 0.0,
        prefix_cache: bool = True,
        overlap: bool = True,
        debug: bool = False,
    ):
        if max_seq_len % page_size:
            raise ValueError(
                f"max_seq_len {max_seq_len} must be a multiple of "
                f"page_size {page_size}"
            )
        self.pages_per_seq = max_seq_len // page_size
        if num_pages is None:
            num_pages = max_slots * self.pages_per_seq + 1
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.params = params
        self.overlap = overlap
        self._top_k = int(top_k)
        self._top_p = float(top_p)

        self.decode_model = model.clone(
            decode=True, page_size=page_size, num_pages=num_pages
        )
        # Size the paged pool from abstract shapes only (eval_shape traces
        # init without running it); token length 1 — pool shapes depend only
        # on (num_pages, page_size), never on the init input.
        abstract = jax.eval_shape(
            self.decode_model.init,
            jax.random.PRNGKey(0),
            jnp.zeros((max_slots, 1), jnp.int32),
        )["cache"]
        self.cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), abstract
        )

        self.allocator = PagedBlockAllocator(num_pages)
        self.prefix_cache = (
            PrefixCache(self.allocator, page_size) if prefix_cache else None
        )
        self.scheduler = Scheduler(
            self.allocator,
            max_slots=max_slots,
            page_size=page_size,
            pages_per_seq=self.pages_per_seq,
            token_budget=token_budget,
            max_prefill_chunk=max_prefill_chunk,
            prefix_cache=self.prefix_cache,
            debug=debug,
        )
        self.admission = AdmissionController(
            max_queue=max_queue,
            max_request_tokens=max_seq_len,
            max_queue_tokens=max_queue_tokens,
        )
        self.metrics = ServingMetrics()
        self.requests: Dict[int, Request] = {}
        self._next_id = 0
        self._keys: Dict[int, jax.Array] = {}

        # Reusable host staging buffers for the batched decode inputs —
        # refilled in place every step instead of reallocated. Rows for
        # inactive slots MUST be re-zeroed each step (a stale block-table
        # row would scatter the masked write into a page some other request
        # now owns); jnp.asarray copies host->device, so mutating these
        # after dispatch is safe.
        self._stage_tokens = np.zeros((max_slots,), np.int32)
        self._stage_tables = np.zeros(
            (max_slots, self.pages_per_seq), np.int32
        )
        self._stage_lens = np.zeros((max_slots,), np.int32)
        self._stage_temps = np.zeros((max_slots,), np.float32)
        self._stage_keys = np.zeros((max_slots, 2), np.uint32)
        self._stage_use_prev = np.zeros((max_slots,), np.int32)
        self._zero_prev = jnp.zeros((max_slots,), jnp.int32)
        # (sampled-token device array, decode slots, their requests) of the
        # not-yet-resolved dispatch, or None.
        self._inflight: Optional[
            Tuple[jax.Array, List[int], List[Request]]
        ] = None

    # ------------------------------------------------------------- compiled

    @functools.cached_property
    def _decode_step(self):
        """THE batched decode program: one compile for the engine's
        lifetime. Greedy and sampled rows coexist via a per-slot temperature
        vector (0 = greedy); ``prev``/``use_prev`` splice the previous
        step's device-resident samples in as inputs so overlapped slots
        never wait on a host readback."""
        top_k, top_p = self._top_k, self._top_p

        def run(params, cache, tokens, prev, use_prev, tables, lens, temps,
                keys):
            tok = jnp.where(use_prev > 0, prev, tokens)
            last_logits, cache = decode_token_step(
                self.decode_model, params, cache, tok[:, None],
                block_tables=tables, seq_lens=lens,
            )
            greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            safe_t = jnp.where(temps > 0, temps, 1.0)
            scaled = truncate_logits(
                last_logits / safe_t[:, None], top_k, top_p
            )
            sampled = jax.vmap(jax.random.categorical)(keys, scaled)
            nxt = jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)
            return nxt, cache

        return jax.jit(run, donate_argnums=(1,))

    @functools.lru_cache(maxsize=16)
    def _prefill_step(self, chunk: int):
        """One compile per power-of-two chunk length; returns only the
        updated cache, so XLA prunes the LM head from the program."""

        def run(params, cache, tokens, table, length):
            _, cache = decode_token_step(
                self.decode_model, params, cache, tokens,
                block_tables=table, seq_lens=length,
            )
            return cache

        return jax.jit(run, donate_argnums=(1,))

    @functools.cached_property
    def _copy_page(self):
        """Copy one physical page across every layer's K/V pool — the
        device half of copy-on-write. Page ids are traced scalars, so this
        compiles exactly once."""

        def run(cache, src, dst):
            return jax.tree_util.tree_map(
                lambda pool: pool.at[dst].set(pool[src]), cache
            )

        return jax.jit(run, donate_argnums=(0,))

    # ----------------------------------------------------------------- API

    def submit(
        self,
        prompt: Sequence[int],
        params: Optional[SamplingParams] = None,
    ) -> int:
        """Queue one request; returns its id. Raises
        :class:`~.admission.QueueFull` (backpressure) or
        :class:`~.admission.RequestTooLong` (can never fit) — admission is
        decided NOW, not at first schedule, and counts the currently-cached
        prefix: a shared-prompt request costs only its uncached tail of
        prefill work against the queue-token budget."""
        params = params or SamplingParams()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        cached = 0
        if self.prefix_cache is not None and prompt:
            cached = self.prefix_cache.peek(prompt)
        self.admission.check(
            len(prompt), params, self.scheduler.num_waiting,
            cached_tokens=cached,
            queued_uncached_tokens=sum(
                r.est_uncached for r in self.scheduler.waiting
            ),
        )
        req = Request(
            req_id=self._next_id,
            prompt=prompt,
            params=params,
            submit_time=time.perf_counter(),
            est_uncached=max(0, len(prompt) - 1 - cached),
        )
        self._next_id += 1
        self.requests[req.req_id] = req
        self._keys[req.req_id] = jax.random.PRNGKey(params.seed)
        self.scheduler.add(req)
        return req.req_id

    def _resolve_inflight(self) -> List[int]:
        """Read back the outstanding decode dispatch (the ONE blocking
        device sync — under overlap it lands while the next step computes),
        fill in sampled tokens, retire what finished."""
        nxt, slots, reqs = self._inflight
        self._inflight = None
        nxt_host = np.asarray(nxt)
        now = time.perf_counter()
        finished: List[int] = []
        for slot, req in zip(slots, reqs):
            done = self.scheduler.resolve_decoded(
                req, int(nxt_host[slot]), now=now
            )
            if done is not None:
                self.scheduler.retire(done, now=now)
                self.metrics.observe_finished(done)
                self._keys.pop(done.req_id, None)
                finished.append(done.req_id)
        return finished

    def step(self) -> List[int]:
        """Run one engine iteration; returns ids of requests that FINISHED
        during it (under overlap, a finish surfaces on the step after its
        token was dispatched). A no-op (empty list) when nothing is queued,
        running, or in flight."""
        plan = self.scheduler.schedule()

        for _slot, src, dst in plan.copies:
            self.cache = self._copy_page(
                self.cache,
                jnp.asarray(src, jnp.int32),
                jnp.asarray(dst, jnp.int32),
            )

        if plan.empty:
            # Nothing to dispatch — drain the outstanding readback (e.g.
            # the final token of the last request) before reporting idle.
            return (
                self._resolve_inflight() if self._inflight is not None
                else []
            )

        for slot, chunk in plan.prefill:
            req = self.scheduler.slots[slot]
            start = req.len_cached
            tok = np.asarray(
                [req.tokens[start : start + chunk]], np.int32
            )
            table = req.table.as_row(self.pages_per_seq)[None]
            self.cache = self._prefill_step(chunk)(
                self.params, self.cache, jnp.asarray(tok),
                jnp.asarray(table), jnp.asarray([start], jnp.int32),
            )
            self.scheduler.note_prefilled(slot, chunk)

        finished: List[int] = []
        dispatched = None
        if plan.decode_slots:
            self._stage_tables.fill(0)
            self._stage_lens.fill(0)
            self._stage_use_prev.fill(0)
            for slot in plan.decode_slots:
                req = self.scheduler.slots[slot]
                pos = req.len_cached
                tok = req.tokens[pos]
                if tok == PENDING_TOKEN:
                    # Input is last step's still-in-flight sample: select
                    # it device-side from ``prev``.
                    self._stage_use_prev[slot] = 1
                    self._stage_tokens[slot] = 0
                else:
                    self._stage_tokens[slot] = tok
                self._stage_tables[slot] = req.table.as_row(
                    self.pages_per_seq
                )
                self._stage_lens[slot] = pos
                self._stage_temps[slot] = req.params.temperature
                self._stage_keys[slot] = np.asarray(
                    jax.random.fold_in(
                        self._keys[req.req_id], req.n_issued
                    ),
                    np.uint32,
                )
            prev = (
                self._inflight[0] if self._inflight is not None
                else self._zero_prev
            )
            nxt, self.cache = self._decode_step(
                self.params, self.cache,
                jnp.asarray(self._stage_tokens), prev,
                jnp.asarray(self._stage_use_prev),
                jnp.asarray(self._stage_tables),
                jnp.asarray(self._stage_lens),
                jnp.asarray(self._stage_temps),
                jnp.asarray(self._stage_keys),
            )
            dispatched = (
                nxt,
                list(plan.decode_slots),
                [
                    self.scheduler.note_decode_dispatched(s)
                    for s in plan.decode_slots
                ],
            )
        # Resolve LAST step's tokens now — the np.asarray sync overlaps
        # with the decode dispatched above.
        if self._inflight is not None:
            finished.extend(self._resolve_inflight())
        self._inflight = dispatched
        if not self.overlap and self._inflight is not None:
            finished.extend(self._resolve_inflight())
        self.metrics.observe_step(new_tokens=len(plan.decode_slots))
        return finished

    def poll(self, req_id: int) -> RequestStatus:
        req = self.requests[req_id]
        return RequestStatus(
            req_id=req_id,
            state=req.state.value,
            prompt_len=len(req.prompt),
            generated=list(req.generated),
            finished=req.done,
            preempt_count=req.preempt_count,
        )

    def run(self, max_steps: int = 10_000) -> List[int]:
        """Drive :meth:`step` until the engine drains; returns every
        request id finished along the way. ``max_steps`` bounds a scheduling
        bug to a loud failure instead of a hang."""
        finished: List[int] = []
        steps = 0
        while self.scheduler.has_work or self._inflight is not None:
            if steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps "
                    f"({self.scheduler.num_waiting} waiting, "
                    f"{len(self.scheduler.running)} running)"
                )
            finished.extend(self.step())
            steps += 1
        return finished

    def stats(self) -> Dict[str, float]:
        """Metrics snapshot + admission counters + cache pressure +
        prefix-cache hit rates."""
        out = self.metrics.snapshot()
        out.update(self.admission.counters())
        out["preemptions"] = self.scheduler.preemptions
        out["cow_copies"] = self.scheduler.cow_copies
        out["pages_free"] = self.allocator.num_free
        out["pages_allocated"] = self.allocator.num_allocated
        out["pages_idle"] = self.allocator.num_idle
        out["page_evictions"] = self.allocator.evictions
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.stats())
        return out
