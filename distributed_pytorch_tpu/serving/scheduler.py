"""Request-level continuous-batching scheduler.

The host-side policy half of the engine: maintains the waiting queue and the
active slot set, interleaves chunked prefill with batched decode under a
per-step token budget, preempts under page pressure, and retires finished
sequences every step so new requests join mid-flight.

Design decisions, in the order they bite:

* **Priority = submission order** (request id, lower wins). Preemption only
  ever evicts a strictly LOWER-priority victim than the sequence that needs
  pages — or, failing that, preempts the requester itself — so the oldest
  running request always makes forward progress and two cache-hungry
  requests cannot livelock trading pages.
* **Decode before prefill in the budget**: every DECODE-state slot reserves
  one token of the step budget first, then the remainder goes to prefill
  chunks. Running sequences never starve (TPOT stays flat), while admitted
  prompts still chunk in within a bounded number of steps (TTFT bounded by
  prompt_len / leftover_budget).
* **Prefill covers positions [0, L-1)** of a request's token list; the LAST
  token always goes through the shared batched decode step, whose sampled
  output is the first new token. This mirrors ``generate``'s serial loop
  exactly (the body at position t decides token t+1), which is what makes
  served output token-identical to offline decode.
* **Chunks are power-of-two sized** (greedy decomposition, capped at
  ``max_prefill_chunk``), so the engine compiles at most log2(cap)+1 prefill
  variants — the "one compilation per shape bucket" contract.
* **Prefill starts at the first uncached token**: with a
  :class:`~.kv_cache.PrefixCache` attached, admission looks the request's
  tokens up in the trie and adopts (refs) every matched page, so a shared
  system prompt is prefilled once fleet-wide. A writer about to extend a
  SHARED page (refcount > 1 — concurrent extenders of a cached partial
  page) gets a copy-on-write entry in the plan first; pages it owns alone
  are extended in place.
* **Preempted sequences keep their generated tokens** and re-enter the
  waiting queue at their original priority; on re-admission the prefix
  cache usually re-serves the pages they just released (release only idles
  registered pages), so re-prefill cost shrinks to the uncached tail.
* **Decode results may resolve a step late** (the engine's overlapped
  loop): :meth:`note_decode_dispatched` advances the host-known state
  (cache position, a PENDING placeholder token) at dispatch, and
  :meth:`resolve_decoded` fills in the sampled value when the device
  readback lands. Everything the planner needs (page pressure, budget,
  max_new_tokens) is host-known at dispatch; only stop-token detection
  waits for the value, costing at most one speculative decode step that
  :meth:`resolve_decoded` rolls back.
* **Speculative decode rows advance by a VARIABLE amount** (``gamma > 0``):
  one scheduled "decode" is a whole draft+verify round that writes
  ``gamma`` K/V positions and emits 1..gamma tokens, so the budget charges
  ``gamma`` per running row and :meth:`_ensure_pages` covers the full
  chunk (``len_cached + gamma``). Acceptance resolves PER ROW via
  :meth:`resolve_spec` — a row that accepted its whole chunk advances by
  gamma while its neighbor advances by 1; no minimum-across-batch stall.
  Rollback of the rejected tail is free: ``len_cached`` simply advances by
  the emitted count, and K/V written past it is masked (and overwritten
  write-then-attend when the real continuation is fed). The prefix trie
  only ever registers pages fully below ``len_cached``, so rejected
  garbage can never be cached, and copy-on-write is decided on the one
  page containing ``len_cached`` exactly as in the single-token path —
  every later page a round touches was freshly allocated for this row.
"""

from __future__ import annotations

import bisect
import dataclasses
import enum
import time
from typing import List, Optional, Tuple

from distributed_pytorch_tpu.obs.flight import NULL_FLIGHT_RECORDER
from distributed_pytorch_tpu.obs.tracer import NULL_TRACER
from distributed_pytorch_tpu.serving.kv_cache import (
    BlockTable,
    OutOfPages,
    PagedBlockAllocator,
    PrefixCache,
)

# Placeholder for a sampled token whose device readback has not landed yet
# (overlapped stepping). Never a valid vocab id; never visible through
# poll() — ``generated`` only ever holds resolved values.
PENDING_TOKEN = -1


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request generation parameters. ``temperature <= 0`` is greedy;
    ``seed`` drives a per-request RNG folded with the token index, so a
    request's sampled stream is independent of batch composition and
    survives preemption. ``top_k``/``top_p`` are engine-level (static in the
    compiled step), not per-request. ``deadline_s`` is a wall-clock budget
    from submission: a request still unfinished after that many seconds is
    retired with the EXPIRED terminal state at the next schedule pass and
    its pages freed (partial output stays pollable). ``stop_sequences``
    generalizes ``stop_token`` to multi-token suffixes: the request
    finishes when its generated tail matches any sequence (the matching
    tokens stay in the output, same as a stop token). Detection is
    host-side at resolve time, so it composes with every engine mode
    including speculative decoding."""

    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0
    stop_token: Optional[int] = None
    deadline_s: Optional[float] = None
    stop_sequences: Tuple[Tuple[int, ...], ...] = ()


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"
    # Terminal without completing: deadline elapsed / cancelled (client
    # cancel, engine close). Partial output remains pollable; pages freed.
    EXPIRED = "expired"
    CANCELLED = "cancelled"


# States from which a request never runs again. A late decode readback for a
# terminal request resolves harmlessly (resolve_decoded discards the value).
_TERMINAL = (
    RequestState.FINISHED,
    RequestState.EXPIRED,
    RequestState.CANCELLED,
)


def _adapter_bound(req: "Request") -> bool:
    """True when ``req`` decodes under LoRA-merged weights. Its K/V is
    computed under DIFFERENT params than base-model requests', so it must
    neither read from nor publish to the token-keyed prefix trie — a
    token-identical prefix under other weights is not the same cache
    entry."""
    mods = req.mods
    return mods is not None and getattr(mods, "adapter", None) is not None


def _flight_trace(req: "Request") -> dict:
    """Flight-recorder stamp for the fleet trace identity: ``{}`` for
    untraced requests (dump shape unchanged), ``{"trace_id": ...}`` when
    the request carries one — so ``replay_to_tracer()`` output merges into
    the fleet trace and a dead replica's last moments land on the victim
    request's waterfall."""
    return {"trace_id": req.trace_id} if req.trace_id is not None else {}


def _stops_on_sequence(req: "Request") -> bool:
    """True when ``req.generated`` ends with any of its stop sequences."""
    gen = req.generated
    for seq in req.params.stop_sequences:
        n = len(seq)
        if n and len(gen) >= n and tuple(gen[-n:]) == tuple(seq):
            return True
    return False


@dataclasses.dataclass
class Request:
    """One in-flight generation request. ``tokens`` = prompt + generated;
    ``len_cached`` counts how many of them have K/V in the paged cache.
    Invariant while in DECODE state: ``len_cached == len(tokens) - 1`` — the
    next decode step feeds ``tokens[len_cached]`` and appends the sample
    (as :data:`PENDING_TOKEN` until the readback resolves it)."""

    req_id: int
    prompt: List[int]
    params: SamplingParams
    tokens: List[int] = dataclasses.field(default_factory=list)
    generated: List[int] = dataclasses.field(default_factory=list)
    len_cached: int = 0
    table: BlockTable = dataclasses.field(default_factory=BlockTable)
    state: RequestState = RequestState.WAITING
    slot: Optional[int] = None
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    preempt_count: int = 0
    # Positions in ``tokens`` holding PENDING_TOKEN, oldest first — decode
    # dispatches whose sampled value has not been read back yet.
    pending_idx: List[int] = dataclasses.field(default_factory=list)
    # Prefix-trie cursor: the node covering the first ``trie_pages`` full
    # pages of ``tokens`` (matched at admission, advanced as pages fill).
    trie_node: int = PrefixCache.ROOT
    trie_pages: int = 0
    # Tokens served from the prefix cache at FIRST admission (None until
    # then; 0 = a clean miss) — the TTFT hit/miss split keys off this.
    cached_prompt_tokens: Optional[int] = None
    # Tokens staged from the HOST page tier at first admission (planned
    # h2d fetches instead of re-prefill). The TTFT source split labels
    # device hits first, then host, then miss.
    host_prompt_tokens: Optional[int] = None
    # Admission-time estimate of uncached prefill work (queue backpressure).
    est_uncached: int = 0
    # Tenant-opaque payload carried through scheduling untouched — and
    # through the elastic snapshot/restore codec, so routing/billing context
    # survives an engine migration. Must be JSON-serializable to snapshot.
    metadata: Optional[dict] = None
    # Typed tenant identity (the front door's fair-share / quota / SLO
    # key). Promoted out of ``metadata`` so drain/restore and fleet
    # failover preserve tenancy without convention.
    tenant_id: str = "anon"
    # Streaming high-water mark: how many of ``generated`` have been
    # handed to the client. A drain snapshot records it so a restored
    # stream resumes exactly here — no replayed or skipped tokens.
    delivered: int = 0
    # Live per-request model mods (duck-typed: the engine binds a
    # ``serving.mods.ModState`` here). The scheduler only calls
    # ``note_token(token) -> bool`` on committed tokens; True finishes
    # the request (e.g. a grammar reached a forced end).
    mods: Optional[object] = None
    # Goodput accounting: prefill positions below this mark re-compute K/V
    # the engine already had (lost to preemption or a snapshot/restore);
    # ``rework_kind`` names the waste bucket they charge to.
    rework_until: int = 0
    rework_kind: str = "preempt_rework"
    # Fleet-wide trace identity, minted a layer up (front door / router)
    # and carried unchanged across preemption, drain hand-off, hedge
    # twins, and failover id-rebasing — req_ids are engine-local and
    # rebased on adoption; this string is the one name a request keeps.
    trace_id: Optional[str] = None

    def __post_init__(self):
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.generated)

    @property
    def n_issued(self) -> int:
        """Sampled tokens requested from the device so far, including ones
        whose readback is pending — the planner's max_new_tokens guard."""
        return len(self.tokens) - len(self.prompt)

    @property
    def remaining_prefill(self) -> int:
        return len(self.tokens) - 1 - self.len_cached

    @property
    def done(self) -> bool:
        return self.state in _TERMINAL


@dataclasses.dataclass
class StepPlan:
    """One engine step's worth of device work: copy-on-write page copies
    (``(slot, src_page, dst_page)``, executed first), host-tier page
    fetches (``(key, dst_page, parent_node, tokens, node_id)``, h2d
    stages executed before any prefill/decode that could read them),
    prefill chunks (executed in order, each ``(slot, chunk_len)``), then
    one batched decode over ``decode_slots``. ``empty`` deliberately
    ignores ``fetches``: the engine executes them BEFORE its empty-plan
    early return, so a fetch planned for a request that was preempted in
    the same schedule still lands (the trie entry stays valid)."""

    copies: List[Tuple[int, int, int]] = dataclasses.field(
        default_factory=list
    )
    fetches: List[Tuple[str, int, int, Tuple[int, ...], int]] = (
        dataclasses.field(default_factory=list)
    )
    prefill: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    decode_slots: List[int] = dataclasses.field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.prefill and not self.decode_slots

def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 0


class Scheduler:
    """Waiting queue + slot set + page-pressure policy (see module doc).

    ``prefix_cache`` enables automatic prefix caching; ``gamma > 0``
    switches decode planning to speculative rounds (each scheduled decode
    writes ``gamma`` K/V positions and resolves 1..gamma tokens via
    :meth:`resolve_spec`); ``debug=True`` runs the O(num_pages) allocator
    invariant sweep after every :meth:`schedule` call — kept on in tests,
    off on the serving hot path.
    """

    def __init__(
        self,
        allocator: PagedBlockAllocator,
        *,
        max_slots: int,
        page_size: int,
        pages_per_seq: int,
        token_budget: int = 64,
        max_prefill_chunk: int = 32,
        prefix_cache: Optional[PrefixCache] = None,
        gamma: int = 0,
        debug: bool = False,
        tracer=NULL_TRACER,
        flight=NULL_FLIGHT_RECORDER,
    ):
        if token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got {token_budget}")
        if gamma < 0:
            raise ValueError(f"gamma must be >= 0, got {gamma}")
        if _pow2_floor(max_prefill_chunk) != max_prefill_chunk:
            raise ValueError(
                f"max_prefill_chunk must be a power of two, got "
                f"{max_prefill_chunk} (chunk sizes are compile-cache keys)"
            )
        self.allocator = allocator
        self.max_slots = max_slots
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.token_budget = token_budget
        self.max_prefill_chunk = max_prefill_chunk
        self.prefix_cache = prefix_cache
        self.gamma = gamma
        self.debug = debug
        self.tracer = tracer
        self.flight = flight
        self.waiting: List[Request] = []  # kept sorted by req_id
        self.slots: List[Optional[Request]] = [None] * max_slots
        self.preemptions = 0
        self.expired = 0
        self.cancelled = 0
        # Deadline sweeps cost a clock read + O(live) scan per schedule;
        # skip them entirely until a deadline-bearing request shows up.
        self._any_deadlines = False

    @property
    def cow_copies(self) -> int:
        """Lifetime copy-on-write splits (counted on the allocator — the
        page ledger of record — since the registry reads them there)."""
        return self.allocator.cow_copies

    # ------------------------------------------------------------- queries

    @property
    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or any(r is not None for r in self.slots)

    def describe_requests(
        self, now: Optional[float] = None
    ) -> List[dict]:
        """Per-request live-state for ``/statusz``: every waiting and
        slotted request as one JSON-serializable dict — phase (the request
        state), slot, age since submit, prompt/cached/generated token
        counts, preemptions. Read-only; the engine calls it under the
        registry lock so a server-thread reader never sees a slot table
        mid-update."""
        if now is None:
            now = time.perf_counter()

        def describe(req: Request) -> dict:
            doc = {
                "req_id": req.req_id,
                "phase": req.state.value,
                "slot": req.slot,
                "age_s": max(0.0, now - req.submit_time),
                "prompt_len": len(req.prompt),
                "len_cached": req.len_cached,
                "generated": req.n_generated,
                "max_new_tokens": req.params.max_new_tokens,
                "preempt_count": req.preempt_count,
            }
            if req.trace_id is not None:
                doc["trace_id"] = req.trace_id
            return doc

        out = [describe(r) for r in self.waiting]
        out.extend(describe(r) for r in self.slots if r is not None)
        return out

    # ------------------------------------------------------------ mutation

    def add(self, req: Request) -> None:
        bisect.insort(self.waiting, req, key=lambda r: r.req_id)
        if req.params.deadline_s is not None:
            self._any_deadlines = True

    def _admit(
        self, req: Request, slot: int, plan: Optional[StepPlan] = None
    ) -> None:
        req.slot = slot
        req.len_cached = 0
        req.trie_node = PrefixCache.ROOT
        req.trie_pages = 0
        host_served = 0
        if self.prefix_cache is not None and not _adapter_bound(req):
            assert not req.table.pages, "admitting a request holding pages"
            pages, matched, node = self.prefix_cache.lookup(req.tokens)
            req.table.pages = pages
            req.len_cached = matched
            req.trie_node = node
            req.trie_pages = matched // self.page_size
            if plan is not None:
                host_served = self._admit_host_pages(req, plan)
            if req.cached_prompt_tokens is None:
                req.cached_prompt_tokens = matched
                req.host_prompt_tokens = host_served
        elif req.cached_prompt_tokens is None:
            req.cached_prompt_tokens = 0
            req.host_prompt_tokens = 0
        req.state = (
            RequestState.DECODE if req.remaining_prefill == 0
            else RequestState.PREFILL
        )
        self.slots[slot] = req
        if self.tracer.enabled:
            self.tracer.request_event(
                req.req_id, "admit",
                slot=slot,
                cached_tokens=req.len_cached,
                hit=req.len_cached > 0,
                readmission=req.preempt_count > 0,
            )
        if self.flight.enabled:
            self.flight.record(
                "admit",
                req_id=req.req_id,
                slot=slot,
                cached_tokens=req.len_cached,
                host_tokens=host_served,
                readmission=req.preempt_count > 0,
                **_flight_trace(req),
            )

    def _admit_host_pages(self, req: Request, plan: StepPlan) -> int:
        """Extend ``req``'s device prefix match into the HOST tier: for
        every consecutive full-page window the host holds, allocate a
        device page, register it in the trie (making the chain a device
        hit for any later request), pin the host entry, and plan an h2d
        fetch — so chunked prefill starts at the first token covered by
        NEITHER tier. Stops at the first page the allocator cannot grant
        without preempting (a fetch is a cache optimization, never worth
        evicting live work for). Returns the host-served token count."""
        pc = self.prefix_cache
        if pc is None or pc.host is None:
            return 0
        limit = max(0, len(req.tokens) - 1)
        wanted = pc.host_continuation(
            req.tokens, req.len_cached, req.trie_node, limit
        )
        served = 0
        for key, chunk in wanted:
            try:
                (page,) = self.allocator.allocate(1)
            except OutOfPages:
                break
            # allocate() may itself evict a cached-idle device page, whose
            # host-side spill can LRU-drop an unpinned host entry — even
            # this very key. Re-verify before pinning; a vanished entry
            # ends the continuation (the chain is broken past it).
            if not pc.host.match(key, chunk):
                self.allocator.free([page])
                break
            node, registered = pc.register_full(req.trie_node, chunk, page)
            # The device walk just failed at (trie_node, chunk) in this
            # same schedule pass, so the registration cannot be a dupe.
            assert registered, "host continuation raced a device node"
            req.table.pages.append(page)
            pc.host.pin(key)
            pc.fetch_pending.add(page)
            plan.fetches.append((key, page, req.trie_node, chunk, node))
            req.trie_node = node
            req.trie_pages += 1
            req.len_cached += self.page_size
            served += self.page_size
        if served:
            pc.note_host_hit(served)
            if self.tracer.enabled:
                self.tracer.request_event(
                    req.req_id, "host_fetch_planned",
                    pages=served // self.page_size, tokens=served,
                )
        return served

    def _preempt(self, req: Request) -> None:
        """Evict ``req`` back to the waiting queue: page refs dropped
        (registered pages idle with contents intact, so re-admission
        usually re-matches them), generated tokens KEPT."""
        self.preemptions += 1
        req.preempt_count += 1
        # Positions up to len_cached must be re-prefilled on re-admission;
        # a later prefix-cache re-match shrinks the actual rework charged.
        req.rework_until = max(req.rework_until, req.len_cached)
        if self.tracer.enabled:
            self.tracer.request_event(
                req.req_id, "preempt",
                n_generated=req.n_generated,
                pages_released=len(req.table.pages),
            )
        if self.flight.enabled:
            self.flight.record(
                "preempt",
                req_id=req.req_id,
                n_generated=req.n_generated,
                pages_released=len(req.table.pages),
                **_flight_trace(req),
            )
        req.table.release(self.allocator)
        self.slots[req.slot] = None
        req.slot = None
        req.len_cached = 0
        req.state = RequestState.WAITING
        self.add(req)

    def retire(self, req: Request, now: Optional[float] = None) -> None:
        """Finished: register the final partial page in the prefix trie
        (full pages were registered as they filled), then drop every page
        ref and the slot. Registered pages idle on the LRU — demoted, not
        freed — so the next request with this prefix hits them; eviction
        happens lazily under OutOfPages pressure."""
        if (
            self.prefix_cache is not None
            and req.slot is not None
            and not _adapter_bound(req)
        ):
            self._register_filled(req)
            start = req.trie_pages * self.page_size
            valid = req.len_cached
            if req.pending_idx:
                valid = min(valid, req.pending_idx[0])
            if start < valid and req.trie_pages < len(req.table.pages):
                self.prefix_cache.register_partial(
                    req.trie_node,
                    tuple(req.tokens[start:valid]),
                    req.table.pages[req.trie_pages],
                )
        req.table.release(self.allocator)
        if req.slot is not None:
            self.slots[req.slot] = None
        elif req.state is RequestState.WAITING:
            # Finished while preempted (stop token resolved post-eviction).
            self.waiting.remove(req)
        req.slot = None
        req.state = RequestState.FINISHED
        req.finish_time = time.perf_counter() if now is None else now
        if self.tracer.enabled:
            self.tracer.request_end(
                req.req_id,
                n_generated=req.n_generated,
                preempt_count=req.preempt_count,
            )
        if self.flight.enabled:
            self.flight.record(
                "retire",
                req_id=req.req_id,
                n_generated=req.n_generated,
                preempt_count=req.preempt_count,
                **_flight_trace(req),
            )

    def cancel(
        self,
        req: Request,
        state: RequestState = RequestState.CANCELLED,
        now: Optional[float] = None,
    ) -> bool:
        """Terminal retirement WITHOUT completion — the one primitive that
        deadline expiry, client cancellation, and engine close all share
        (and that restore relies on to shed rows it cannot re-host). Frees
        the request's pages immediately (trie-registered pages demote to
        cached-idle, private ones free), vacates its slot or removes it
        from the waiting queue, and marks the terminal state; generated
        tokens stay pollable. ``pending_idx`` is deliberately KEPT: a
        decode readback still in flight for this row resolves through
        :meth:`resolve_decoded`'s discard branch. Returns False when the
        request was already terminal."""
        assert state in (RequestState.CANCELLED, RequestState.EXPIRED)
        if req.done:
            return False
        if req.slot is not None:
            req.table.release(self.allocator)
            self.slots[req.slot] = None
            req.slot = None
        elif req.state is RequestState.WAITING:
            self.waiting.remove(req)
            req.table.release(self.allocator)  # empty by invariant
        req.state = state
        req.finish_time = time.perf_counter() if now is None else now
        if state is RequestState.EXPIRED:
            self.expired += 1
        else:
            self.cancelled += 1
        if self.tracer.enabled:
            self.tracer.request_end(
                req.req_id,
                terminal=state.value,
                n_generated=req.n_generated,
            )
        if self.flight.enabled:
            self.flight.record(
                "cancel",
                req_id=req.req_id,
                terminal=state.value,
                n_generated=req.n_generated,
                **_flight_trace(req),
            )
        return True

    def expire_deadlines(self, now: Optional[float] = None) -> List[Request]:
        """Retire every live request whose ``deadline_s`` has elapsed since
        submission. Runs at the top of :meth:`schedule` (gated on any
        deadline-bearing request existing), so an expired row's pages are
        back in the pool before this step's planning needs them."""
        now = time.perf_counter() if now is None else now
        out: List[Request] = []
        for req in list(self.waiting) + self.running:
            dl = req.params.deadline_s
            if dl is not None and now - req.submit_time >= dl:
                if self.cancel(req, RequestState.EXPIRED, now=now):
                    out.append(req)
        return out

    def _reclaim_for(self, req: Request) -> bool:
        """Free pages for ``req`` by preempting ONE strictly lower-priority
        victim. Returns False — after preempting ``req`` itself — when no
        such victim exists."""
        victim = None
        for cand in self.running:
            if cand.req_id > req.req_id and (
                victim is None or cand.req_id > victim.req_id
            ):
                victim = cand
        if victim is None:
            # req is the lowest-priority page-holder; it yields.
            self._preempt(req)
            return False
        self._preempt(victim)
        return True

    def _ensure_pages(self, req: Request, n_tokens: int) -> bool:
        """Cover ``n_tokens`` positions of ``req``'s table, preempting
        strictly lower-priority victims as needed. Returns False — after
        preempting ``req`` itself — when even that cannot free enough."""
        while True:
            try:
                req.table.ensure(n_tokens, self.page_size, self.allocator)
                return True
            except OutOfPages:
                if not self._reclaim_for(req):
                    return False

    def _cow_write_page(self, req: Request, plan: StepPlan) -> bool:
        """Guarantee ``req`` exclusively owns the page it is about to write
        (position ``len_cached``). A shared page — refcount > 1, i.e.
        concurrent extenders of a cached partial page — is copied first:
        the plan gains a ``(slot, src, dst)`` device copy, the table swaps
        to the fresh page, and the shared original keeps its other readers
        and its trie registration. Returns False iff ``req`` was preempted
        while reclaiming a page for the copy."""
        if self.prefix_cache is None:
            return True
        while True:
            idx = req.len_cached // self.page_size
            if idx >= len(req.table.pages):
                return True  # write lands on a page ensure() will allocate
            page = req.table.pages[idx]
            if self.allocator.refcount(page) <= 1:
                return True
            try:
                (fresh,) = self.allocator.allocate(1)
            except OutOfPages:
                if not self._reclaim_for(req):
                    return False
                continue  # a victim's release may also have unshared it
            plan.copies.append((req.slot, page, fresh))
            req.table.pages[idx] = fresh
            self.allocator.unref(page)
            self.allocator.note_cow()
            if self.tracer.enabled:
                self.tracer.request_event(
                    req.req_id, "cow_copy", src=page, dst=fresh
                )
            return True

    # ------------------------------------------------------------ planning

    def schedule(self) -> StepPlan:
        """Build the next step's plan. Mutates scheduler state (admission,
        prefix-cache lookup, page allocation, copy-on-write, preemption);
        the engine then executes the device work and reports back via
        :meth:`note_prefilled` / :meth:`note_decode_dispatched` /
        :meth:`resolve_decoded`."""
        plan = StepPlan()

        # 0. Deadline sweep — free expired rows' pages before planning.
        if self._any_deadlines:
            self.expire_deadlines()

        # 1. Admit waiting requests into free slots, oldest first. Pages
        # beyond the prefix-cache match are allocated lazily below, so
        # admission itself cannot fail.
        for slot in range(self.max_slots):
            if not self.waiting:
                break
            if self.slots[slot] is None:
                self._admit(self.waiting.pop(0), slot, plan)

        # 2. Decode set reserves budget first: each running sequence
        # charges its full device write — one token, or a gamma-wide
        # speculative round — and is guaranteed exclusive ownership of
        # (copy-on-write) and pages for every position it may touch. A
        # round may overshoot the budget by at most cost-1; gating on
        # budget <= 0 (not budget < cost) avoids livelock when
        # token_budget < gamma. Requests that already issued
        # max_new_tokens sit out — their last readback resolves this step.
        budget = self.token_budget
        cost = self.gamma if self.gamma else 1
        for req in sorted(self.running, key=lambda r: r.req_id):
            if (
                req.state is not RequestState.DECODE
                or budget <= 0
                or req.n_issued >= req.params.max_new_tokens
            ):
                continue
            if not self._cow_write_page(req, plan):
                continue  # req itself was preempted reclaiming copy space
            # A gamma-wide round may overhang max_seq_len (the needed
            # positions always fit; only wasted chunk width runs past the
            # end) — don't allocate pages for the overhang, the model
            # routes those writes to the null page.
            need = min(
                req.len_cached + cost, self.pages_per_seq * self.page_size
            )
            if self._ensure_pages(req, need):
                plan.decode_slots.append(req.slot)
                budget -= cost

        # 3. Remaining budget goes to prefill chunks, highest priority
        # first, power-of-two sized so compile variants stay bounded.
        # Prefill starts at the first uncached token (len_cached covers the
        # prefix-cache match).
        for req in sorted(self.running, key=lambda r: r.req_id):
            if req.state is not RequestState.PREFILL or budget <= 0:
                continue
            slot = req.slot
            if not self._cow_write_page(req, plan):
                continue  # preempted; nothing was planned for it yet
            planned = req.len_cached
            while budget > 0:
                remaining = len(req.tokens) - 1 - planned
                if remaining <= 0:
                    break
                chunk = min(
                    _pow2_floor(remaining),
                    self.max_prefill_chunk,
                    _pow2_floor(budget),
                )
                if chunk <= 0:
                    break
                if not self._ensure_pages(req, planned + chunk):
                    break  # req was preempted; its plan entries are dropped
                plan.prefill.append((slot, chunk))
                planned += chunk
                budget -= chunk
            if req.state is not RequestState.PREFILL:
                # Preempted while growing: drop any chunks already planned
                # for its (now free) slot.
                plan.prefill = [
                    (s, c) for (s, c) in plan.prefill if s != slot
                ]
        # A prefill allocation above may have preempted a (lower-priority)
        # request that was already planned for decode or a CoW copy — keep
        # only entries whose slot still holds a live request (slots freed
        # mid-schedule stay free until the next schedule's admission pass).
        plan.decode_slots = [
            s for s in plan.decode_slots
            if self.slots[s] is not None
            and self.slots[s].state is RequestState.DECODE
        ]
        plan.copies = [
            (s, src, dst) for (s, src, dst) in plan.copies
            if self.slots[s] is not None
        ]
        # Validate planned host fetches against the trie: a fetch whose
        # request was preempted mid-schedule is KEPT as long as its trie
        # entry survived (the page idles with to-be-valid content and
        # re-serves the prefix), but one whose destination page was
        # recycled by later allocation pressure has nowhere valid to
        # land — _on_evict already dropped the entry and the
        # fetch-pending mark, so only the host pin needs releasing.
        if plan.fetches:
            pc = self.prefix_cache
            kept = []
            for fetch in plan.fetches:
                key, page, parent, toks, node = fetch
                if pc._full.get((parent, toks)) == (node, page):
                    kept.append(fetch)
                else:
                    pc.fetch_pending.discard(page)
                    pc.host.unpin(key)
            plan.fetches = kept
        if self.debug:
            self.allocator.check_invariants()
        return plan

    # ----------------------------------------------------------- execution

    def _register_filled(self, req: Request) -> None:
        """Register every newly completed full page of ``req`` in the
        prefix trie (dedup: an existing node for the same prefix wins and
        the private page is simply not cached). Pages whose tokens are
        still PENDING readback are skipped until resolved."""
        if (
            self.prefix_cache is None
            or req.slot is None
            or _adapter_bound(req)
        ):
            return
        page = self.page_size
        valid = req.len_cached
        if req.pending_idx:
            valid = min(valid, req.pending_idx[0])
        while (req.trie_pages + 1) * page <= valid:
            k = req.trie_pages
            req.trie_node, _ = self.prefix_cache.register_full(
                req.trie_node,
                tuple(req.tokens[k * page : (k + 1) * page]),
                req.table.pages[k],
            )
            req.trie_pages = k + 1

    def note_prefilled(self, slot: int, chunk: int) -> None:
        req = self.slots[slot]
        assert req is not None, f"prefill completion for empty slot {slot}"
        if self.tracer.enabled:
            self.tracer.request_event(
                req.req_id, "prefill_chunk",
                chunk=chunk, start=req.len_cached,
            )
        req.len_cached += chunk
        assert req.len_cached <= len(req.tokens) - 1, (
            f"request {req.req_id} prefilled past its last token"
        )
        self._register_filled(req)
        if req.remaining_prefill == 0:
            req.state = RequestState.DECODE

    def note_decode_dispatched(self, slot: int) -> Request:
        """One decode step was ISSUED for ``slot``: advance the host-known
        state now (cache position, placeholder token) so the next schedule
        can plan around it; the sampled value lands later via
        :meth:`resolve_decoded`. Returns the request so the engine can pair
        it with the readback even if the slot changes hands meanwhile."""
        req = self.slots[slot]
        assert req is not None, f"decode dispatch for empty slot {slot}"
        assert req.state is RequestState.DECODE
        req.len_cached += 1
        assert req.len_cached == len(req.tokens), (
            f"request {req.req_id} decode out of sync"
        )
        req.pending_idx.append(len(req.tokens))
        req.tokens.append(PENDING_TOKEN)
        return req

    def resolve_decoded(
        self, req: Request, token: int, now: Optional[float] = None
    ) -> Optional[Request]:
        """Fill in the sampled value for ``req``'s oldest pending decode.
        Returns the request when this token FINISHED it (caller retires +
        records metrics). Handles the overlap edge cases: a request already
        finished by an earlier resolve discards this (speculative) value;
        a stop-token finish rolls back any speculative dispatch issued
        after it."""
        if req.done:
            # Speculative decode issued the step after a stop token — the
            # value is discarded and the placeholder tail dropped.
            if req.pending_idx:
                pos = req.pending_idx.pop(0)
                del req.tokens[pos:]
            return None
        pos = req.pending_idx.pop(0)
        assert req.tokens[pos] == PENDING_TOKEN, (
            f"request {req.req_id} resolve out of order"
        )
        token = int(token)
        req.tokens[pos] = token
        req.generated.append(token)
        if req.first_token_time is None:
            req.first_token_time = (
                time.perf_counter() if now is None else now
            )
        self._register_filled(req)
        stop = req.params.stop_token
        # Advance per-request mods (grammar state machines) on EVERY
        # committed token, before the finish check — the state must stay
        # consistent even when this token does not finish the request.
        mods_done = (
            req.mods.note_token(token) if req.mods is not None else False
        )
        if (
            req.n_generated >= req.params.max_new_tokens
            or (stop is not None and token == stop)
            or _stops_on_sequence(req)
            or mods_done
        ):
            # Roll back anything issued speculatively past the finish: the
            # extra KV write is garbage beyond the sequence (masked, and
            # its pages are released at retire).
            del req.tokens[pos + 1 :]
            req.pending_idx.clear()
            if req.state is not RequestState.WAITING:
                req.len_cached = len(req.tokens) - 1
            return req
        return None

    def note_decoded(
        self, slot: int, token: int, now: Optional[float] = None
    ) -> Optional[Request]:
        """Synchronous dispatch + resolve in one call — the non-overlapped
        path and the scheduler-only tests."""
        req = self.note_decode_dispatched(slot)
        return self.resolve_decoded(req, token, now=now)

    def resolve_spec(
        self, req: Request, tokens: List[int], now: Optional[float] = None
    ) -> Optional[Request]:
        """Apply one speculative verify round to ``req``: the accepted
        draft tokens plus the correction, in order. Speculative rounds
        resolve synchronously — the host needs the per-row accepted count
        before it can plan the next round — so there are no PENDING
        placeholders; every appended token advances ``len_cached`` with it
        and the DECODE invariant (``len_cached == len(tokens) - 1``) holds
        between rounds. Truncates at max_new_tokens / the stop token: the
        fixed-gamma device program may emit past either, and the rejected
        or overshoot K/V needs no cleanup (``len_cached`` simply stops
        short; stale positions are masked and overwritten write-then-attend
        by the real continuation). Returns the request when the round
        finished it."""
        assert req.state is RequestState.DECODE and not req.pending_idx, (
            f"request {req.req_id} spec resolve in bad state"
        )
        assert req.len_cached == len(req.tokens) - 1, (
            f"request {req.req_id} spec resolve out of sync"
        )
        finished = False
        stop = req.params.stop_token
        for token in tokens:
            token = int(token)
            req.tokens.append(token)
            req.len_cached += 1
            req.generated.append(token)
            if req.first_token_time is None:
                req.first_token_time = (
                    time.perf_counter() if now is None else now
                )
            mods_done = (
                req.mods.note_token(token)
                if req.mods is not None
                else False
            )
            if (
                req.n_generated >= req.params.max_new_tokens
                or (stop is not None and token == stop)
                or _stops_on_sequence(req)
                or mods_done
            ):
                finished = True
                break
        self._register_filled(req)
        return req if finished else None
