"""Request-level continuous-batching scheduler.

The host-side policy half of the engine: maintains the waiting queue and the
active slot set, interleaves chunked prefill with batched decode under a
per-step token budget, preempts under page pressure, and retires finished
sequences every step so new requests join mid-flight.

Design decisions, in the order they bite:

* **Priority = submission order** (request id, lower wins). Preemption only
  ever evicts a strictly LOWER-priority victim than the sequence that needs
  pages — or, failing that, preempts the requester itself — so the oldest
  running request always makes forward progress and two cache-hungry
  requests cannot livelock trading pages.
* **Decode before prefill in the budget**: every DECODE-state slot reserves
  one token of the step budget first, then the remainder goes to prefill
  chunks. Running sequences never starve (TPOT stays flat), while admitted
  prompts still chunk in within a bounded number of steps (TTFT bounded by
  prompt_len / leftover_budget).
* **Prefill covers positions [0, L-1)** of a request's token list; the LAST
  token always goes through the shared batched decode step, whose sampled
  output is the first new token. This mirrors ``generate``'s serial loop
  exactly (the body at position t decides token t+1), which is what makes
  served output token-identical to offline decode.
* **Chunks are power-of-two sized** (greedy decomposition, capped at
  ``max_prefill_chunk``), so the engine compiles at most log2(cap)+1 prefill
  variants — the "one compilation per shape bucket" contract.
* **Preempted sequences keep their generated tokens** and re-enter the
  waiting queue at their original priority; on re-admission the whole
  prompt+generated prefix is re-prefilled. With per-request fold_in RNG the
  resumed continuation reproduces the identical token stream.
"""

from __future__ import annotations

import bisect
import dataclasses
import enum
import time
from typing import List, Optional, Tuple

from distributed_pytorch_tpu.serving.kv_cache import (
    BlockTable,
    OutOfPages,
    PagedBlockAllocator,
)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request generation parameters. ``temperature <= 0`` is greedy;
    ``seed`` drives a per-request RNG folded with the token index, so a
    request's sampled stream is independent of batch composition and
    survives preemption. ``top_k``/``top_p`` are engine-level (static in the
    compiled step), not per-request."""

    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0
    stop_token: Optional[int] = None


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"


@dataclasses.dataclass
class Request:
    """One in-flight generation request. ``tokens`` = prompt + generated;
    ``len_cached`` counts how many of them have K/V in the paged cache.
    Invariant while in DECODE state: ``len_cached == len(tokens) - 1`` — the
    next decode step feeds ``tokens[len_cached]`` and appends the sample."""

    req_id: int
    prompt: List[int]
    params: SamplingParams
    tokens: List[int] = dataclasses.field(default_factory=list)
    generated: List[int] = dataclasses.field(default_factory=list)
    len_cached: int = 0
    table: BlockTable = dataclasses.field(default_factory=BlockTable)
    state: RequestState = RequestState.WAITING
    slot: Optional[int] = None
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    preempt_count: int = 0

    def __post_init__(self):
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.generated)

    @property
    def remaining_prefill(self) -> int:
        return len(self.tokens) - 1 - self.len_cached

    @property
    def done(self) -> bool:
        return self.state is RequestState.FINISHED


@dataclasses.dataclass
class StepPlan:
    """One engine step's worth of device work: prefill chunks (executed in
    order, each ``(slot, chunk_len)``), then one batched decode over
    ``decode_slots``."""

    prefill: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    decode_slots: List[int] = dataclasses.field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.prefill and not self.decode_slots


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 0


class Scheduler:
    """Waiting queue + slot set + page-pressure policy (see module doc)."""

    def __init__(
        self,
        allocator: PagedBlockAllocator,
        *,
        max_slots: int,
        page_size: int,
        pages_per_seq: int,
        token_budget: int = 64,
        max_prefill_chunk: int = 32,
    ):
        if token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got {token_budget}")
        if _pow2_floor(max_prefill_chunk) != max_prefill_chunk:
            raise ValueError(
                f"max_prefill_chunk must be a power of two, got "
                f"{max_prefill_chunk} (chunk sizes are compile-cache keys)"
            )
        self.allocator = allocator
        self.max_slots = max_slots
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.token_budget = token_budget
        self.max_prefill_chunk = max_prefill_chunk
        self.waiting: List[Request] = []  # kept sorted by req_id
        self.slots: List[Optional[Request]] = [None] * max_slots
        self.preemptions = 0

    # ------------------------------------------------------------- queries

    @property
    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or any(r is not None for r in self.slots)

    # ------------------------------------------------------------ mutation

    def add(self, req: Request) -> None:
        bisect.insort(self.waiting, req, key=lambda r: r.req_id)

    def _admit(self, req: Request, slot: int) -> None:
        req.slot = slot
        req.len_cached = 0
        req.state = (
            RequestState.DECODE if req.remaining_prefill == 0
            else RequestState.PREFILL
        )
        self.slots[slot] = req

    def _preempt(self, req: Request) -> None:
        """Evict ``req`` back to the waiting queue: pages freed, generated
        tokens KEPT (they re-prefill on re-admission)."""
        self.preemptions += 1
        req.preempt_count += 1
        req.table.release(self.allocator)
        self.slots[req.slot] = None
        req.slot = None
        req.len_cached = 0
        req.state = RequestState.WAITING
        self.add(req)

    def retire(self, req: Request, now: Optional[float] = None) -> None:
        """Finished: free pages and the slot. Copy-free — the slot and its
        stale cache pages are immediately reusable (masking handles the
        rest)."""
        req.table.release(self.allocator)
        if req.slot is not None:
            self.slots[req.slot] = None
        req.slot = None
        req.state = RequestState.FINISHED
        req.finish_time = time.perf_counter() if now is None else now

    def _ensure_pages(self, req: Request, n_tokens: int) -> bool:
        """Cover ``n_tokens`` positions of ``req``'s table, preempting
        strictly lower-priority victims as needed. Returns False — after
        preempting ``req`` itself — when even that cannot free enough."""
        while True:
            try:
                req.table.ensure(n_tokens, self.page_size, self.allocator)
                return True
            except OutOfPages:
                victim = None
                for cand in self.running:
                    if cand.req_id > req.req_id and (
                        victim is None or cand.req_id > victim.req_id
                    ):
                        victim = cand
                if victim is None:
                    # req is the lowest-priority page-holder; it yields.
                    self._preempt(req)
                    return False
                self._preempt(victim)

    # ------------------------------------------------------------ planning

    def schedule(self) -> StepPlan:
        """Build the next step's plan. Mutates scheduler state (admission,
        page allocation, preemption); the engine then executes the device
        work and reports back via :meth:`note_prefilled` /
        :meth:`note_decoded`."""
        plan = StepPlan()

        # 1. Admit waiting requests into free slots, oldest first. Pages are
        # allocated lazily below, so admission itself cannot fail.
        for slot in range(self.max_slots):
            if not self.waiting:
                break
            if self.slots[slot] is None:
                self._admit(self.waiting.pop(0), slot)

        # 2. Decode set reserves budget first: one token per running
        # sequence, each guaranteed a page for its write position.
        budget = self.token_budget
        for req in sorted(self.running, key=lambda r: r.req_id):
            if req.state is not RequestState.DECODE or budget <= 0:
                continue
            if self._ensure_pages(req, req.len_cached + 1):
                plan.decode_slots.append(req.slot)
                budget -= 1

        # 3. Remaining budget goes to prefill chunks, highest priority
        # first, power-of-two sized so compile variants stay bounded.
        for req in sorted(self.running, key=lambda r: r.req_id):
            if req.state is not RequestState.PREFILL:
                continue
            slot = req.slot
            planned = req.len_cached
            while budget > 0:
                remaining = len(req.tokens) - 1 - planned
                if remaining <= 0:
                    break
                chunk = min(
                    _pow2_floor(remaining),
                    self.max_prefill_chunk,
                    _pow2_floor(budget),
                )
                if chunk <= 0:
                    break
                if not self._ensure_pages(req, planned + chunk):
                    break  # req was preempted; its plan entries are dropped
                plan.prefill.append((slot, chunk))
                planned += chunk
                budget -= chunk
            if req.state is not RequestState.PREFILL:
                # Preempted while growing: drop any chunks already planned
                # for its (now free) slot.
                plan.prefill = [
                    (s, c) for (s, c) in plan.prefill if s != slot
                ]
        # A prefill allocation above may have preempted a (lower-priority)
        # request that was already planned for decode — keep only slots
        # still holding a DECODE-state request.
        plan.decode_slots = [
            s for s in plan.decode_slots
            if self.slots[s] is not None
            and self.slots[s].state is RequestState.DECODE
        ]
        return plan

    # ----------------------------------------------------------- execution

    def note_prefilled(self, slot: int, chunk: int) -> None:
        req = self.slots[slot]
        assert req is not None, f"prefill completion for empty slot {slot}"
        req.len_cached += chunk
        assert req.len_cached <= len(req.tokens) - 1, (
            f"request {req.req_id} prefilled past its last token"
        )
        if req.remaining_prefill == 0:
            req.state = RequestState.DECODE

    def note_decoded(
        self, slot: int, token: int, now: Optional[float] = None
    ) -> Optional[Request]:
        """Record one decode-step output for ``slot``. Returns the request
        when this token FINISHED it (caller retires + records metrics)."""
        req = self.slots[slot]
        assert req is not None, f"decode result for empty slot {slot}"
        assert req.state is RequestState.DECODE
        req.len_cached += 1
        assert req.len_cached == len(req.tokens), (
            f"request {req.req_id} decode out of sync"
        )
        req.tokens.append(int(token))
        req.generated.append(int(token))
        if req.first_token_time is None:
            req.first_token_time = (
                time.perf_counter() if now is None else now
            )
        stop = req.params.stop_token
        if (
            req.n_generated >= req.params.max_new_tokens
            or (stop is not None and int(token) == stop)
        ):
            return req
        return None
