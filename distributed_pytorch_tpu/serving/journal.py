"""Write-ahead journal + worker registry: the durable control plane.

PR 16 made replicas crash-isolated processes; this module makes the
*router* restartable. Every control-plane decision that matters for
exactly-once serving is journaled to disk before (or immediately after)
it takes effect, so a SIGKILLed ``FleetRouter`` can be rebuilt by
``FleetRouter.recover`` from the journal plus the still-running workers:

* **submits** — prompt, sampling params, tenant, mods spec, trace_id,
  and the (replica, req_id) placement;
* **assigns** — re-placements after failover / hedge promotion;
* **deliver marks** — batched per-stream delivered-token high-water
  marks (flushed once per router step, not per token);
* **progress marks** — batched per-request committed-token high-water
  marks (observability + recovery sanity, never authoritative: the
  worker wins on committed tokens);
* **finish / cancel acks** — terminal transitions, with the full
  generated token list on finish so a finished-but-undelivered stream
  can drain after recovery even if its worker is gone;
* **replica spawn / death events** — which workers existed, where their
  control servers listen, and which ones the old router already
  declared dead (those are never re-adopted).

Format — CRC-per-record JSONL segments, the same checksum/quarantine
discipline as ``checkpoint.py`` (crc32c when a native impl exists,
stdlib crc32 otherwise; the record tags which algorithm wrote it via the
segment meta record). One record per line::

    <crc32-hex-8> <compact-json>\n

A record whose line is truncated (torn write at SIGKILL) or whose CRC
mismatches (bit rot, chaos ``corrupt_file``) is *quarantined*: the bad
tail is copied to ``<segment>.corrupt`` (``.corrupt.N`` on collision),
the segment is truncated back to the last good record, and replay
resumes from there — corruption costs the torn record, never the run.

Disk use is bounded by **segment rotation + compaction**: when the live
segment exceeds ``segment_max_records`` the journal rotates to a fresh
segment whose head is a condensed re-statement of live state only —
open requests, undelivered finished tails, and live replicas — and the
older segments are deleted. Closed, fully-delivered requests vanish at
the first rotation after they close.

Durability model: records are flushed to the OS page cache after every
append (``flush()``, no fsync). That survives any *process* crash —
SIGKILL included, which is the failure mode this journal exists for. A
kernel panic or power loss can lose the last marks, which degrades
exactly-once to at-least-once: streams re-deliver a suffix and the door
dedups by token index (see ``FrontDoor.adopt_streams``).

The **worker registry** lives next to the segments in
``<dir>/workers/<name>.json``: each ``ProcessReplicaClient`` spawn
records pid + control/obs URLs + spec fingerprint there, and removes the
file on clean shutdown. ``FleetRouter.recover`` re-adopts workers whose
registry entry still points at a live pid that answers ``/adopt`` with a
matching fingerprint.

Stdlib-only on purpose: replaying a journal or listing orphaned workers
must not require JAX.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

JOURNAL_VERSION = 1
SEGMENT_PREFIX = "journal-"
SEGMENT_SUFFIX = ".jsonl"
WORKERS_SUBDIR = "workers"

try:  # Same CRC ladder as checkpoint.py: Castagnoli if native, else crc32.
    import crc32c as _crc32c_mod

    _CRC_ALGO = "crc32c"

    def _crc(data: bytes) -> int:
        return _crc32c_mod.crc32c(data)

except ImportError:
    _CRC_ALGO = "crc32"

    def _crc(data: bytes) -> int:
        return zlib.crc32(data) & 0xFFFFFFFF


class JournalError(RuntimeError):
    """The journal directory is unusable (not a directory, unwritable,
    or a segment could not be opened). Per-record corruption is NOT an
    error — it is quarantined and replay continues."""


# --------------------------------------------------------------------------
# Replayed state


@dataclass
class JournalState:
    """The fold of every surviving record: what the dead router knew.

    ``requests`` maps fid -> a mutable doc with keys ``prompt``,
    ``params``, ``metadata``, ``tenant``, ``mods``, ``trace_id``,
    ``replica``, ``req_id``, ``delivered``, ``committed``, ``finished``,
    ``gen`` (generated tokens, only once finished), ``cancelled``.
    ``replicas`` maps name -> its last spawn doc plus ``alive`` (False
    once a death/removal record was journaled — recovery never re-adopts
    those). ``corrupt`` lists quarantine paths written during replay.
    """

    requests: Dict[int, Dict[str, Any]] = field(default_factory=dict)
    replicas: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    next_fid: int = 0
    records: int = 0
    segments: int = 0
    corrupt: List[str] = field(default_factory=list)

    def apply(self, rec: Dict[str, Any]) -> None:
        kind = rec.get("k")
        if kind == "meta":
            self.next_fid = max(self.next_fid, int(rec.get("next_fid", 0)))
        elif kind == "submit":
            fid = int(rec["fid"])
            self.requests[fid] = {
                "prompt": list(rec["prompt"]),
                "params": dict(rec["params"]),
                "metadata": rec.get("metadata"),
                "tenant": rec.get("tenant", "anon"),
                "mods": rec.get("mods"),
                "trace_id": rec.get("trace_id"),
                "replica": rec.get("replica"),
                "req_id": rec.get("req_id"),
                "delivered": int(rec.get("delivered", 0)),
                "committed": int(rec.get("committed", 0)),
                "finished": False,
                "gen": None,
                "cancelled": False,
            }
            self.next_fid = max(self.next_fid, fid + 1)
        elif kind == "assign":
            doc = self.requests.get(int(rec["fid"]))
            if doc is not None:
                doc["replica"] = rec.get("replica")
                doc["req_id"] = rec.get("req_id")
        elif kind == "deliver":
            for fid_s, n in rec.get("marks", {}).items():
                doc = self.requests.get(int(fid_s))
                if doc is not None:
                    doc["delivered"] = max(doc["delivered"], int(n))
        elif kind == "progress":
            for fid_s, n in rec.get("marks", {}).items():
                doc = self.requests.get(int(fid_s))
                if doc is not None:
                    doc["committed"] = max(doc["committed"], int(n))
        elif kind == "finish":
            doc = self.requests.get(int(rec["fid"]))
            if doc is not None:
                doc["finished"] = True
                doc["gen"] = list(rec.get("gen", []))
                doc["committed"] = len(doc["gen"])
        elif kind == "cancel":
            doc = self.requests.get(int(rec["fid"]))
            if doc is not None:
                doc["cancelled"] = True
        elif kind == "replica":
            name = rec["name"]
            ev = rec.get("ev")
            if ev == "spawn":
                doc = {
                    key: rec.get(key)
                    for key in (
                        "kind", "index", "pid", "control_url", "obs_url",
                        "fingerprint",
                    )
                }
                doc["alive"] = True
                self.replicas[name] = doc
            else:  # dead / removed
                doc = self.replicas.setdefault(name, {"alive": False})
                doc["alive"] = False
                doc["reason"] = rec.get("reason")
        # "recovery" records are informational; unknown kinds from a
        # newer writer are skipped rather than fatal.
        self.records += 1

    def open_requests(self) -> Dict[int, Dict[str, Any]]:
        """Requests recovery must still care about: not cancelled, and
        either unfinished or finished with an undelivered tail."""
        out = {}
        for fid, doc in self.requests.items():
            if doc["cancelled"]:
                continue
            if doc["finished"] and doc["delivered"] >= len(doc["gen"] or ()):
                continue
            out[fid] = doc
        return out


# --------------------------------------------------------------------------
# Segment I/O


def _segment_path(dir_path: str, index: int) -> str:
    return os.path.join(
        dir_path, f"{SEGMENT_PREFIX}{index:06d}{SEGMENT_SUFFIX}"
    )


def journal_segments(dir_path: str) -> List[str]:
    """Segment files in replay order (by index)."""
    if not os.path.isdir(dir_path):
        return []
    out = []
    for name in os.listdir(dir_path):
        if name.startswith(SEGMENT_PREFIX) and name.endswith(SEGMENT_SUFFIX):
            stem = name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
            if stem.isdigit():
                out.append((int(stem), os.path.join(dir_path, name)))
    return [path for _, path in sorted(out)]


def _segment_index(path: str) -> int:
    stem = os.path.basename(path)[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
    return int(stem)


def encode_record(rec: Dict[str, Any]) -> bytes:
    payload = json.dumps(
        rec, separators=(",", ":"), sort_keys=True
    ).encode("utf-8")
    return b"%08x " % _crc(payload) + payload + b"\n"


def decode_record(line: bytes) -> Optional[Dict[str, Any]]:
    """One journal line -> record dict, or None if torn/corrupt."""
    if not line.endswith(b"\n"):
        return None  # torn tail: the writer died mid-append
    body = line[:-1]
    if len(body) < 10 or body[8:9] != b" ":
        return None
    try:
        want = int(body[:8], 16)
    except ValueError:
        return None
    payload = body[9:]
    if _crc(payload) != want:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def quarantine_tail(path: str, good_len: int) -> Optional[str]:
    """Copy everything past ``good_len`` to ``<path>.corrupt`` (checkpoint's
    collision-suffix naming) and truncate the segment back to the last good
    record. Returns the quarantine path, or None if nothing was written."""
    try:
        with open(path, "rb") as f:
            f.seek(good_len)
            tail = f.read()
        if not tail:
            return None
        dest = path + ".corrupt"
        n = 0
        while os.path.exists(dest):
            n += 1
            dest = f"{path}.corrupt.{n}"
        with open(dest, "wb") as f:
            f.write(tail)
        with open(path, "r+b") as f:
            f.truncate(good_len)
    except OSError:
        return None
    print(
        f"[journal] quarantined torn/corrupt tail of "
        f"{os.path.basename(path)} -> {os.path.basename(dest)}"
    )
    return dest


def _replay_segment(path: str, state: JournalState) -> None:
    with open(path, "rb") as f:
        data = f.read()
    offset = 0
    while offset < len(data):
        end = data.find(b"\n", offset)
        line = data[offset:] if end < 0 else data[offset:end + 1]
        rec = decode_record(line)
        if rec is None:
            dest = quarantine_tail(path, offset)
            if dest is not None:
                state.corrupt.append(dest)
            return
        state.apply(rec)
        offset = end + 1


def replay_journal(dir_path: str) -> JournalState:
    """Fold every segment (in order) into a :class:`JournalState`,
    quarantining any torn or CRC-corrupt tail and resuming from the last
    good record."""
    state = JournalState()
    for path in journal_segments(dir_path):
        _replay_segment(path, state)
        state.segments += 1
    return state


# --------------------------------------------------------------------------
# Writer


class Journal:
    """Append-only writer over CRC'd JSONL segments with rotation +
    compaction. One instance per router incarnation; never shared.

    Opening always starts a *fresh* segment (a dead incarnation's torn
    tail is someone else's replay problem, handled by
    :func:`replay_journal` before the new writer is built). Pass the
    replayed ``state`` to seed the live-state mirror — the constructor
    then writes a compacted base segment and deletes the old ones.
    """

    def __init__(
        self,
        dir_path: str,
        *,
        segment_max_records: int = 4096,
        state: Optional[JournalState] = None,
    ):
        self.dir = dir_path
        self.segment_max_records = max(8, int(segment_max_records))
        os.makedirs(dir_path, exist_ok=True)
        if not os.path.isdir(dir_path):
            raise JournalError(f"journal dir {dir_path!r} is not a directory")
        self._state = state if state is not None else JournalState()
        existing = journal_segments(dir_path)
        self._seg_index = (
            _segment_index(existing[-1]) + 1 if existing else 1
        )
        self._fh = None
        self._seg_records = 0
        self.records_written = 0
        self.rotations = 0
        self.compacted_away = 0
        self._open_segment()
        if state is not None:
            # Recovery path: re-state live truth compactly, then drop the
            # old incarnation's segments — they are fully captured.
            self._write_compaction_base()
            for path in existing:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- plumbing ----------------------------------------------------------

    def _open_segment(self) -> None:
        path = _segment_path(self.dir, self._seg_index)
        try:
            self._fh = open(path, "ab")
        except OSError as exc:
            raise JournalError(f"cannot open segment {path!r}: {exc}")
        self._seg_records = 0
        self.append({
            "k": "meta",
            "version": JOURNAL_VERSION,
            "crc": _CRC_ALGO,
            "segment": self._seg_index,
            "next_fid": self._state.next_fid,
        })

    def append(self, rec: Dict[str, Any]) -> None:
        if self._fh is None:
            raise JournalError("journal is closed")
        self._fh.write(encode_record(rec))
        # flush() pushes to the OS page cache: survives SIGKILL of this
        # process, which is the crash model. No fsync — power loss only
        # degrades exactly-once to at-least-once (door dedups by index).
        self._fh.flush()
        self._state.apply(rec)
        self._seg_records += 1
        self.records_written += 1
        if self._seg_records >= self.segment_max_records:
            self.rotate()

    def rotate(self) -> None:
        """Close the live segment, open the next one with a compacted
        base, and delete everything older — bounded disk."""
        old = journal_segments(self.dir)
        self._fh.close()
        self._seg_index += 1
        self.rotations += 1
        self._open_segment()
        self._write_compaction_base()
        for path in old:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _write_compaction_base(self) -> None:
        """Condense live state into the head of the current segment:
        live replicas, open requests (with their current placement and
        high-water marks), and undelivered finished tails. Closed,
        fully-delivered requests are dropped here — this is the
        compaction that bounds disk use."""
        live = self._state.open_requests()
        self.compacted_away += len(self._state.requests) - len(live)
        for name, doc in sorted(self._state.replicas.items()):
            if not doc.get("alive"):
                continue
            self.append({
                "k": "replica", "ev": "spawn", "name": name,
                **{key: doc.get(key) for key in (
                    "kind", "index", "pid", "control_url", "obs_url",
                    "fingerprint",
                )},
            })
        for fid in sorted(live):
            doc = live[fid]
            self.append({
                "k": "submit", "fid": fid,
                "prompt": doc["prompt"], "params": doc["params"],
                "metadata": doc["metadata"], "tenant": doc["tenant"],
                "mods": doc["mods"], "trace_id": doc["trace_id"],
                "replica": doc["replica"], "req_id": doc["req_id"],
                "delivered": doc["delivered"],
                "committed": doc["committed"],
            })
            if doc["finished"]:
                self.append({"k": "finish", "fid": fid, "gen": doc["gen"]})
        # Drop closed requests from the mirror too, or they re-survive
        # every future rotation.
        self._state.requests = dict(live)

    # -- record helpers ----------------------------------------------------

    def append_submit(
        self, fid: int, *, prompt, params: Dict[str, Any], metadata,
        tenant: str, mods, trace_id, replica: Optional[str],
        req_id: Optional[int],
    ) -> None:
        self.append({
            "k": "submit", "fid": int(fid), "prompt": list(prompt),
            "params": params, "metadata": metadata, "tenant": tenant,
            "mods": mods, "trace_id": trace_id, "replica": replica,
            "req_id": req_id,
        })

    def append_assign(self, fid: int, replica: str, req_id: int) -> None:
        self.append({
            "k": "assign", "fid": int(fid), "replica": replica,
            "req_id": int(req_id),
        })

    def append_deliver(self, marks: Dict[int, int]) -> None:
        if marks:
            self.append({
                "k": "deliver",
                "marks": {str(fid): int(n) for fid, n in marks.items()},
            })

    def append_progress(self, marks: Dict[int, int]) -> None:
        if marks:
            self.append({
                "k": "progress",
                "marks": {str(fid): int(n) for fid, n in marks.items()},
            })

    def append_finish(self, fid: int, gen) -> None:
        self.append({
            "k": "finish", "fid": int(fid), "gen": [int(t) for t in gen],
        })

    def append_cancel(self, fid: int) -> None:
        self.append({"k": "cancel", "fid": int(fid)})

    def append_replica(self, ev: str, name: str, **info: Any) -> None:
        self.append({"k": "replica", "ev": ev, "name": name, **info})

    def append_recovery(self, summary: Dict[str, Any]) -> None:
        self.append({"k": "recovery", **summary})

    # -- lifecycle ---------------------------------------------------------

    @property
    def state(self) -> JournalState:
        return self._state

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                self._fh.close()
            except OSError:
                pass
            self._fh = None


# --------------------------------------------------------------------------
# Worker registry


def registry_dir(run_dir: str) -> str:
    return os.path.join(run_dir, WORKERS_SUBDIR)


def write_worker_entry(run_dir: str, entry: Dict[str, Any]) -> str:
    """Atomically record a spawned worker (pid, control/obs URLs, spec
    fingerprint) under ``<run_dir>/workers/<name>.json``."""
    name = entry["name"]
    dir_path = registry_dir(run_dir)
    os.makedirs(dir_path, exist_ok=True)
    path = os.path.join(dir_path, f"{name}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(entry, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def remove_worker_entry(run_dir: str, name: str) -> None:
    try:
        os.unlink(os.path.join(registry_dir(run_dir), f"{name}.json"))
    except OSError:
        pass


def read_worker_registry(run_dir: str) -> Dict[str, Dict[str, Any]]:
    """name -> registry entry for every recorded worker (dead or alive —
    callers probe the pid)."""
    dir_path = registry_dir(run_dir)
    out: Dict[str, Dict[str, Any]] = {}
    if not os.path.isdir(dir_path):
        return out
    for fname in sorted(os.listdir(dir_path)):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(dir_path, fname)) as f:
                entry = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(entry, dict) and "name" in entry:
            out[entry["name"]] = entry
    return out


def pid_alive(pid: Optional[int]) -> bool:
    """Signal-0 liveness probe (same-user processes only, which is the
    only kind this control plane spawns)."""
    if not pid or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True
