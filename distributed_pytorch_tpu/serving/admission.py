"""Admission control + serving metrics.

The engine's front door. Two jobs:

* **Backpressure**: a bounded waiting queue (``QueueFull`` the moment it
  overflows — callers shed load or retry, the engine never buffers
  unboundedly) and an up-front feasibility check (``RequestTooLong`` for
  requests that could never fit the block table even on an empty cache —
  rejecting at submit beats preempt-thrashing forever at runtime). The
  optional ``max_queue_tokens`` budget bounds queued PREFILL WORK rather
  than request count, and counts only uncached tokens: a thousand requests
  sharing a cached system prompt cost their tails, not their full prompts,
  so prefix caching directly raises sustainable admission rate.
* **Latency accounting**: per-request TTFT (submit -> first generated
  token), TPOT (mean inter-token time past the first), and e2e latency,
  recorded into bounded :class:`~distributed_pytorch_tpu.metrics
  .ReservoirHistogram` reservoirs with p50/p95/p99 export, plus exact
  throughput counters. TTFT is additionally split by prefix-cache outcome
  (hit = any prompt tokens served from cache at first admission) via a
  :class:`~distributed_pytorch_tpu.metrics.ReservoirGroup`, the number the
  bench prints to show cache hits shaving prefill out of first-token
  latency.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, Optional

from distributed_pytorch_tpu.metrics import ReservoirGroup, ReservoirHistogram
from distributed_pytorch_tpu.serving.scheduler import Request, SamplingParams


class AdmissionError(RuntimeError):
    """Base class: the request was NOT accepted."""


class QueueFull(AdmissionError):
    """Waiting queue at capacity — backpressure; retry later."""


class RequestTooLong(AdmissionError):
    """prompt + max_new_tokens can never fit the per-sequence block table."""


class EngineDraining(AdmissionError):
    """The engine is draining (or closed) — no new work is accepted.

    Distinct from :class:`QueueFull` on purpose: a full queue means "retry
    here, later"; a draining engine means "retry ELSEWHERE, now" (the
    load balancer should route to a live replica)."""


class AdmissionController:
    """Bounded-queue gate in front of the scheduler."""

    def __init__(
        self,
        *,
        max_queue: int,
        max_request_tokens: int,
        max_queue_tokens: Optional[int] = None,
        recent_rejections_max: int = 32,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if recent_rejections_max < 1:
            raise ValueError(
                "recent_rejections_max must be >= 1, got "
                f"{recent_rejections_max}"
            )
        self.max_queue = max_queue
        self.max_request_tokens = max_request_tokens
        self.max_queue_tokens = max_queue_tokens
        self.accepted = 0
        self.rejected_queue_full = 0
        self.rejected_too_long = 0
        self.rejected_draining = 0
        self.cached_tokens_admitted = 0
        # Typed tenancy (promoted out of the opaque ``metadata`` dict):
        # per-tenant admission counts, the billing-grade view of who is
        # actually getting through the gate.
        self.accepted_by_tenant: Dict[str, int] = {}
        self.draining = False
        # Last few rejections, keyed by the fleet-wide trace_id when the
        # caller supplied one: a request that never got past this gate has
        # no spans anywhere, so this ring is the only place ``/requestz``
        # can point at to explain a missing trace. Bounded at
        # ``recent_rejections_max`` entries (each a small dict — tens of
        # bytes), so a rejection storm costs O(recent_rejections_max)
        # memory, never O(rejections); the same eviction contract as the
        # trace sampler's ``max_kept``.
        self.recent_rejections: "collections.deque[dict]" = (
            collections.deque(maxlen=recent_rejections_max)
        )

    def close(self) -> None:
        """Stop admitting — first act of the drain protocol (and of engine
        close). Idempotent."""
        self.draining = True

    def reopen(self) -> None:
        self.draining = False

    def check(
        self,
        prompt_len: int,
        params: SamplingParams,
        queue_len: int,
        *,
        cached_tokens: int = 0,
        queued_uncached_tokens: int = 0,
        tenant_id: str = "anon",
        trace_id: Optional[str] = None,
    ) -> None:
        """Raise an :class:`AdmissionError` subclass iff the request must be
        rejected; otherwise count it accepted. ``cached_tokens`` is the
        prefix-cache match for this prompt at submit time;
        ``queued_uncached_tokens`` the uncached prefill work already
        waiting — both feed the optional queue-token budget.
        ``tenant_id`` keys the per-tenant accepted counter (fair-share
        policy itself lives a layer up, in the front door); ``trace_id``
        stamps rejections into :attr:`recent_rejections` so a trace that
        never produced a span is still explainable."""
        if self.draining:
            self.rejected_draining += 1
            raise self._reject(
                EngineDraining(
                    "engine is draining; no new requests accepted"
                ),
                "draining", tenant_id, trace_id,
            )
        if prompt_len < 1:
            self.rejected_too_long += 1
            raise self._reject(
                RequestTooLong(
                    "empty prompt: generation is conditioned on at least "
                    "one token (offline generate() has the same contract "
                    "— a zero-length row's position 0 is never decided)"
                ),
                "too_long", tenant_id, trace_id,
            )
        total = prompt_len + params.max_new_tokens
        if total > self.max_request_tokens:
            self.rejected_too_long += 1
            raise self._reject(
                RequestTooLong(
                    f"prompt ({prompt_len}) + max_new_tokens "
                    f"({params.max_new_tokens}) = {total} exceeds the "
                    f"per-sequence cache capacity {self.max_request_tokens}"
                ),
                "too_long", tenant_id, trace_id,
            )
        if queue_len >= self.max_queue:
            self.rejected_queue_full += 1
            raise self._reject(
                QueueFull(
                    f"waiting queue at capacity ({self.max_queue}); "
                    "retry later"
                ),
                "queue_full", tenant_id, trace_id,
            )
        if self.max_queue_tokens is not None:
            incoming = max(0, prompt_len - 1 - cached_tokens)
            if queued_uncached_tokens + incoming > self.max_queue_tokens:
                self.rejected_queue_full += 1
                raise self._reject(
                    QueueFull(
                        f"queued uncached prefill work "
                        f"({queued_uncached_tokens} + {incoming} tokens) "
                        f"exceeds budget {self.max_queue_tokens}; retry "
                        "later"
                    ),
                    "queue_full", tenant_id, trace_id,
                )
        self.accepted += 1
        self.cached_tokens_admitted += cached_tokens
        self.accepted_by_tenant[tenant_id] = (
            self.accepted_by_tenant.get(tenant_id, 0) + 1
        )

    def _reject(
        self,
        exc: AdmissionError,
        reason: str,
        tenant_id: str,
        trace_id: Optional[str],
    ) -> AdmissionError:
        self.recent_rejections.append(
            {
                "reason": reason,
                "tenant_id": tenant_id,
                "trace_id": trace_id,
                "detail": str(exc),
            }
        )
        return exc

    def status(self) -> Dict[str, object]:
        """The ``/statusz`` admission block: every rejection counter plus
        the live draining flag (``/healthz`` derives its verdict from the
        same flag) and the recent-rejection ring (trace_id-stamped, so a
        trace that died at the gate is still accounted for)."""
        out: Dict[str, object] = dict(self.counters())
        out["draining"] = self.draining
        out["recent_rejections"] = list(self.recent_rejections)
        return out

    def counters(self) -> Dict[str, int]:
        return {
            "accepted": self.accepted,
            "rejected_queue_full": self.rejected_queue_full,
            "rejected_too_long": self.rejected_too_long,
            "rejected_draining": self.rejected_draining,
            "cached_tokens_admitted": self.cached_tokens_admitted,
        }

    def register_into(self, registry) -> None:
        """Expose the admission counters through a
        :class:`~distributed_pytorch_tpu.obs.MetricsRegistry`."""
        registry.counter_fn("admission_accepted_total", lambda: self.accepted)
        registry.counter_fn(
            "admission_rejected_queue_full_total",
            lambda: self.rejected_queue_full,
        )
        registry.counter_fn(
            "admission_rejected_too_long_total",
            lambda: self.rejected_too_long,
        )
        registry.counter_fn(
            "admission_rejected_draining_total",
            lambda: self.rejected_draining,
        )
        registry.counter_fn(
            "cached_tokens_admitted_total",
            lambda: self.cached_tokens_admitted,
        )


class ServingMetrics:
    """TTFT / TPOT / e2e reservoirs + exact throughput counters.

    ``speculative=True`` labels this engine's TPOT samples "spec" in the
    mode split (so a spec-on and a spec-off run over the same workload can
    be compared reservoir-to-reservoir) and is the mode whose verify
    rounds feed :meth:`observe_verify` — per-round acceptance fraction and
    emitted-token reservoirs plus exact proposed/accepted counters, the
    numbers that say whether the draft is earning its keep."""

    def __init__(
        self, reservoir_capacity: int = 1024, speculative: bool = False
    ):
        self.speculative = speculative
        self.ttft = ReservoirHistogram(reservoir_capacity, seed=1)
        self.tpot = ReservoirHistogram(reservoir_capacity, seed=2)
        self.e2e = ReservoirHistogram(reservoir_capacity, seed=3)
        # TTFT by prefix-cache outcome at the request's FIRST admission:
        # "hit" iff any prompt tokens came from device-resident trie
        # pages, else "host" iff any were staged up from the host page
        # tier, else "miss". Device wins ties — a request served by both
        # tiers already had the cheaper device hit.
        self.ttft_by_source = ReservoirGroup(
            ("hit", "host", "miss"), reservoir_capacity, seed=4
        )
        # Speculative-verify quality: per-round acceptance fraction (of
        # gamma proposals) and tokens emitted per verify (1..gamma).
        self.spec = ReservoirGroup(
            ("acceptance_rate", "tokens_per_verify"),
            reservoir_capacity,
            seed=10,
        )
        self.tpot_by_mode = ReservoirGroup(
            ("spec", "plain"), reservoir_capacity, seed=20
        )
        self.verify_rounds = 0
        self.draft_proposed = 0
        self.draft_accepted = 0
        self.tokens_generated = 0
        self.requests_completed = 0
        self.engine_steps = 0
        self._start = time.perf_counter()

    def observe_step(self, new_tokens: int) -> None:
        self.engine_steps += 1
        self.tokens_generated += new_tokens

    def observe_verify(
        self, accepted: int, emitted: int, gamma: int
    ) -> None:
        """One speculative verify round: ``accepted`` of ``gamma`` draft
        proposals survived, ``emitted`` tokens entered the sequence
        (accepted + the correction, capped at gamma)."""
        self.verify_rounds += 1
        self.draft_proposed += gamma
        self.draft_accepted += accepted
        self.spec.record("acceptance_rate", accepted / gamma)
        self.spec.record("tokens_per_verify", float(emitted))

    def observe_finished(self, req: Request) -> None:
        self.requests_completed += 1
        if req.first_token_time is not None:
            ttft = req.first_token_time - req.submit_time
            self.ttft.record(ttft)
            if (req.cached_prompt_tokens or 0) > 0:
                source = "hit"
            elif (req.host_prompt_tokens or 0) > 0:
                source = "host"
            else:
                source = "miss"
            self.ttft_by_source.record(source, ttft)
            if req.finish_time is not None:
                self.e2e.record(req.finish_time - req.submit_time)
                if req.n_generated > 1:
                    tpot = (
                        req.finish_time - req.first_token_time
                    ) / (req.n_generated - 1)
                    self.tpot.record(tpot)
                    self.tpot_by_mode.record(
                        "spec" if self.speculative else "plain", tpot
                    )

    @staticmethod
    def register_into(registry, get) -> None:
        """Register the serving counters and latency reservoirs into a
        :class:`~distributed_pytorch_tpu.obs.MetricsRegistry`. ``get`` is a
        zero-arg callable returning the CURRENT metrics object — the bench
        replaces ``engine.metrics`` wholesale after warm-up, so every
        resolver goes through ``get()`` at snapshot time rather than
        capturing one instance."""
        registry.counter_fn("engine_steps_total", lambda: get().engine_steps)
        registry.counter_fn(
            "tokens_generated_total", lambda: get().tokens_generated
        )
        registry.counter_fn(
            "requests_completed_total", lambda: get().requests_completed
        )
        registry.counter_fn(
            "verify_rounds_total", lambda: get().verify_rounds
        )
        registry.counter_fn(
            "draft_tokens_proposed_total", lambda: get().draft_proposed
        )
        registry.counter_fn(
            "draft_tokens_accepted_total", lambda: get().draft_accepted
        )
        registry.gauge_fn(
            "uptime_seconds", lambda: time.perf_counter() - get()._start
        )
        registry.gauge_fn(
            "tokens_per_sec",
            lambda: get().snapshot()["tokens_per_sec"],
        )
        registry.reservoir("ttft_seconds", lambda: get().ttft)
        registry.reservoir("tpot_seconds", lambda: get().tpot)
        registry.reservoir("e2e_seconds", lambda: get().e2e)
        registry.reservoir(
            "ttft_seconds_by_source",
            lambda: get().ttft_by_source,
            label="source",
        )
        registry.reservoir(
            "tpot_seconds_by_mode", lambda: get().tpot_by_mode, label="mode"
        )
        registry.reservoir(
            "spec_per_verify", lambda: get().spec, label="stat"
        )

    def snapshot(self) -> Dict[str, float]:
        """One flat dict: counters + tokens/s + per-metric percentiles —
        the payload ``bench.py --serving`` writes and the smoke test
        asserts non-empty."""
        elapsed = time.perf_counter() - self._start
        out: Dict[str, float] = {
            "engine_steps": self.engine_steps,
            "tokens_generated": self.tokens_generated,
            "requests_completed": self.requests_completed,
            "elapsed_s": elapsed,
            "tokens_per_sec": (
                self.tokens_generated / elapsed if elapsed > 0 else 0.0
            ),
        }
        out.update(self.ttft.summary("ttft_s_"))
        out.update(self.ttft_by_source.summary("ttft_s_"))
        out.update(self.tpot.summary("tpot_s_"))
        out.update(self.tpot_by_mode.summary("tpot_s_"))
        out.update(self.e2e.summary("e2e_s_"))
        if self.speculative or self.verify_rounds:
            out["verify_rounds"] = self.verify_rounds
            out["draft_tokens_proposed"] = self.draft_proposed
            out["draft_tokens_accepted"] = self.draft_accepted
            out["spec_acceptance_rate"] = (
                self.draft_accepted / self.draft_proposed
                if self.draft_proposed
                else 0.0
            )
            out.update(self.spec.summary("spec_"))
        return out
