"""Step-scheduled profiling with TensorBoard trace export.

Twin of ``_create_profiler`` (reference ``multigpu_profile.py:80-91``):
``torch.profiler.profile(schedule(wait=1, warmup=1, active=5),
on_trace_ready=tensorboard_trace_handler(...))`` driven by
``start()/step()/stop()`` hooks in the batch loop (``:61-62,70-71,73-74``).

TPU-native: ``jax.profiler.start_trace/stop_trace`` captures libtpu/XLA device
traces viewable in TensorBoard (XProf) or Perfetto. The wait/warmup/active step
schedule is replicated host-side: tracing turns on after ``wait + warmup``
steps and off ``active`` steps later. Per-host subdirectories replace the
reference's per-device ``worker_name``.

Inside the traced window every step is additionally wrapped in a
``jax.profiler.StepTraceAnnotation`` named by the GLOBAL step number, so
XProf's step-time view and the trace timeline attribute device work to
specific optimizer steps (the reference's ``record_function`` analog).
The annotation opens when a traced step begins and closes right before the
``step()`` hook advances the schedule — exactly bracketing the work between
hooks — and never leaks across the trace stop (the window transition closes
it first).
"""

from __future__ import annotations

import os
from typing import Optional

import jax


class StepProfiler:
    """Profile a window of training steps.

    Usage (mirrors the reference's hook placement in ``run_epoch``)::

        profiler = StepProfiler("log/resnet50", wait=1, warmup=1, active=5)
        profiler.start()
        for batch in loader:
            ...train step...
            profiler.step()
        profiler.stop()

    ``annotate=False`` drops the per-step ``StepTraceAnnotation`` markers
    (the bare pre-annotation behavior) — the wait/warmup/active window is
    identical either way.
    """

    def __init__(
        self,
        logdir: str,
        *,
        wait: int = 1,
        warmup: int = 1,
        active: int = 5,
        annotate: bool = True,
    ):
        self.logdir = os.path.join(logdir, f"host_{jax.process_index()}")
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self.annotate = annotate
        self._step = 0
        self._tracing = False
        self._annotation = None

    @property
    def trace_started_at(self) -> int:
        return self.wait + self.warmup

    def start(self) -> None:
        self._step = 0
        self._maybe_transition()
        self._open_annotation()

    def step(self) -> None:
        """Call once per optimizer step (twin of ``profiler.step()``,
        reference ``multigpu_profile.py:71``)."""
        self._close_annotation()
        self._step += 1
        self._maybe_transition()
        self._open_annotation()

    def stop(self) -> None:
        self._close_annotation()
        if self._tracing:
            self._stop_trace()

    def rewind(self, step: int) -> None:
        """Reset the schedule to ``step`` — the elastic-restore path, where
        a restart resumes from a snapshot taken BEFORE the current step
        counter. A live trace whose window no longer covers ``step`` stops
        cleanly (annotation closed first); a rewind back INTO the window
        re-arms ``_maybe_transition`` so the trace starts again, writing a
        second capture to the same logdir. Idempotent under
        ``rewind(self._step)``."""
        self._close_annotation()
        self._step = int(step)
        begin = self.trace_started_at
        end = begin + self.active
        if self._tracing and not (begin <= self._step < end):
            self._stop_trace()
        self._maybe_transition()
        self._open_annotation()

    def _open_annotation(self) -> None:
        """Bracket the upcoming step's work in a StepTraceAnnotation named
        by the global step — only while the trace is live (annotations
        outside a trace are dead weight on every batch)."""
        if self._tracing and self.annotate:
            self._annotation = jax.profiler.StepTraceAnnotation(
                "train", step_num=self._step
            )
            self._annotation.__enter__()

    def _close_annotation(self) -> None:
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None

    def _maybe_transition(self) -> None:
        begin = self.trace_started_at
        end = begin + self.active
        if not self._tracing and begin <= self._step < end:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._tracing = True
        elif self._tracing and self._step >= end:
            self._stop_trace()

    def _stop_trace(self) -> None:
        self._close_annotation()  # an annotation must not outlive its trace
        jax.profiler.stop_trace()
        self._tracing = False
