"""Step-scheduled profiling with TensorBoard trace export.

Twin of ``_create_profiler`` (reference ``multigpu_profile.py:80-91``):
``torch.profiler.profile(schedule(wait=1, warmup=1, active=5),
on_trace_ready=tensorboard_trace_handler(...))`` driven by
``start()/step()/stop()`` hooks in the batch loop (``:61-62,70-71,73-74``).

TPU-native: ``jax.profiler.start_trace/stop_trace`` captures libtpu/XLA device
traces viewable in TensorBoard (XProf) or Perfetto. The wait/warmup/active step
schedule is replicated host-side: tracing turns on after ``wait + warmup``
steps and off ``active`` steps later. Per-host subdirectories replace the
reference's per-device ``worker_name``.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


class StepProfiler:
    """Profile a window of training steps.

    Usage (mirrors the reference's hook placement in ``run_epoch``)::

        profiler = StepProfiler("log/resnet50", wait=1, warmup=1, active=5)
        profiler.start()
        for batch in loader:
            ...train step...
            profiler.step()
        profiler.stop()
    """

    def __init__(self, logdir: str, *, wait: int = 1, warmup: int = 1, active: int = 5):
        self.logdir = os.path.join(logdir, f"host_{jax.process_index()}")
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self._step = 0
        self._tracing = False

    @property
    def trace_started_at(self) -> int:
        return self.wait + self.warmup

    def start(self) -> None:
        self._step = 0
        self._maybe_transition()

    def step(self) -> None:
        """Call once per optimizer step (twin of ``profiler.step()``,
        reference ``multigpu_profile.py:71``)."""
        self._step += 1
        self._maybe_transition()

    def stop(self) -> None:
        if self._tracing:
            self._stop_trace()

    def _maybe_transition(self) -> None:
        begin = self.trace_started_at
        end = begin + self.active
        if not self._tracing and begin <= self._step < end:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._tracing = True
        elif self._tracing and self._step >= end:
            self._stop_trace()

    def _stop_trace(self) -> None:
        jax.profiler.stop_trace()
        self._tracing = False
