"""Draft distillation: make speculative decoding actually fast.

Speculative decoding's speedup is the mean accepted chunk length, and that
is a property of how well the DRAFT predicts the TARGET — a random draft
accepts ~0 and degenerates to serial decode with extra overhead
(examples/generate_lm.py --speculative shows the machinery, not a win).
This rung closes the loop the way a real deployment does: distill a small
draft against the target's own next-token distributions (forward KL,
teacher logits computed on the fly), then measure the acceptance statistic
rise through ``speculative_generate(return_stats=True)``.

Run:  python examples/draft_distill.py --fake_devices 8    # CPU CI rig
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


def main(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_pytorch_tpu import ShardedLoader, Trainer
    from distributed_pytorch_tpu.models import TransformerLM
    from distributed_pytorch_tpu.speculative import speculative_generate
    from distributed_pytorch_tpu.training.losses import (
        softmax_cross_entropy_loss,
    )
    from distributed_pytorch_tpu.utils.data import ArrayDataset

    from examples.lora_finetune import token_stream  # the toy Markov data

    rng = np.random.default_rng(args.seed)
    vocab = 64
    target = TransformerLM(
        vocab_size=vocab, d_model=args.d_model, n_layers=args.n_layers,
        n_heads=4, d_ff=4 * args.d_model, dtype=jnp.float32,
    )
    draft = TransformerLM(
        vocab_size=vocab, d_model=args.d_model // 4, n_layers=1,
        n_heads=2, d_ff=args.d_model, dtype=jnp.float32,
    )

    # 1) Train the target on the toy distribution.
    data = token_stream(rng, args.n_train, args.seq, vocab, shift=1)
    loader = ShardedLoader(ArrayDataset(data[:, :-1], data[:, 1:]),
                           args.batch_size)
    trainer = Trainer(target, loader, optax.adam(1e-2), save_every=0,
                      loss_fn=softmax_cross_entropy_loss)
    trainer.train(args.target_epochs)
    # Host snapshot: the jitted step donates its state buffers.
    target_params = jax.tree_util.tree_map(np.asarray, trainer.state.params)

    prompts = jnp.asarray(
        token_stream(rng, args.eval_batch, 8, vocab, shift=1)
    )

    def acceptance(draft_params):
        _, stats = speculative_generate(
            target, target_params, draft, draft_params, prompts,
            args.new_tokens, gamma=args.gamma, return_stats=True,
        )
        return int(stats["positions_advanced"]) / max(int(stats["rounds"]), 1)

    draft_params = draft.init(
        jax.random.PRNGKey(args.seed + 1),
        jnp.zeros((1, 8), jnp.int32),
    )["params"]
    before = acceptance(draft_params)

    # 2) Distill: forward KL(target || draft) on the training sequences,
    # teacher logits computed on the fly (training/distill.py — the same
    # step tools/decode_bench.py --speculative uses).
    from distributed_pytorch_tpu.training.distill import make_distill_step

    inputs = jnp.asarray(data[:, :-1])
    opt = optax.adam(1e-2)
    opt_state = opt.init(draft_params)
    distill_step = make_distill_step(target, draft, opt)
    steps_per_epoch = len(inputs) // args.batch_size
    if steps_per_epoch == 0:
        raise SystemExit(
            f"--batch_size {args.batch_size} exceeds --n_train "
            f"{args.n_train}: distillation would silently no-op"
        )
    for epoch in range(args.distill_epochs):
        order = np.random.default_rng(epoch).permutation(len(inputs))
        loss = None
        for i in range(steps_per_epoch):
            idx = order[i * args.batch_size : (i + 1) * args.batch_size]
            draft_params, opt_state, loss = distill_step(
                draft_params, opt_state, inputs[idx], target_params
            )
        print(f"distill epoch {epoch}: kl={float(loss):.4f}", flush=True)

    after = acceptance(draft_params)
    n_t = sum(x.size for x in jax.tree_util.tree_leaves(target_params))
    n_d = sum(x.size for x in jax.tree_util.tree_leaves(draft_params))
    print(
        f"mean accepted chunk (gamma={args.gamma}): random draft "
        f"{before:.2f} -> distilled {after:.2f} "
        f"(draft is {n_d / n_t:.1%} of the target's {n_t:,} params; each "
        f"accepted chunk replaces that many serial target steps with one "
        f"chunked forward)"
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="draft distillation rung")
    parser.add_argument("--d_model", default=64, type=int)
    parser.add_argument("--n_layers", default=2, type=int)
    parser.add_argument("--seq", default=16, type=int)
    parser.add_argument("--n_train", default=2048, type=int)
    parser.add_argument("--batch_size", default=64, type=int)
    parser.add_argument("--target_epochs", default=3, type=int)
    parser.add_argument("--distill_epochs", default=3, type=int)
    parser.add_argument("--eval_batch", default=8, type=int)
    parser.add_argument("--new_tokens", default=32, type=int)
    parser.add_argument("--gamma", default=4, type=int)
    parser.add_argument("--seed", default=0, type=int)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args)
