"""Rung 1 — serial training on one chip. Twin of ``single_gpu.py``.

The whole reference hot loop (``single_gpu.py:21-26``) is one jitted
``train_step``; there is no device id to pass around — JAX places arrays on the
default device.

Run:  python examples/single_chip.py 10 2 [--batch_size 32] [--policy bf16]
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


import optax

from distributed_pytorch_tpu import MaterializedDataset, ShardedLoader, Trainer
from distributed_pytorch_tpu.models import ToyRegressor

# Compute-dtype policies (training/mixed_precision.py): params stay float32
# master weights in every case. fp16 has a 5-bit exponent, so it trains under
# a dynamic loss scale; bf16/f32 need none. The reference trains fp32 only.
POLICIES = ("f32", "bf16", "fp16")


def load_train_objs(policy: str = "f32"):
    """Factory twin of ``load_train_objs`` (``single_gpu.py:48-52``):
    2048-sample toy dataset, Linear(20,1) model, SGD(lr=1e-3)."""
    from distributed_pytorch_tpu.training import (
        BF16_POLICY,
        F32_POLICY,
        FP16_POLICY,
    )

    dtype = {
        "f32": F32_POLICY,
        "bf16": BF16_POLICY,
        "fp16": FP16_POLICY,
    }[policy].compute_dtype
    dataset = MaterializedDataset(2048)
    model = ToyRegressor(dtype=dtype)
    optimizer = optax.sgd(1e-3)
    return dataset, model, optimizer


def main(total_epochs: int, save_every: int, batch_size: int, policy: str):
    dataset, model, optimizer = load_train_objs(policy)
    loader = ShardedLoader(dataset, batch_size, shuffle=True)
    loss_scale = None
    if policy == "fp16":
        from distributed_pytorch_tpu.training import DynamicLossScale

        loss_scale = DynamicLossScale.create()
    trainer = Trainer(
        model, loader, optimizer, save_every, loss_scale=loss_scale
    )
    trainer.train(total_epochs)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="serial training job (rung 1)")
    parser.add_argument("total_epochs", type=int, help="Total epochs to train the model")
    parser.add_argument("save_every", type=int, help="How often to save a checkpoint")
    parser.add_argument("--batch_size", default=32, type=int,
                        help="Input batch size on each device (default: 32)")
    parser.add_argument("--policy", default="f32", choices=POLICIES,
                        help="compute dtype policy (fp16 adds dynamic loss scaling)")
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args.total_epochs, args.save_every, args.batch_size, args.policy)
