"""Rung 5 — profiled real-model training: ResNet-50 on synthetic images with
step-scheduled TensorBoard traces. Twin of ``multigpu_profile.py``.

* torchvision ``resnet50()`` (``multigpu_profile.py:23``) -> our flax ResNet-50
  (NHWC, optional bfloat16 compute for the MXU); the reference's commented-out
  ``vit_l_32`` alternative (``multigpu_profile.py:24``) is a first-class flag
  here: ``--model vit`` swaps in ``ViT_L32`` (305M params), no code edits;
* ``torch.profiler`` with schedule(wait=1, warmup=1, active=5) and
  ``tensorboard_trace_handler`` (``:80-91``) -> ``StepProfiler`` over
  ``jax.profiler.start_trace/stop_trace`` with the same step schedule;
* lazy ``MyRandomDataset(2048, (3,224,224))`` (``:16``) -> ``RandomDataset``
  with NHWC ``(224,224,3)`` and integer class targets.

View traces:  tensorboard --logdir log/resnet50

Run:  python examples/multichip_profile.py [--epochs 3] [--batch_size 32] [--bf16]
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


import jax
import jax.numpy as jnp
import optax

from distributed_pytorch_tpu import RandomDataset, ShardedLoader, StepProfiler, Trainer, make_mesh
from distributed_pytorch_tpu.models import ResNet50, ViT_L32
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss


def load_train_objs(model_name: str, bf16: bool):
    """Factory twin of ``multigpu_profile.py:13-27`` (the torchvision
    resnet50/vit_l_32 swap-in, ``:23-24``, as a flag instead of a comment)."""
    dataset = RandomDataset(2048, (224, 224, 3), num_classes=1000)
    dtype = jnp.bfloat16 if bf16 else jnp.float32
    if model_name == "vit":
        model = ViT_L32(num_classes=1000, dtype=dtype)
    else:
        model = ResNet50(dtype=dtype)
    optimizer = optax.sgd(1e-3, momentum=0.9)
    return dataset, model, optimizer


def main(epochs: int, batch_size: int, model_name: str, bf16: bool,
         profile: bool, logdir: str):
    mesh = make_mesh() if jax.device_count() > 1 else None
    dataset, model, optimizer = load_train_objs(model_name, bf16)
    loader = ShardedLoader(dataset, batch_size * jax.device_count(), drop_last=True)
    profiler = StepProfiler(logdir, wait=1, warmup=1, active=5) if profile else None
    trainer = Trainer(
        model,
        loader,
        optimizer,
        save_every=epochs,  # checkpoint at the end (reference saves once, :107-108)
        checkpoint_path=f"{model_name}_checkpoint.npz",
        mesh=mesh,
        loss_fn=softmax_cross_entropy_loss,
        profiler=profiler,
        log_every=10,
    )
    trainer.train(epochs)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="profiled ResNet-50 job (rung 5)")
    parser.add_argument("--epochs", default=3, type=int)
    parser.add_argument("--batch_size", default=32, type=int, help="per-chip batch size")
    parser.add_argument("--model", default="resnet50", choices=["resnet50", "vit"],
                        help="real model to train (reference multigpu_profile.py:23-24)")
    parser.add_argument("--bf16", action="store_true", help="bfloat16 compute (MXU-native)")
    parser.add_argument("--no_profile", action="store_true")
    parser.add_argument("--logdir", default="", type=str,
                        help="trace directory (default: log/<model>)")
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args.epochs, args.batch_size, args.model, args.bf16,
         not args.no_profile, args.logdir or f"log/{args.model}")
