"""Rung 5 — profiled real-model training: ResNet-50 on synthetic images with
step-scheduled TensorBoard traces. Twin of ``multigpu_profile.py``.

* torchvision ``resnet50()`` (``multigpu_profile.py:23``) -> our flax ResNet-50
  (NHWC, optional bfloat16 compute for the MXU);
* ``torch.profiler`` with schedule(wait=1, warmup=1, active=5) and
  ``tensorboard_trace_handler`` (``:80-91``) -> ``StepProfiler`` over
  ``jax.profiler.start_trace/stop_trace`` with the same step schedule;
* lazy ``MyRandomDataset(2048, (3,224,224))`` (``:16``) -> ``RandomDataset``
  with NHWC ``(224,224,3)`` and integer class targets.

View traces:  tensorboard --logdir log/resnet50

Run:  python examples/multichip_profile.py [--epochs 3] [--batch_size 32] [--bf16]
"""

import argparse


import jax
import jax.numpy as jnp
import optax

from distributed_pytorch_tpu import RandomDataset, ShardedLoader, StepProfiler, Trainer, make_mesh
from distributed_pytorch_tpu.models import ResNet50
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss


def load_train_objs(bf16: bool):
    """Factory twin of ``multigpu_profile.py:13-27``."""
    dataset = RandomDataset(2048, (224, 224, 3), num_classes=1000)
    model = ResNet50(dtype=jnp.bfloat16 if bf16 else jnp.float32)
    optimizer = optax.sgd(1e-3, momentum=0.9)
    return dataset, model, optimizer


def main(epochs: int, batch_size: int, bf16: bool, profile: bool, logdir: str):
    mesh = make_mesh() if jax.device_count() > 1 else None
    dataset, model, optimizer = load_train_objs(bf16)
    loader = ShardedLoader(dataset, batch_size * jax.device_count(), drop_last=True)
    profiler = StepProfiler(logdir, wait=1, warmup=1, active=5) if profile else None
    trainer = Trainer(
        model,
        loader,
        optimizer,
        save_every=epochs,  # checkpoint at the end (reference saves once, :107-108)
        checkpoint_path="resnet50_checkpoint.npz",
        mesh=mesh,
        loss_fn=softmax_cross_entropy_loss,
        profiler=profiler,
        log_every=10,
    )
    trainer.train(epochs)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="profiled ResNet-50 job (rung 5)")
    parser.add_argument("--epochs", default=3, type=int)
    parser.add_argument("--batch_size", default=32, type=int, help="per-chip batch size")
    parser.add_argument("--bf16", action="store_true", help="bfloat16 compute (MXU-native)")
    parser.add_argument("--no_profile", action="store_true")
    parser.add_argument("--logdir", default="log/resnet50", type=str)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args.epochs, args.batch_size, args.bf16, not args.no_profile, args.logdir)
