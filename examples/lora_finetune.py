"""LoRA fine-tuning rung: adapt a frozen TransformerLM with rank-r deltas.

No reference analog (the reference stops at from-scratch training);
parameter-efficient fine-tuning is the standard way a real fleet adapts a
pretrained model, and on a mesh its payoff is distributed: gradients, Adam
moments, and checkpoint deltas shrink to the adapter tree, so the grad
all-reduce and ZeRO-sharded state scale with rank x (m+n) per kernel, not
m x n (training/lora.py).

The script "pretrains" a small LM on one token distribution, then LoRA-
fine-tunes it on a shifted distribution with the base frozen — printing
the trainable-parameter ratio, per-epoch loss, and a before/after eval
showing the adapters (not the base) absorbed the shift. The merged export
then drives generation.generate.

Run:  python examples/lora_finetune.py --fake_devices 8   # CPU CI rig
      python examples/lora_finetune.py --rank 16          # real TPU
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


def token_stream(rng, n, seq, vocab, *, shift):
    """Markov-ish toy data: next token = current + shift (mod vocab) with
    noise — a distribution a tiny LM learns quickly, and whose ``shift``
    is the knob fine-tuning must absorb."""
    import numpy as np

    x = rng.integers(0, vocab, (n, 1), np.int32)
    rows = [x]
    for _ in range(seq - 1):
        nxt = (rows[-1] + shift) % vocab
        noise = rng.integers(0, vocab, nxt.shape, np.int32)
        take = rng.random(nxt.shape) < 0.1
        rows.append(np.where(take, noise, nxt).astype(np.int32))
    return np.concatenate(rows, axis=1)


def main(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_pytorch_tpu import (
        LoraModel,
        ShardedLoader,
        Trainer,
        generate,
        make_mesh,
    )
    from distributed_pytorch_tpu.models import TransformerLM
    from distributed_pytorch_tpu.training.losses import (
        softmax_cross_entropy_loss,
    )
    from distributed_pytorch_tpu.utils.data import ArrayDataset

    rng = np.random.default_rng(args.seed)
    vocab = 64
    model = TransformerLM(
        vocab_size=vocab, d_model=args.d_model, n_layers=args.n_layers,
        n_heads=4, d_ff=4 * args.d_model, dtype=jnp.float32,
    )
    mesh = make_mesh() if jax.device_count() > 1 else None

    def eval_loss(apply_params, seqs):
        logits = model.apply({"params": apply_params}, jnp.asarray(seqs[:, :-1]))
        return float(
            softmax_cross_entropy_loss(logits, jnp.asarray(seqs[:, 1:]))
        )

    # 1) "Pretrain" on shift=+1 data (full-parameter training).
    pre = token_stream(rng, args.n_train, args.seq, vocab, shift=1)
    loader = ShardedLoader(
        ArrayDataset(pre[:, :-1], pre[:, 1:]), args.batch_size
    )
    trainer = Trainer(model, loader, optax.adam(1e-2), save_every=0,
                      mesh=mesh, loss_fn=softmax_cross_entropy_loss)
    trainer.train(args.pretrain_epochs)
    # Host-side copy: the jitted step DONATES its state, so the pretrained
    # device buffers are consumed by fine-tuning's first step — anything we
    # want to compare against afterwards must be snapshotted now.
    base_params = jax.tree_util.tree_map(np.asarray, trainer.state.params)

    # 2) LoRA fine-tune on shift=+3 data; the base stays frozen.
    wrapped = LoraModel(model, rank=args.rank)
    ft = token_stream(rng, args.n_train, args.seq, vocab, shift=3)
    ft_loader = ShardedLoader(
        ArrayDataset(ft[:, :-1], ft[:, 1:]), args.batch_size
    )
    ft_trainer = Trainer(
        wrapped, ft_loader, optax.adam(1e-2), save_every=0, mesh=mesh,
        loss_fn=softmax_cross_entropy_loss,
    )
    # Start from the pretrained base, not a fresh init.
    ft_trainer.state = ft_trainer.state.replace(
        model_state={**ft_trainer.state.model_state, "lora_base": base_params}
    )
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(base_params))
    n_adapt = sum(
        x.size for x in jax.tree_util.tree_leaves(ft_trainer.state.params)
    )
    print(
        f"trainable: {n_adapt:,} adapter params over a frozen {n_base:,}-param "
        f"base ({n_adapt / n_base:.1%}) at rank {args.rank}"
    )
    eval_seqs = token_stream(rng, 256, args.seq, vocab, shift=3)
    before = eval_loss(base_params, eval_seqs)
    ft_trainer.train(args.epochs)

    merged = wrapped.merged_params(ft_trainer.state)
    after = eval_loss(merged, eval_seqs)
    # The frozen base must be bit-identical after fine-tuning.
    unchanged = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(
                ft_trainer.state.model_state["lora_base"]
            ),
            jax.tree_util.tree_leaves(base_params),
        )
    )
    print(
        f"shifted-distribution eval loss: base {before:.4f} -> "
        f"LoRA-merged {after:.4f} (base frozen: {unchanged})"
    )

    out = np.asarray(
        generate(model, merged, jnp.asarray(eval_seqs[:2, :4]), 8)
    )
    print(f"merged-export generation: {out[0].tolist()}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="LoRA fine-tuning rung")
    parser.add_argument("--rank", default=8, type=int)
    parser.add_argument("--d_model", default=64, type=int)
    parser.add_argument("--n_layers", default=2, type=int)
    parser.add_argument("--seq", default=16, type=int)
    parser.add_argument("--n_train", default=2048, type=int)
    parser.add_argument("--batch_size", default=64, type=int,
                        help="global batch size")
    parser.add_argument("--pretrain_epochs", default=3, type=int)
    parser.add_argument("--epochs", default=3, type=int)
    parser.add_argument("--seed", default=0, type=int)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args)
