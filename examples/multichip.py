"""Rung 2 — single-host data parallelism over all local chips.
Twin of ``multigpu.py``.

What the reference needed a process per GPU for (``mp.spawn``,
``init_process_group``, DDP wrapping, DistributedSampler — ``multigpu.py:12-36``)
is here ONE process and ONE jitted step over a ``data`` mesh: JAX addresses all
local chips from a single Python process, the global batch is sharded along the
mesh's ``data`` axis, and XLA inserts the gradient all-reduce onto ICI.

``batch_size`` is per-chip (matching the reference's per-rank semantics); the
global batch is ``batch_size * n_chips``.

Run:  python examples/multichip.py 10 2 [--batch_size 32]
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


import jax
import optax

from distributed_pytorch_tpu import MaterializedDataset, ShardedLoader, Trainer, make_mesh
from distributed_pytorch_tpu.models import ToyRegressor


def load_train_objs():
    """Factory twin of ``multigpu.py:65-69``."""
    dataset = MaterializedDataset(2048)
    model = ToyRegressor()
    optimizer = optax.sgd(1e-3)
    return dataset, model, optimizer


def main(total_epochs: int, save_every: int, batch_size: int):
    mesh = make_mesh()  # 1-D {"data": all local chips}
    n_chips = jax.device_count()
    dataset, model, optimizer = load_train_objs()
    # One process feeds the full global batch; the mesh shards it across chips.
    # (Per-process sharding appears at rung 4 when hosts multiply.)
    loader = ShardedLoader(dataset, batch_size * n_chips, shuffle=True)
    trainer = Trainer(model, loader, optimizer, save_every, mesh=mesh)
    trainer.train(total_epochs)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="single-host data-parallel job (rung 2)")
    parser.add_argument("total_epochs", type=int, help="Total epochs to train the model")
    parser.add_argument("save_every", type=int, help="How often to save a checkpoint")
    parser.add_argument("--batch_size", default=32, type=int,
                        help="Input batch size per chip (default: 32)")
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args.total_epochs, args.save_every, args.batch_size)
