"""Rung 3 — env-driven bootstrap + elastic snapshot resume.
Twin of ``multigpu_torchrun.py``.

The torchrun contract (env rendezvous + restart-and-resume,
``multigpu_torchrun.py:12-13,30-40,57-65``) maps to:

* rendezvous: ``setup_distributed()`` reads ``COORDINATOR_ADDRESS`` /
  ``NUM_PROCESSES`` / ``PROCESS_ID`` (the MASTER_ADDR / WORLD_SIZE / RANK
  analogs) and calls ``jax.distributed.initialize``; unset -> single process.
* elasticity: if ``snapshot.npz`` exists the Trainer loads it on init and
  ``train()`` resumes from ``epochs_run``. Kill any process mid-run, relaunch
  the same command, and training continues from the last snapshot — including
  optimizer state, which the reference forgets.

Run (single host):    python examples/multichip_envrun.py 10 2
Run (N processes):    COORDINATOR_ADDRESS=host0:1234 NUM_PROCESSES=N PROCESS_ID=i \
                          python examples/multichip_envrun.py 10 2
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


import jax
import optax

from distributed_pytorch_tpu import (
    MaterializedDataset,
    ShardedLoader,
    Trainer,
    make_mesh,
    setup_distributed,
    shutdown_distributed,
)
from distributed_pytorch_tpu.models import ToyRegressor


def load_train_objs():
    """Factory twin of ``multigpu_torchrun.py:71-75``."""
    dataset = MaterializedDataset(2048)
    model = ToyRegressor()
    optimizer = optax.sgd(1e-3)
    return dataset, model, optimizer


def main(total_epochs: int, save_every: int, batch_size: int, snapshot_path: str):
    setup_distributed()  # env-driven; no-op when single-process
    mesh = make_mesh()
    dataset, model, optimizer = load_train_objs()
    # Each process loads only the shard its chips will consume.
    per_process_batch = batch_size * jax.local_device_count()
    loader = ShardedLoader(
        dataset,
        per_process_batch,
        shuffle=True,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    trainer = Trainer(
        model, loader, optimizer, save_every, snapshot_path=snapshot_path, mesh=mesh
    )
    trainer.train(total_epochs)
    shutdown_distributed()


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="env-bootstrapped elastic training job (rung 3)"
    )
    parser.add_argument("total_epochs", type=int, help="Total epochs to train the model")
    parser.add_argument("save_every", type=int, help="How often to save a snapshot")
    parser.add_argument("--batch_size", default=32, type=int,
                        help="Input batch size per chip (default: 32)")
    parser.add_argument("--snapshot_path", default="snapshot.npz", type=str)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args.total_epochs, args.save_every, args.batch_size, args.snapshot_path)
