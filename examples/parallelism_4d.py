"""Beyond-parity showcase — composing every parallelism axis the framework
supports on one host: DP x TP (megatron param sharding) x SP (ring attention)
on a TransformerLM, then DP x EP (mixture-of-experts) and DP x PP (GPipe
pipeline) variants.

The reference ladder stops at data parallelism (SURVEY.md §2b); this script is
where the additional axes become user-visible. Everything is placement
annotations over the same jitted train step — no model code changes between
configurations.

Run:  python examples/parallelism_4d.py --steps 10 --fake_devices 8
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


def run_config(name, model, mesh, rules, tokens, steps, batch_spec=None):
    import jax
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P  # noqa: F401

    from distributed_pytorch_tpu.parallel.partitioning import (
        make_param_specs,
        make_state_shardings,
        shard_train_state,
    )
    from distributed_pytorch_tpu.parallel.sharding import (
        put_global_batch,
        replicated_sharding,
    )
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    optimizer = optax.adam(1e-3)
    state = create_train_state(model, optimizer, inputs)
    if rules:
        specs = make_param_specs(state.params, rules, mesh=mesh)
        shardings = make_state_shardings(mesh, state, specs)
    else:
        shardings = replicated_sharding(mesh)
    state = shard_train_state(state, shardings)
    step = make_train_step(
        model.apply, optimizer, softmax_cross_entropy_loss,
        mesh=mesh,
        state_sharding=shardings if rules else None,
        batch_spec=batch_spec,
    )
    batch = put_global_batch(mesh, (inputs, targets), spec=batch_spec)
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    print(
        f"[{name}] mesh={dict(mesh.shape)} "
        f"loss {losses[0]:.4f} -> {losses[-1]:.4f}",
        flush=True,
    )


def main(steps: int):
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from distributed_pytorch_tpu.models import (
        PipelinedTransformerLM,
        TransformerLM,
    )
    from distributed_pytorch_tpu.models.moe import MOE_EP_RULES
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.partitioning import TRANSFORMER_TP_RULES
    from distributed_pytorch_tpu.parallel.pipeline import PIPELINE_STAGE_RULES

    n = jax.device_count()
    assert n % 4 == 0, f"need a multiple of 4 devices, have {n}"
    dp = n // 4
    rng = np.random.default_rng(0)

    # --- DP x SP x TP: long-context ring attention + megatron shards ------
    mesh = make_mesh({"data": dp, "sequence": 2, "tensor": 2})
    lm = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        mesh=mesh, sequence_axis="sequence",
    )
    tokens = rng.integers(0, 256, (4 * dp, 129), dtype=np.int32)
    run_config(
        "dp x sp x tp", lm, mesh, TRANSFORMER_TP_RULES, tokens, steps,
        batch_spec=P("data", "sequence"),
    )

    # --- DP x SP(ulysses) x TP: the all-to-all SP strategy ---------------
    # SAME mesh and SAME tokens as the ring block above, only
    # sequence_mode="ulysses" (two all-to-alls redistribute seq->heads;
    # needs (n_heads / tp) % sp == 0 — here 4/2 = 2 local heads over
    # sp=2), so the two strategies' printed losses are directly
    # comparable.
    uly = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        mesh=mesh, sequence_axis="sequence", sequence_mode="ulysses",
    )
    run_config(
        "dp x sp(ulysses) x tp", uly, mesh, TRANSFORMER_TP_RULES, tokens,
        steps, batch_spec=P("data", "sequence"),
    )

    # --- DP x EP: mixture-of-experts over the expert axis -----------------
    mesh = make_mesh({"data": dp, "expert": 4})
    moe = TransformerLM(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        n_experts=4, moe_every=2, mesh=mesh,
    )
    tokens = rng.integers(0, 256, (4 * dp, 65), dtype=np.int32)
    run_config("dp x ep", moe, mesh, MOE_EP_RULES, tokens, steps)

    # --- DP x PP: GPipe pipeline over the stage axis ----------------------
    mesh = make_mesh({"data": dp, "stage": 4})
    pp = PipelinedTransformerLM(
        vocab_size=256, d_model=64, n_stages=4, layers_per_stage=1,
        n_heads=4, d_ff=128, num_microbatches=4, mesh=mesh,
    )
    tokens = rng.integers(0, 256, (8 * dp, 65), dtype=np.int32)
    run_config("dp x pp", pp, mesh, PIPELINE_STAGE_RULES, tokens, steps)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="composed-parallelism showcase")
    parser.add_argument("--steps", default=10, type=int)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices

        use_fake_cpu_devices(args.fake_devices)
    main(args.steps)
