"""Rung 4 — multi-host TPU pod-slice training. Twin of ``multinode_torchrun.py``
plus the ``slurm/`` launcher directory.

Differences from rung 3 are exactly the reference's rung-3 -> rung-4 diff,
restated for TPU:

* local vs global rank (``multinode_torchrun.py:24-25``): JAX owns the split —
  ``jax.process_index()`` is the global identity, local device binding is
  automatic. Logging uses the global process index, like the reference's
  ``global_rank`` banner (``:52``).
* the launcher: ``launch/tpu_pod_run.sh`` (gcloud ``--worker=all``) replaces
  ``slurm/sbatch_run.sh``; on a real pod slice ``jax.distributed.initialize``
  autodetects topology so no env is needed at all.
* the global batch spans hosts: each process feeds only its addressable shard
  (``put_global_batch`` inside the Trainer assembles the global array) — and
  the snapshot is written by *global* process 0 only, fixing the reference's
  per-node multi-writer race (``multinode_torchrun.py:68``).

Run on a pod slice (from launch/tpu_pod_run.sh):
    gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
        --command="cd /path/to/repo && python examples/multihost_pod.py 50 5"
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


import jax
import optax

from distributed_pytorch_tpu import (
    MaterializedDataset,
    ShardedLoader,
    Trainer,
    make_mesh,
    setup_distributed,
    shutdown_distributed,
)
from distributed_pytorch_tpu.models import ToyRegressor
from distributed_pytorch_tpu.training.losses import mse_loss


def load_train_objs():
    """Factory twin of ``multinode_torchrun.py:72-76`` (MSE loss here — the one
    rung where the reference's loss matches its regression head)."""
    dataset = MaterializedDataset(2048)
    model = ToyRegressor()
    optimizer = optax.sgd(1e-3)
    return dataset, model, optimizer


def main(total_epochs: int, save_every: int, batch_size: int, snapshot_path: str):
    setup_distributed()  # pod metadata / env / single-process, in that order
    print(
        f"[proc {jax.process_index()}/{jax.process_count()}] "
        f"{jax.local_device_count()} local / {jax.device_count()} global chips",
        flush=True,
    )
    mesh = make_mesh()
    dataset, model, optimizer = load_train_objs()
    loader = ShardedLoader(
        dataset,
        batch_size * jax.local_device_count(),
        shuffle=True,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
    )
    trainer = Trainer(
        model,
        loader,
        optimizer,
        save_every,
        snapshot_path=snapshot_path,
        mesh=mesh,
        loss_fn=mse_loss,
    )
    trainer.train(total_epochs)
    shutdown_distributed()


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="multi-host pod training job (rung 4)")
    parser.add_argument("total_epochs", type=int, help="Total epochs to train the model")
    parser.add_argument("save_every", type=int, help="How often to save a snapshot")
    parser.add_argument("--batch_size", default=32, type=int,
                        help="Input batch size per chip (default: 32)")
    parser.add_argument("--snapshot_path", default="snapshot.npz", type=str)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args.total_epochs, args.save_every, args.batch_size, args.snapshot_path)
