"""Rung 6 — beyond the reference ladder: long-context LM training with
sequence parallelism (ring attention, or ulysses all-to-all via --sp_mode).

The reference has no attention code at all (SURVEY.md §5: "sequence length is
not a concept in this codebase"); this rung exercises the framework machinery
the reference never reaches: a ``data x sequence`` mesh, batch sharded on
``data``, sequence dim sharded on ``sequence``, K/V shards rotating over the
ICI ring inside each attention layer (``ops/attention.py::ring_attention``)
so per-chip attention memory stays O(T / n_sequence_chips).

Run:  python examples/longcontext_lm.py --steps 20 --seq_len 2048 \
          --data_parallel 2 --sequence_parallel 4 --fake_devices 8
      # the all-to-all strategy (needs n_heads divisible by SP size):
      python examples/longcontext_lm.py --sp_mode ulysses ...
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time


def main(args):
    import jax
    import numpy as np
    import optax

    from distributed_pytorch_tpu.models import TransformerLM
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    from distributed_pytorch_tpu.parallel.sharding import replicated_sharding
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    mesh = make_mesh(
        {"data": args.data_parallel, "sequence": args.sequence_parallel}
    )
    print(f"mesh: {dict(mesh.shape)} over {mesh.devices.size} devices", flush=True)

    model = TransformerLM(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        d_ff=4 * args.d_model,
        remat=args.remat != "none",
        remat_policy="full" if args.remat == "none" else args.remat,
        mesh=mesh,
        sequence_axis="sequence",
        sequence_mode=args.sp_mode,
        fused_head_chunk=args.fused_head_chunk,
    )
    optimizer = optax.adamw(3e-4)
    fused = args.fused_head_chunk > 0

    rng = np.random.default_rng(0)
    tokens = rng.integers(
        0, args.vocab_size, (args.batch_size, args.seq_len + 1), dtype=np.int32
    )
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    state = create_train_state(model, optimizer, inputs)
    state = jax.device_put(state, replicated_sharding(mesh))
    # With the fused head the model consumes targets and returns the scalar
    # loss itself; the [B*T, vocab] logits tensor is never materialized.
    step = make_train_step(
        model.apply,
        optimizer,
        (lambda out, _: out) if fused else softmax_cross_entropy_loss,
        mesh=mesh,
        apply_takes_targets=fused,
    )

    # The batch is sharded over "data"; inside each attention layer the
    # sequence dim is re-sharded over "sequence" by the shard_map.
    from distributed_pytorch_tpu.parallel.sharding import put_global_batch

    batch = put_global_batch(mesh, (inputs, targets))
    state, loss = step(state, batch)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    tok_per_s = args.steps * args.batch_size * args.seq_len / dt
    print(
        f"loss={float(loss):.4f}  {args.steps} steps in {dt:.2f}s  "
        f"({tok_per_s:,.0f} tokens/s)",
        flush=True,
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="long-context LM with ring attention")
    parser.add_argument("--steps", default=10, type=int)
    parser.add_argument("--seq_len", default=2048, type=int)
    parser.add_argument("--batch_size", default=2, type=int, help="global batch")
    parser.add_argument("--vocab_size", default=1024, type=int)
    parser.add_argument("--d_model", default=128, type=int)
    parser.add_argument("--n_layers", default=2, type=int)
    parser.add_argument("--n_heads", default=4, type=int)
    parser.add_argument("--data_parallel", default=2, type=int)
    parser.add_argument("--sequence_parallel", default=4, type=int)
    parser.add_argument(
        "--sp_mode", default="ring", choices=["ring", "ulysses"],
        help="sequence-parallel strategy: ring (K/V rotation, O(T/sp) "
        "memory) or ulysses (all-to-all seq->heads, local full-T flash)",
    )
    parser.add_argument(
        "--remat", default="none", choices=["none", "full", "mlp"],
        help="rematerialization: none (flash keeps activations linear in T — "
        "fastest, measured +18%% over full at T=8k), mlp (recompute only the "
        "d_ff activations), full (whole block; re-runs flash fwd in backward)",
    )
    parser.add_argument("--fused_head_chunk", default=0, type=int,
                        help=">0: fused LM-head cross-entropy with this vocab "
                        "chunk size (never materializes the logits)")
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices instead of real chips")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args)
