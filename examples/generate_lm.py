"""Text generation from a trained (or randomly initialized) TransformerLM.

The inference-side rung — no reference analog (the reference ladder stops at
training, SURVEY.md §0); a complete framework needs the sampling path. The
whole decode is ONE compiled ``lax.fori_loop`` (generation.py): greedy or
temperature/top-k sampling, ragged prompts, KV caches updated in place.

Flags tour:
  --snapshot PATH     load params from a training snapshot (else seeded init)
  --quantize          weight-only int8 decode (ops/quant.py): ~half the
                      weight HBM traffic; greedy outputs typically identical
  --speculative       draft-model speculative decode (speculative.py):
                      gamma-token proposals verified in one chunked target
                      forward; greedy-exact, prints acceptance stats
  --fake_devices N    run on N virtual CPU devices; with N > 1 the decode is
                      sharded over a data mesh (batch + KV caches P("data"))

Run:  python examples/generate_lm.py --batch 4 --new_tokens 32 [--quantize]
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np


def maybe_data_mesh(args, jax):
    """The shared mesh-gating rule for every decode branch: shard when
    multi-device AND the batch divides; otherwise say so out loud (a
    silent single-device fallback would contradict the --fake_devices
    help's sharding promise)."""
    if jax.device_count() <= 1:
        return None
    if args.batch % jax.device_count() != 0:
        print(
            f"[generate_lm] batch {args.batch} does not divide over "
            f"{jax.device_count()} devices - decoding SINGLE-device",
            flush=True,
        )
        return None
    from distributed_pytorch_tpu.parallel.mesh import make_mesh

    return make_mesh()


def main(args):
    import jax
    import jax.numpy as jnp

    from distributed_pytorch_tpu.generation import generate
    from distributed_pytorch_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        attention_window=args.window,
        rope_scale=args.rope_scale,
        rope_theta=args.rope_theta,
        d_ff=4 * args.d_model,
        dtype=jnp.float32 if args.f32 else jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    if args.snapshot:
        from distributed_pytorch_tpu.checkpoint import load_snapshot
        from distributed_pytorch_tpu.training.train_step import TrainState

        template = TrainState(
            params=params, model_state={}, opt_state=(), step=jnp.zeros((), jnp.int32)
        )
        state, _ = load_snapshot(args.snapshot, template)
        params = state.params

    rng = np.random.default_rng(args.seed)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab, (args.batch, args.prompt_len)), jnp.int32
    )

    if args.speculative:
        # No silent flag drops: speculation (greedy or sampled — the
        # temperature/top_k/top_p flags pass through) runs full-precision,
        # single-device or data-mesh-sharded (multi-device batches shard
        # below like plain decode).
        dropped = [
            name
            for name, active in (
                ("--beam", args.beam > 0),
                ("--length_penalty", args.length_penalty != 0),
                ("--quantize", args.quantize),
                ("--quantized_cache", args.quantized_cache),
            )
            if active
        ]
        if dropped:
            raise SystemExit(
                f"--speculative is full-precision decode; "
                f"incompatible with {', '.join(dropped)}"
            )
        # Speculative decode against a width/depth-reduced draft sharing
        # the vocabulary (randomly initialized here — a real draft would
        # be trained/distilled; acceptance statistics show the machinery
        # either way, and the OUTPUT is exactly the target's own decode by
        # construction: greedy-exact at temperature 0, target-distributed
        # rejection sampling above it — see speculative.py).
        from distributed_pytorch_tpu.speculative import speculative_generate

        draft = model.clone(
            d_model=max(args.d_model // 4, 8),
            n_layers=max(args.n_layers // 2, 1),
            d_ff=max(args.d_model, 32),
        )
        draft_params = draft.init(
            jax.random.PRNGKey(args.seed + 1), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        spec_mesh = maybe_data_mesh(args, jax)
        gamma = 4 if args.gamma is None else args.gamma
        out, stats = speculative_generate(
            model, params, draft, draft_params, prompt, args.new_tokens,
            gamma=gamma, return_stats=True,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, rng=jax.random.PRNGKey(args.seed),
            mesh=spec_mesh,
        )
        out = np.asarray(out)
        rounds = int(stats["rounds"])
        adv = int(stats["positions_advanced"])
        for row in range(min(args.batch, 4)):
            ids = out[row]
            print(
                f"[row {row}] prompt={ids[:args.prompt_len].tolist()} "
                f"-> continuation={ids[args.prompt_len:].tolist()}"
            )
        print(
            f"speculative: {rounds} target chunk-forwards for {adv} "
            f"positions (mean accepted chunk {adv / max(rounds, 1):.2f} "
            f"of gamma={gamma})"
        )
        return

    if args.beam:
        from distributed_pytorch_tpu.generation import beam_search

        # Same no-silent-flag-drops contract as --speculative above.
        blocked = [
            name
            for name, active in (
                ("sampling flags (deterministic search)",
                 args.temperature > 0 or args.top_k > 0
                 or 0 < args.top_p < 1),
                ("--gamma (speculative-only)", args.gamma is not None),
                ("--quantize", args.quantize),
                ("--quantized_cache", args.quantized_cache),
            )
            if active
        ]
        if blocked:
            raise SystemExit(
                f"--beam is full-precision deterministic search; incompatible with {', '.join(blocked)}"
            )
        beam_mesh = maybe_data_mesh(args, jax)
        out, scores = beam_search(
            model, params, prompt, args.new_tokens, beam_size=args.beam,
            length_penalty=args.length_penalty, mesh=beam_mesh,
        )
        out, scores = np.asarray(out), np.asarray(scores)
        for row in range(min(args.batch, 2)):
            for k in range(min(args.beam, 3)):
                ids = out[row, k]
                print(
                    f"[row {row} beam {k}] score={scores[row, k]:.3f} "
                    f"-> {ids[args.prompt_len:].tolist()}"
                )
        print(
            f"beam search: {args.batch}x{args.beam} beams x "
            f"{args.new_tokens} tokens"
        )
        return

    mesh = maybe_data_mesh(args, jax)
    out = generate(
        model,
        params,
        prompt,
        args.new_tokens,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        mesh=mesh,
        quantize=args.quantize,
        quantized_cache=args.quantized_cache,
    )
    out = np.asarray(out)
    for row in range(min(args.batch, 4)):
        ids = out[row]
        print(
            f"[row {row}] prompt={ids[:args.prompt_len].tolist()} "
            f"-> continuation={ids[args.prompt_len:].tolist()}"
        )
    parts = []
    if args.quantize:
        parts.append("int8 weights")
    if args.quantized_cache:
        parts.append("int8 KV cache")
    mode = " + ".join(parts) if parts else "full precision"
    where = f"{jax.device_count()}-device mesh" if mesh else "single device"
    print(f"generated {args.batch}x{args.new_tokens} tokens ({mode}, {where})")


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="LM generation (inference rung)")
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--d_model", type=int, default=128)
    parser.add_argument("--n_layers", type=int, default=4)
    parser.add_argument("--n_heads", type=int, default=4)
    parser.add_argument(
        "--n_kv_heads", type=int, default=0,
        help="grouped-query attention: K/V heads (0 = n_heads/MHA, 1 = "
        "MQA); the decode cache stores only these",
    )
    parser.add_argument(
        "--window", type=int, default=0,
        help="sliding-window attention: each position attends the last W "
        "tokens only (0 = full causal)",
    )
    parser.add_argument(
        "--rope_scale", type=float, default=1.0,
        help="RoPE linear position interpolation (context extension): "
        "positions divided by this factor",
    )
    parser.add_argument(
        "--rope_theta", type=float, default=10000.0,
        help="RoPE frequency base (raise for NTK-style context extension)",
    )
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--prompt_len", type=int, default=8)
    parser.add_argument("--new_tokens", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="0 = greedy argmax")
    parser.add_argument("--top_k", type=int, default=0)
    parser.add_argument("--top_p", type=float, default=0.0,
                        help="nucleus sampling: keep the smallest token set "
                        "reaching this cumulative mass (0 or >=1 disables)")
    parser.add_argument("--speculative", action="store_true",
                        help="speculative decode with a reduced draft model "
                        "(speculative.py): greedy by default, modified "
                        "rejection sampling with --temperature (exactly "
                        "target-distributed either way); prints acceptance "
                        "stats")
    parser.add_argument("--gamma", type=int, default=None,
                        help="speculative proposal chunk length (default 4)")
    parser.add_argument("--beam", type=int, default=0,
                        help="beam_search with this many beams (prints "
                        "top sequences + true log-prob scores)")
    parser.add_argument("--length_penalty", type=float, default=0.0)
    parser.add_argument("--quantize", action="store_true",
                        help="weight-only int8 decode")
    parser.add_argument("--quantized_cache", action="store_true",
                        help="int8 KV cache (halves long-context decode memory)")
    parser.add_argument("--f32", action="store_true",
                        help="float32 compute instead of the bf16 default")
    parser.add_argument("--snapshot", default=None,
                        help="load params from a training snapshot")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices

        use_fake_cpu_devices(args.fake_devices)
    main(args)
