"""Rung 5b — real data: ResNet-18 on CIFAR-10 with exact eval accuracy.

The reference's "real model" rung swaps a torchvision model onto its loader
(``multigpu_profile.py:13-27``, with the ViT alternative commented at
``:23-24``) but never trains on real data or evaluates. This rung completes
the story the way BASELINE.json configs[4] ("ResNet-18 / CIFAR-10") asks:
real (or clearly-labeled synthetic stand-in) CIFAR-10, normalized NHWC, SGD +
momentum + cosine decay, and per-epoch **exact** eval accuracy via the
Trainer's per-sample-weighted evaluation (wrap-pad duplicates weighted out —
see ``Trainer.evaluate``).

Run (real TPU, real data if ``--data_dir`` holds CIFAR-10, labeled synthetic
stand-in otherwise — this rig has no egress):

    python examples/real_data.py --epochs 4
    python examples/real_data.py --epochs 2 --fake_devices 8   # CPU CI rig
"""

import os
import sys

# Make the repo importable when run as `python tools/x.py` / `python examples/x.py`
# (sys.path[0] is the script's dir, not the repo root).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse


def main(args):
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_pytorch_tpu import ShardedLoader, Trainer, make_mesh
    from distributed_pytorch_tpu.models.resnet import ResNet18
    from distributed_pytorch_tpu.training.losses import (
        per_sample_accuracy,
        softmax_cross_entropy_loss,
    )
    from distributed_pytorch_tpu.utils.datasets import (
        AugmentedDataset,
        as_datasets,
        cifar10_or_synthetic,
    )

    arrays, is_real = cifar10_or_synthetic(
        args.data_dir, smooth_frac=args.smooth_frac
    )
    if args.subset:
        n_test = max(args.subset // 5, 1)
        arrays = tuple(a[: n] for a, n in zip(
            arrays, (args.subset, args.subset, n_test, n_test)
        ))
    oracle = None
    if not is_real:
        # The stand-in's Bayes ceiling (~0.935 at defaults): the number the
        # eval-accuracy curve should converge toward over epochs — printed so
        # the curve is interpretable, not just "went up".
        from distributed_pytorch_tpu.utils.datasets import (
            synthetic_oracle_accuracy,
        )

        oracle = synthetic_oracle_accuracy(
            arrays[2], arrays[3], smooth_frac=args.smooth_frac
        )
        print(f"[datasets] synthetic Bayes-oracle accuracy: {oracle:.4f}")
        if args.augment:
            # No silent caps: crop/flip assume translation/flip invariance,
            # which the stand-in's pixel-aligned templates do not have —
            # measured on this rig, augmentation pins eval accuracy at
            # chance (BASELINE.md round 4). Real CIFAR-10 wants it; the
            # synthetic stand-in does not.
            print(
                "[datasets] WARNING: --augment on the synthetic stand-in "
                "destroys its pixel-aligned signal; expect chance-level "
                "eval accuracy. Drop --augment for synthetic runs.",
                flush=True,
            )
    train_ds, test_ds = as_datasets(arrays)
    if args.augment:
        # Standard CIFAR recipe (pad-4 random crop + flip) — what a sane
        # real-CIFAR accuracy needs; deterministic per (seed, epoch, index).
        train_ds = AugmentedDataset(train_ds)

    n_chips = jax.device_count()
    mesh = make_mesh() if n_chips > 1 else None
    global_batch = args.batch_size * n_chips
    train_loader = ShardedLoader(train_ds, global_batch, shuffle=True)
    eval_loader = ShardedLoader(test_ds, global_batch)

    steps_per_epoch = len(train_loader)
    schedule = optax.cosine_decay_schedule(
        args.lr, args.epochs * steps_per_epoch
    )
    optimizer = optax.chain(
        optax.add_decayed_weights(5e-4),
        optax.sgd(schedule, momentum=0.9, nesterov=True),
    )
    model = ResNet18(
        num_classes=10, cifar_stem=True, dtype=jnp.bfloat16,
        num_filters=args.width,
    )
    trainer = Trainer(
        model,
        train_loader,
        optimizer,
        save_every=0,
        mesh=mesh,
        loss_fn=softmax_cross_entropy_loss,
        log_every=args.log_every,
    )

    metric_fns = {"accuracy": per_sample_accuracy}
    metrics = {}
    for epoch in range(args.epochs):
        trainer._run_epoch(epoch)
        trainer.epochs_run = epoch + 1
        metrics = trainer.evaluate(eval_loader, metric_fns=metric_fns)
        tag = "real CIFAR-10" if is_real else (
            f"synthetic stand-in, oracle {oracle:.4f}"
        )
        print(
            f"epoch {epoch}: eval_loss={metrics.get('loss', float('nan')):.4f} "
            f"eval_accuracy={metrics.get('accuracy', float('nan')):.4f} "
            f"({tag})",
            flush=True,
        )
    return metrics


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="ResNet-18 on CIFAR-10 (rung 5b)")
    parser.add_argument("--epochs", default=4, type=int)
    parser.add_argument("--batch_size", default=128, type=int,
                        help="per-chip batch size")
    parser.add_argument("--lr", default=0.1, type=float)
    parser.add_argument("--data_dir", default="data", type=str)
    parser.add_argument("--augment", action="store_true",
                        help="pad-4 random crop + horizontal flip (the "
                        "standard CIFAR training recipe)")
    parser.add_argument("--subset", default=0, type=int,
                        help="debug: use only the first N train samples")
    parser.add_argument("--width", default=64, type=int,
                        help="stem filter count (64 = standard ResNet-18; "
                        "smaller = width-reduced variant for CPU-scale runs "
                        "where the full net overfits small subsets, "
                        "BASELINE.md round 4)")
    parser.add_argument("--smooth_frac", default=0.5, type=float,
                        help="stand-in only: fraction of template variance "
                        "in a low-frequency component. Spatially-WHITE "
                        "templates (0.0) are unlearnable by a conv stack "
                        "with global average pooling — the Bayes rule is a "
                        "position-specific matched filter weight sharing "
                        "cannot express (measured: ResNet-18 stays at "
                        "chance while a linear probe reaches the oracle "
                        "band; BASELINE.md rounds 4-5). Real images are "
                        "low-frequency dominated, so 0.5 is the more "
                        "CIFAR-faithful default; ignored with real data.")
    parser.add_argument("--log_every", default=0, type=int)
    parser.add_argument("--fake_devices", default=0, type=int,
                        help="debug: present N virtual CPU devices")
    args = parser.parse_args()
    if args.fake_devices:
        from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices
        use_fake_cpu_devices(args.fake_devices)
    main(args)
