"""Pallas flash-attention kernel tests, run in interpreter mode on CPU
(the same kernel code lowers to Mosaic on a real TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention import dot_product_attention
from distributed_pytorch_tpu.ops.flash_attention import flash_attention


def make_qkv(b=2, t=32, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = make_qkv()
    out = flash_attention(
        q, k, v, causal=causal, block_q=8, block_k=8, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(causal):
    q, k, v = make_qkv(t=16)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8, interpret=True
            )
            ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_uneven_blocks_mismatched_qk():
    """block_q != block_k exercises the diagonal bookkeeping."""
    q, k, v = make_qkv(t=48)
    out = flash_attention(
        q, k, v, causal=True, block_q=8, block_k=16, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_block_fitting_finds_divisor():
    """t=40 with requested block 128: largest multiple-of-8 divisor (40) is
    used rather than falling back to dense — verify via numerics (the kernel
    path is exercised because interpret=True)."""
    q, k, v = make_qkv(t=40)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_under_mesh_matches_dense():
    """With a mesh, the kernel runs under shard_map (per-device batch shard)
    and still matches dense attention."""
    from distributed_pytorch_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 2, "tensor": 2}, devices=jax.devices()[:4])
    q, k, v = make_qkv(b=4, t=16, h=2, d=8)
    out = jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=8, block_k=8,
            interpret=True, mesh=mesh,
        )
    )(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_fallback_on_non_tiling_shape():
    """A prime sequence length can't tile: falls back to dense, still right."""
    q, k, v = make_qkv(t=17)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_cpu_backend_defaults_to_dense():
    """interpret=None off-TPU returns the dense path (fast CI), bit-identical."""
    q, k, v = make_qkv(t=16)
    out = flash_attention(q, k, v, causal=False)
    ref = dot_product_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0)
