"""Pallas flash-attention kernel tests, run in interpreter mode on CPU
(the same kernel code lowers to Mosaic on a real TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.ops.attention import dot_product_attention
from distributed_pytorch_tpu.ops.flash_attention import flash_attention


def make_qkv(b=2, t=32, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = make_qkv()
    out = flash_attention(
        q, k, v, causal=causal, block_q=8, block_k=8, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(causal):
    q, k, v = make_qkv(t=16)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8, interpret=True
            )
            ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_uneven_blocks_mismatched_qk():
    """block_q != block_k exercises the diagonal bookkeeping."""
    q, k, v = make_qkv(t=48)
    out = flash_attention(
        q, k, v, causal=True, block_q=8, block_k=16, interpret=True
    )
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_block_fitting_finds_divisor():
    """t=40 with requested block 128: largest multiple-of-8 divisor (40) is
    used rather than falling back to dense — verify via numerics (the kernel
    path is exercised because interpret=True)."""
    q, k, v = make_qkv(t=40)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_under_mesh_matches_dense():
    """With a mesh, the kernel runs under shard_map (per-device batch shard)
    and still matches dense attention."""
    from distributed_pytorch_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 2, "tensor": 2}, devices=jax.devices()[:4])
    q, k, v = make_qkv(b=4, t=16, h=2, d=8)
    out = jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=8, block_k=8,
            interpret=True, mesh=mesh,
        )
    )(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_fallback_on_non_tiling_shape():
    """A prime sequence length can't tile: falls back to dense, still right."""
    q, k, v = make_qkv(t=17)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_cpu_backend_defaults_to_dense():
    """interpret=None off-TPU returns the dense path (fast CI), bit-identical."""
    q, k, v = make_qkv(t=16)
    out = flash_attention(q, k, v, causal=False)
    ref = dot_product_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0)


class TestSlidingWindow:
    """window > 0: banded causal attention — kernel must match the dense
    banded reference exactly, including tiles straddling the band edges and
    degenerate windows (1 = self-only, > T = plain causal)."""

    def _qkv(self, b=2, t=64, h=2, d=16, seed=3):
        rng = np.random.default_rng(seed)
        return tuple(
            jnp.asarray(rng.standard_normal((b, t, h, d)) * 0.5, jnp.float32)
            for _ in range(3)
        )

    @pytest.mark.parametrize("window", [1, 5, 16, 40, 200])
    def test_forward_matches_dense_band(self, window):
        q, k, v = self._qkv()
        ref = dot_product_attention(q, k, v, causal=True, window=window)
        out = flash_attention(
            q, k, v, causal=True, window=window, interpret=True,
            block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("window", [5, 16, 40])
    def test_gradients_match_dense_band(self, window):
        q, k, v = self._qkv()

        def dense_loss(q, k, v):
            return jnp.sum(
                dot_product_attention(q, k, v, causal=True, window=window)
                ** 2
            )

        def flash_loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True, window=window, interpret=True,
                    block_q=16, block_k=16,
                )
                ** 2
            )

        ref = jax.grad(dense_loss, (0, 1, 2))(q, k, v)
        got = jax.grad(flash_loss, (0, 1, 2))(q, k, v)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_window_equals_full_causal_when_large(self):
        q, k, v = self._qkv(t=32)
        full = flash_attention(
            q, k, v, causal=True, interpret=True, block_q=16, block_k=16
        )
        banded = flash_attention(
            q, k, v, causal=True, window=32, interpret=True,
            block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(banded), np.asarray(full), rtol=1e-6
        )

    def test_window_requires_causal(self):
        q, k, v = self._qkv(t=16)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=4)
        with pytest.raises(ValueError, match="causal"):
            dot_product_attention(q, k, v, causal=False, window=4)

    def test_negative_window_rejected(self):
        q, k, v = self._qkv(t=16)
        with pytest.raises(ValueError, match=">= 0"):
            flash_attention(q, k, v, causal=True, window=-1)
        with pytest.raises(ValueError, match=">= 0"):
            dot_product_attention(q, k, v, causal=True, window=-1)
