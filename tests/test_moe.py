"""Mixture-of-Experts / expert-parallelism tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.models import TransformerLM
from distributed_pytorch_tpu.models.moe import MOE_EP_RULES, MoEMLP
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    make_param_specs,
    make_state_shardings,
    shard_train_state,
)
from distributed_pytorch_tpu.parallel.sharding import (
    put_global_batch,
    replicated_sharding,
)
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def moe_lm(mesh=None, n_experts=4):
    return TransformerLM(
        vocab_size=64, d_model=16, n_layers=2, n_heads=4, d_ff=32,
        n_experts=n_experts, moe_every=2, mesh=mesh,
    )


def make_batch(n_rows=4):
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 64, (n_rows, 17), dtype=np.int32)
    return tokens[:, :-1], tokens[:, 1:]


@pytest.mark.slow
def test_moe_mlp_routes_all_tokens_with_ample_capacity():
    """With capacity_factor >= n_experts every token gets a slot, so the MoE
    layer output equals running each token through its argmax expert."""
    layer = MoEMLP(n_experts=2, d_ff=8, d_model=4, capacity_factor=2.0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 6, 4)), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)
    # Pass params only: sow APPENDS to a passed-in "losses" collection, so the
    # train step strips it before apply (see create_train_state) — mirror that.
    y, state = layer.apply({"params": variables["params"]}, x, mutable=["losses"])
    assert y.shape == x.shape
    # Manual per-token expert evaluation.
    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    up = p["up_kernel"][idx]  # [B, T, d_model, d_ff]
    down = p["down_kernel"][idx]
    h = jax.nn.gelu(
        jnp.einsum("btm,btmf->btf", x, up) + p["up_bias"][idx]
    )
    expected = (
        jnp.einsum("btf,btfm->btm", h, down) + p["down_bias"][idx]
    ) * jnp.max(probs, axis=-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expected), atol=1e-5)
    # Aux loss was sown, pre-scaled.
    (aux,) = state["losses"]["moe_aux"]
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_moe_capacity_drops_overflow_tokens():
    """With capacity 1 slot per expert, overflowed tokens produce zero output
    (they ride the residual path in the transformer block)."""
    layer = MoEMLP(n_experts=2, d_ff=8, d_model=4, capacity_factor=2.0 / 6.0)
    x = jnp.asarray(
        np.tile(np.random.default_rng(1).standard_normal((1, 1, 4)), (1, 6, 1)),
        jnp.float32,
    )  # identical tokens -> all route to one expert, capacity 1 keeps 1
    variables = layer.init(jax.random.PRNGKey(0), x)
    y, _ = layer.apply(variables, x, mutable=["losses"])
    norms = np.linalg.norm(np.asarray(y[0]), axis=-1)
    assert (norms > 1e-6).sum() == 1  # exactly one token served


@pytest.mark.slow
def test_moe_lm_trains_and_loss_decreases():
    model = moe_lm()
    inputs, targets = make_batch()
    state = create_train_state(model, optax.adam(1e-2), inputs)
    assert "losses" not in state.model_state  # sown terms never persist
    step = make_train_step(model.apply, optax.adam(1e-2), softmax_cross_entropy_loss)
    first = None
    batch = (jnp.asarray(inputs), jnp.asarray(targets))
    for i in range(10):
        state, loss = step(state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first


@pytest.mark.slow
def test_ep_sharded_training_matches_replicated():
    """DP x EP training is numerically equivalent to replicated DP: expert
    sharding (and its all-to-all) changes placement only."""
    inputs, targets = make_batch(n_rows=4)
    optimizer = optax.adam(1e-2)

    mesh_dp = make_mesh({"data": 2}, devices=jax.devices()[:2])
    model_dp = moe_lm(mesh=mesh_dp)
    state_dp = create_train_state(model_dp, optimizer, inputs, rng_seed=5)
    state_dp = shard_train_state(state_dp, replicated_sharding(mesh_dp))
    step_dp = make_train_step(
        model_dp.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh_dp
    )
    batch_dp = put_global_batch(mesh_dp, (inputs, targets))
    losses_dp = []
    for _ in range(3):
        state_dp, loss = step_dp(state_dp, batch_dp)
        losses_dp.append(float(loss))

    mesh = make_mesh({"data": 2, "expert": 4})
    model = moe_lm(mesh=mesh)
    state = create_train_state(model, optimizer, inputs, rng_seed=5)
    specs = make_param_specs(state.params, MOE_EP_RULES, mesh=mesh)
    # Expert kernels must actually be sharded over the expert axis.
    flat = jtu.tree_leaves_with_path(specs)
    moe_specs = [
        s for path, s in flat if "up_kernel" in str(path) or "down_kernel" in str(path)
    ]
    assert moe_specs and all(s == P("expert", None, None) for s in moe_specs)
    shardings = make_state_shardings(mesh, state, specs)
    state = shard_train_state(state, shardings)
    step = make_train_step(
        model.apply,
        optimizer,
        softmax_cross_entropy_loss,
        mesh=mesh,
        state_sharding=shardings,
    )
    batch = put_global_batch(mesh, (inputs, targets))
    losses = []
    for _ in range(3):
        state, loss = step(state, batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, losses_dp, rtol=2e-4)
