"""Mixture-of-Experts / expert-parallelism tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_tpu.models import TransformerLM
from distributed_pytorch_tpu.models.moe import MOE_EP_RULES, MoEMLP
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import (
    make_param_specs,
    make_state_shardings,
    shard_train_state,
)
from distributed_pytorch_tpu.parallel.sharding import (
    put_global_batch,
    replicated_sharding,
)
from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
from distributed_pytorch_tpu.training.train_step import (
    create_train_state,
    make_train_step,
)


def moe_lm(mesh=None, n_experts=4):
    return TransformerLM(
        vocab_size=64, d_model=16, n_layers=2, n_heads=4, d_ff=32,
        n_experts=n_experts, moe_every=2, mesh=mesh,
    )


def make_batch(n_rows=4):
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 64, (n_rows, 17), dtype=np.int32)
    return tokens[:, :-1], tokens[:, 1:]


@pytest.mark.slow
def test_moe_mlp_routes_all_tokens_with_ample_capacity():
    """With capacity_factor >= n_experts every token gets a slot, so the MoE
    layer output equals running each token through its argmax expert."""
    layer = MoEMLP(n_experts=2, d_ff=8, d_model=4, capacity_factor=2.0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 6, 4)), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)
    # Pass params only: sow APPENDS to a passed-in "losses" collection, so the
    # train step strips it before apply (see create_train_state) — mirror that.
    y, state = layer.apply({"params": variables["params"]}, x, mutable=["losses"])
    assert y.shape == x.shape
    # Manual per-token expert evaluation.
    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    up = p["up_kernel"][idx]  # [B, T, d_model, d_ff]
    down = p["down_kernel"][idx]
    h = jax.nn.gelu(
        jnp.einsum("btm,btmf->btf", x, up) + p["up_bias"][idx]
    )
    expected = (
        jnp.einsum("btf,btfm->btm", h, down) + p["down_bias"][idx]
    ) * jnp.max(probs, axis=-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expected), atol=1e-5)
    # Aux loss was sown, pre-scaled.
    (aux,) = state["losses"]["moe_aux"]
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_moe_capacity_drops_overflow_tokens():
    """With capacity 1 slot per expert, overflowed tokens produce zero output
    (they ride the residual path in the transformer block)."""
    layer = MoEMLP(n_experts=2, d_ff=8, d_model=4, capacity_factor=2.0 / 6.0)
    x = jnp.asarray(
        np.tile(np.random.default_rng(1).standard_normal((1, 1, 4)), (1, 6, 1)),
        jnp.float32,
    )  # identical tokens -> all route to one expert, capacity 1 keeps 1
    variables = layer.init(jax.random.PRNGKey(0), x)
    y, _ = layer.apply(variables, x, mutable=["losses"])
    norms = np.linalg.norm(np.asarray(y[0]), axis=-1)
    assert (norms > 1e-6).sum() == 1  # exactly one token served


@pytest.mark.slow
def test_moe_lm_trains_and_loss_decreases():
    model = moe_lm()
    inputs, targets = make_batch()
    state = create_train_state(model, optax.adam(1e-2), inputs)
    assert "losses" not in state.model_state  # sown terms never persist
    step = make_train_step(model.apply, optax.adam(1e-2), softmax_cross_entropy_loss)
    first = None
    batch = (jnp.asarray(inputs), jnp.asarray(targets))
    for i in range(10):
        state, loss = step(state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first


@pytest.mark.slow
def test_ep_sharded_training_matches_replicated():
    """DP x EP training is numerically equivalent to replicated DP: expert
    sharding (and its all-to-all) changes placement only."""
    inputs, targets = make_batch(n_rows=4)
    optimizer = optax.adam(1e-2)

    mesh_dp = make_mesh({"data": 2}, devices=jax.devices()[:2])
    model_dp = moe_lm(mesh=mesh_dp)
    state_dp = create_train_state(model_dp, optimizer, inputs, rng_seed=5)
    state_dp = shard_train_state(state_dp, replicated_sharding(mesh_dp))
    step_dp = make_train_step(
        model_dp.apply, optimizer, softmax_cross_entropy_loss, mesh=mesh_dp
    )
    batch_dp = put_global_batch(mesh_dp, (inputs, targets))
    losses_dp = []
    for _ in range(3):
        state_dp, loss = step_dp(state_dp, batch_dp)
        losses_dp.append(float(loss))

    mesh = make_mesh({"data": 2, "expert": 4})
    model = moe_lm(mesh=mesh)
    state = create_train_state(model, optimizer, inputs, rng_seed=5)
    specs = make_param_specs(state.params, MOE_EP_RULES, mesh=mesh)
    # Expert kernels must actually be sharded over the expert axis.
    flat = jtu.tree_leaves_with_path(specs)
    moe_specs = [
        s for path, s in flat if "up_kernel" in str(path) or "down_kernel" in str(path)
    ]
    assert moe_specs and all(s == P("expert", None, None) for s in moe_specs)
    shardings = make_state_shardings(mesh, state, specs)
    state = shard_train_state(state, shardings)
    step = make_train_step(
        model.apply,
        optimizer,
        softmax_cross_entropy_loss,
        mesh=mesh,
        state_sharding=shardings,
    )
    batch = put_global_batch(mesh, (inputs, targets))
    losses = []
    for _ in range(3):
        state, loss = step(state, batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, losses_dp, rtol=2e-4)


class TestTopTwoRouting:
    """router_top_k=2 (GShard-style): two gated experts per token with
    renormalized gates, shared capacity (primaries first)."""

    def test_ample_capacity_matches_manual_two_expert_sum(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 6, 4)), jnp.float32)
        layer = MoEMLP(
            n_experts=3, d_ff=8, d_model=4, router_top_k=2,
            capacity_factor=4.0,
        )
        variables = layer.init(jax.random.PRNGKey(0), x)
        out, _ = layer.apply(variables, x, mutable=["losses"])
        p = variables["params"]
        logits = x @ p["router"]["kernel"] + p["router"]["bias"]
        probs = jax.nn.softmax(logits, -1)
        i1 = jnp.argmax(probs, -1)
        i2 = jnp.argmax(probs * (1 - jax.nn.one_hot(i1, 3)), -1)
        g1 = jnp.take_along_axis(probs, i1[..., None], -1)[..., 0]
        g2 = jnp.take_along_axis(probs, i2[..., None], -1)[..., 0]
        denom = g1 + g2 + 1e-9

        def expert(e, xi):
            h = jax.nn.gelu(xi @ p["up_kernel"][e] + p["up_bias"][e])
            return h @ p["down_kernel"][e] + p["down_bias"][e]

        for b in range(2):
            for t in range(6):
                ref = (g1[b, t] / denom[b, t]) * expert(
                    int(i1[b, t]), x[b, t]
                ) + (g2[b, t] / denom[b, t]) * expert(int(i2[b, t]), x[b, t])
                np.testing.assert_allclose(
                    np.asarray(out[b, t]), np.asarray(ref),
                    rtol=1e-5, atol=1e-5,
                )

    def test_secondary_queues_behind_primary_for_capacity(self):
        """The GShard priority invariant, pinned directly: with capacity 1
        per expert, a token whose PRIMARY is expert e keeps e's slot even
        when an earlier token wanted e as its secondary — and dropped
        assignments contribute exactly zero."""
        import flax

        # Router crafted so tokens (1,0) -> primary e0, (-1,0) -> primary
        # e1, with the other expert always the secondary.
        x = jnp.asarray(
            [[[1.0, 0.0], [1.0, 0.0], [-1.0, 0.0]]], jnp.float32
        )  # t0, t1 prefer e0; t2 prefers e1
        layer = MoEMLP(
            n_experts=2, d_ff=8, d_model=2, router_top_k=2,
            capacity_factor=1.0 / 3.0,  # capacity = ceil(2*3/(3*2)) = 1
        )
        variables = layer.init(jax.random.PRNGKey(0), x)
        p = flax.core.unfreeze(variables)["params"]
        p["router"]["kernel"] = jnp.asarray(
            [[4.0, -4.0], [0.0, 0.0]], jnp.float32
        )
        p["router"]["bias"] = jnp.zeros((2,), jnp.float32)
        out, _ = layer.apply({"params": p}, x, mutable=["losses"])

        logits = x[0] @ p["router"]["kernel"]
        probs = jax.nn.softmax(logits, -1)

        def expert(e, xi):
            h = jax.nn.gelu(xi @ p["up_kernel"][e] + p["up_bias"][e])
            return h @ p["down_kernel"][e] + p["down_bias"][e]

        # Slot accounting at capacity 1: e0 keeps t0 (its first PRIMARY),
        # e1 keeps t2 (its only primary) — t0's secondary claim on e1 came
        # earlier in token order but must NOT displace t2's primary.
        g = probs / (probs[:, 0] + probs[:, 1] + 1e-9)[:, None]
        np.testing.assert_allclose(  # t0: primary kept, secondary dropped
            np.asarray(out[0, 0]),
            np.asarray(g[0, 0] * expert(0, x[0, 0])),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(  # t1: both choices over capacity -> 0
            np.asarray(out[0, 1]), np.zeros(2), atol=1e-6
        )
        np.testing.assert_allclose(  # t2: primary e1 survives
            np.asarray(out[0, 2]),
            np.asarray(g[2, 1] * expert(1, x[0, 2])),
            rtol=1e-5, atol=1e-5,
        )

    def test_top2_lm_trains_and_loss_decreases(self):
        import optax

        from distributed_pytorch_tpu.training.losses import (
            softmax_cross_entropy_loss,
        )
        from distributed_pytorch_tpu.training.train_step import (
            create_train_state,
            make_train_step,
        )

        model = TransformerLM(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
            n_experts=4, moe_every=2, moe_top_k=2,
        )
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (8, 17)), jnp.int32)
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        opt = optax.adam(3e-3)
        state = create_train_state(model, opt, inputs)
        step = make_train_step(model.apply, opt, softmax_cross_entropy_loss)
        first = last = None
        for _ in range(25):
            state, loss = step(state, (inputs, targets))
            if first is None:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.9, (first, last)

    def test_rejects_bad_k(self):
        x = jnp.zeros((1, 4, 4), jnp.float32)
        with pytest.raises(ValueError, match="router_top_k"):
            MoEMLP(n_experts=2, d_ff=8, d_model=4, router_top_k=3).init(
                jax.random.PRNGKey(0), x
            )
        # k=2 with a single expert has no second choice -> explicit error,
        # not a silent half-weight duplicate.
        with pytest.raises(ValueError, match="at least"):
            MoEMLP(n_experts=1, d_ff=8, d_model=4, router_top_k=2).init(
                jax.random.PRNGKey(0), x
            )

    def test_top2_ep_sharded_matches_replicated(self):
        """Expert-parallel top-2: sharded experts over the mesh produce the
        same outputs as the replicated run (the all-to-all seam is
        placement, not math)."""
        from distributed_pytorch_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": 2, "expert": 4})
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 8, 4)), jnp.float32)
        plain = MoEMLP(
            n_experts=4, d_ff=8, d_model=4, router_top_k=2,
            capacity_factor=2.0,
        )
        sharded = MoEMLP(
            n_experts=4, d_ff=8, d_model=4, router_top_k=2,
            capacity_factor=2.0, mesh=mesh,
        )
        variables = plain.init(jax.random.PRNGKey(0), x)
        ref, _ = plain.apply(variables, x, mutable=["losses"])
        out, _ = sharded.apply(variables, x, mutable=["losses"])
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )
