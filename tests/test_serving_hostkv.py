"""Hierarchical KV: host-RAM page tier behind the device prefix trie.

Covers the tier in isolation (numpy pools standing in for device arrays)
and wired into the engine: spill on eviction, fetch on a host-trie hit,
bitwise token parity tier-on vs tier-off, the double-entry byte
cross-check against the XLA transfer ledger, restore-via-fetch shrinking
``restore_reprefill`` goodput waste, and snapshot ``host_keys`` wire
round-trips.
"""

import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    HostPageTier,
    InferenceEngine,
    RequestSnapshot,
    SamplingParams,
    restore_engine,
    snapshot_engine,
)
from distributed_pytorch_tpu.serving.kv_cache import chain_next


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32, **kw,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


# ------------------------------------------------------------- tier (unit)


class TestHostPageTierUnit:
    """The tier alone, with numpy 'device' pools: every state transition,
    the O(1) gauges vs the O(n) sweep, and the teardown gate."""

    PAGE = 2

    def _tier(self, capacity=3):
        # Fake device pool: page p holds the constant p, so drained host
        # content is trivially checkable.
        device = np.arange(8, dtype=np.float32)[:, None, None, None]
        device = np.broadcast_to(device, (8, self.PAGE, 2, 4)).copy()
        tier = HostPageTier(
            {"target": device},
            num_host_pages=capacity,
            page_size=self.PAGE,
            gather_fn=lambda page: {"target": device[page]},
        )
        return tier, device

    def test_spill_drain_fetch_roundtrip(self):
        tier, device = self._tier()
        key = chain_next("root", (5, 7))
        assert tier.note_evict(3, key, (5, 7))
        # PENDING: matchable, counted resident, not yet drained.
        assert tier.match(key, (5, 7))
        assert not tier.match(key, (5, 8)), "token window must verify"
        assert tier.pages_resident == 1 and tier.pending_spills == 1
        tier.check_invariants()
        moved = tier.drain_spills()
        assert moved == device[3].nbytes
        assert tier.spill_bytes_total == moved
        assert tier.pending_spills == 0
        chunk = tier.chunks(key)["target"]
        np.testing.assert_array_equal(chunk, device[3])
        assert tier.fetches == 1
        assert tier.fetch_bytes_total == device[3].nbytes
        tier.assert_quiescent()

    def test_duplicate_key_refreshes_lru_only(self):
        tier, _ = self._tier()
        key = chain_next("root", (1, 2))
        assert tier.note_evict(1, key, (1, 2))
        tier.drain_spills()
        # Content-addressed: a re-spill of the same chain key is a no-op
        # write-back, not a second slot.
        assert not tier.note_evict(2, key, (1, 2))
        assert tier.spills == 1 and tier.pages_resident == 1
        tier.check_invariants()
        tier.assert_quiescent()

    def test_host_lru_evicts_oldest_unpinned(self):
        tier, _ = self._tier(capacity=2)
        ka = chain_next("root", (1, 2))
        kb = chain_next("root", (3, 4))
        kc = chain_next("root", (5, 6))
        tier.note_evict(1, ka, (1, 2))
        tier.note_evict(2, kb, (3, 4))
        tier.drain_spills()
        tier.pin(ka)  # a planned fetch protects the oldest entry
        assert tier.note_evict(3, kc, (5, 6))
        tier.drain_spills()
        # kb (oldest UNPINNED) went, ka survived its pin.
        assert tier.match(ka, (1, 2)) and not tier.match(kb, (3, 4))
        assert tier.host_evictions == 1
        tier.check_invariants()
        tier.unpin(ka)
        tier.assert_quiescent()

    def test_spill_dropped_when_all_pinned(self):
        tier, _ = self._tier(capacity=1)
        ka = chain_next("root", (1, 2))
        tier.note_evict(1, ka, (1, 2))
        tier.drain_spills()
        tier.pin(ka)
        kb = chain_next("root", (3, 4))
        assert not tier.note_evict(2, kb, (3, 4))
        assert tier.spill_drops == 1
        assert tier.match(ka, (1, 2))
        tier.unpin(ka)
        tier.check_invariants()

    def test_quiescence_rejects_pins_and_undrained_spills(self):
        tier, _ = self._tier()
        key = chain_next("root", (9, 9))
        tier.note_evict(4, key, (9, 9))
        with pytest.raises(AssertionError):
            tier.assert_quiescent()  # undrained spill
        tier.drain_spills()
        tier.pin(key)
        with pytest.raises(AssertionError):
            tier.assert_quiescent()  # pinned entry
        tier.unpin(key)
        tier.assert_quiescent()


# --------------------------------------------------------- engine (parity)


def _engine(model, params, host_pages, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 9)
    kw.setdefault("token_budget", 16)
    kw.setdefault("max_prefill_chunk", 8)
    kw.setdefault("debug", True)
    return InferenceEngine(model, params, host_pages=host_pages, **kw)


# Disjoint 8-token prompts (two full pages each at page_size=4) so every
# prompt's pages evict the previous prompt's out of the 8-usable-page pool.
PROMPTS = [[i * 8 + j + 1 for j in range(8)] for i in range(5)]


def _run_working_set(eng):
    """Two passes over PROMPTS: pass 1 populates + spills, pass 2 should
    re-serve the spilled prefixes from the host tier."""
    outs = []
    for _ in range(2):
        for p in PROMPTS:
            rid = eng.submit(p, SamplingParams(max_new_tokens=4))
            eng.run()
            outs.append(eng.poll(rid).generated)
    return outs


class TestHostTierEngineParity:
    def test_token_parity_and_ledger_cross_check(self, model_and_params):
        """Working set 5x the device pool: tier-on serves prefixes from
        host RAM with BITWISE-identical tokens, and the tier's own byte
        counters match the XLA transfer ledger's tagged d2h/h2d rows
        exactly (double-entry bookkeeping)."""
        model, params = model_and_params
        off = _engine(model, params, host_pages=None)
        outs_off = _run_working_set(off)
        s_off = off.stats()
        off.close()

        on = _engine(model, params, host_pages=32, xla_ledger=True)
        outs_on = _run_working_set(on)
        s_on = on.stats()
        on.close()  # drains trailing spills, asserts both tiers quiescent

        assert outs_on == outs_off, "host tier changed generated tokens"
        assert s_on["prefix_tokens_hit_host"] > 0, "no host-tier hits"
        assert s_on["hostkv_spills"] > 0 and s_on["hostkv_fetches"] > 0
        # Tier-off never touches the host counters' namespace.
        assert "hostkv_spills" not in s_off
        assert s_off["prefix_tokens_hit_host"] == 0
        # Hit-rate split: device rate unchanged in meaning, total adds host.
        assert s_on["prefix_hit_rate_total"] > s_on["prefix_hit_rate"]
        # Double-entry byte cross-check, exact: the engine charged the
        # ledger the same sums the tier counted.
        md = on.xla.metadata()
        assert (
            md["bytes_d2h_by_tag"].get("hostkv_spill", 0)
            == on.hostkv.spill_bytes_total
        )
        assert (
            md["bytes_h2d_by_tag"].get("hostkv_fetch", 0)
            == on.hostkv.fetch_bytes_total
        )
        assert s_on["hostkv_spill_bytes"] > 0
        assert s_on["hostkv_fetch_bytes"] > 0
        # Zero leaked pages on either tier.
        assert s_on["pages_allocated"] == 0
        on.allocator.check_invariants()
        on.hostkv.check_invariants()

    def test_fetch_lands_before_dependent_decode(self, model_and_params):
        """A request admitted entirely through host pages (full-page
        prefix, one-token tail) decodes from fetched K/V in the same step
        the fetch executes — parity proves the h2d landed before the
        attention read."""
        model, params = model_and_params
        eng = _engine(model, params, host_pages=16)
        p = PROMPTS[0]
        ref_rid = eng.submit(p, SamplingParams(max_new_tokens=6))
        eng.run()
        ref = eng.poll(ref_rid).generated
        for q in PROMPTS[1:]:  # force p's pages host-side
            eng.submit(q, SamplingParams(max_new_tokens=2))
            eng.run()
        rid = eng.submit(p, SamplingParams(max_new_tokens=6))
        eng.run()
        assert eng.poll(rid).generated == ref
        assert eng.stats()["prefix_tokens_hit_host"] >= 4
        eng.close()


class TestQuantizedHostTier:
    """ISSUE 19: int8 KV pages ride spill/fetch unmodified — the tier is
    tree_map-generic, so the int8 pools and their 3-d float32 scale pools
    round-trip host RAM together, at the quantized byte size."""

    def test_int8_spill_fetch_round_trip(self, model_and_params):
        model, params = model_and_params
        off = _engine(model, params, host_pages=None, kv_quant="int8")
        outs_off = _run_working_set(off)
        off.close()

        on = _engine(
            model, params, host_pages=32, kv_quant="int8",
            paged_kernel=True, xla_ledger=True,
        )
        outs_on = _run_working_set(on)
        s_on = on.stats()
        on.close()

        # The tier must not change a token (the fetched int8 payload +
        # scales are the same content a re-prefill would re-quantize to).
        assert outs_on == outs_off, "host tier changed int8 tokens"
        assert s_on["prefix_tokens_hit_host"] > 0
        assert s_on["hostkv_spills"] > 0 and s_on["hostkv_fetches"] > 0
        # Double-entry bookkeeping stays exact at the quantized sizes.
        md = on.xla.metadata()
        assert (
            md["bytes_d2h_by_tag"].get("hostkv_spill", 0)
            == on.hostkv.spill_bytes_total
        )
        assert (
            md["bytes_h2d_by_tag"].get("hostkv_fetch", 0)
            == on.hostkv.fetch_bytes_total
        )
        assert s_on["pages_allocated"] == 0
        on.allocator.check_invariants()
        on.hostkv.check_invariants()

    def test_int8_page_bytes_are_quantized(self, model_and_params):
        """Per-page spill bytes = int8 payload + f32 scales, to the byte:
        layers x {K,V} x (page*Hkv*D x 1B + page*Hkv x 4B)."""
        model, params = model_and_params
        fp = _engine(model, params, host_pages=16)
        q8 = _engine(model, params, host_pages=16, kv_quant="int8")
        for eng in (fp, q8):
            _run_working_set(eng)
        n_layers = model.n_layers
        kv_heads = model.n_kv_heads or model.n_heads
        d = model.d_model // model.n_heads
        page = fp.page_size
        fp_page = n_layers * 2 * page * kv_heads * d * 4
        q8_page = n_layers * 2 * (page * kv_heads * d + page * kv_heads * 4)
        assert fp.hostkv.spill_bytes_total == fp.hostkv.counters()[
            "hostkv_spills"
        ] * fp_page
        assert q8.hostkv.spill_bytes_total == q8.hostkv.counters()[
            "hostkv_spills"
        ] * q8_page
        assert q8_page < fp_page / 2
        fp.close()
        q8.close()


# ------------------------------------------------- restore via host fetch


class TestRestoreViaHostFetch:
    def _warm_adopter(self, model, params, host_pages, prompt):
        """An adopter that ran ``prompt`` once and then had its pages
        evicted by disjoint work — host tier (when on) now holds the
        chain, device trie does not."""
        eng = _engine(
            model, params, host_pages=host_pages, goodput=True
        )
        eng.submit(prompt, SamplingParams(max_new_tokens=6))
        eng.run()
        for q in PROMPTS[1:]:
            eng.submit(q, SamplingParams(max_new_tokens=2))
            eng.run()
        if eng.goodput is not None:
            eng.goodput.reset()  # isolate the restore's waste
        return eng

    def test_restore_reprefill_waste_shrinks_with_host_tier(
        self, model_and_params
    ):
        """Satellite: ``restore_engine`` used to re-prefill recovered
        requests from token zero. With the snapshot's ``key_chain`` pages
        host-resident in the adopter, recovery goes through h2d fetch and
        the ``restore_reprefill`` goodput charge shrinks."""
        model, params = model_and_params
        prompt = PROMPTS[0]
        from tests.test_serving import offline_greedy

        ref = offline_greedy(model, params, prompt, 6)

        def victim_snapshot():
            victim = _engine(model, params, host_pages=None)
            rid = victim.submit(prompt, SamplingParams(max_new_tokens=6))
            while len(victim.poll(rid).generated) < 2:
                victim.step()
            snap = snapshot_engine(victim)
            victim.close()
            return snap

        results = {}
        for label, host_pages in (("host", 32), ("cold", None)):
            adopter = self._warm_adopter(
                model, params, host_pages, prompt
            )
            [rid] = restore_engine(
                adopter, victim_snapshot(), rebase_ids=True
            )
            hit_host0 = adopter.stats()["prefix_tokens_hit_host"]
            adopter.run()
            assert adopter.poll(rid).generated == ref, (
                "restored stream diverged from offline decode"
            )
            results[label] = {
                "waste": adopter.goodput.wasted["restore_reprefill"],
                "host_hits": (
                    adopter.stats()["prefix_tokens_hit_host"] - hit_host0
                ),
            }
            adopter.close()

        assert results["host"]["host_hits"] >= 8, (
            "restore did not recover the prompt through the host tier"
        )
        assert results["cold"]["waste"] > 0, (
            "control restore should charge restore_reprefill"
        )
        assert results["host"]["waste"] < results["cold"]["waste"], (
            f"host-tier restore wasted {results['host']['waste']:.6f}s, "
            f"cold restore {results['cold']['waste']:.6f}s — fetch "
            "recovery should shrink the reprefill charge"
        )


# --------------------------------------------------- snapshot host_keys


class TestSnapshotHostKeys:
    def test_host_keys_survive_wire_roundtrip(self, model_and_params):
        """``snapshot_engine`` records the host-resident continuation of
        each request's chain; the JSON codec round-trips it and old
        payloads without the field decode to ()."""
        model, params = model_and_params
        eng = _engine(model, params, host_pages=16)
        p = PROMPTS[0]
        eng.submit(p, SamplingParams(max_new_tokens=2))
        eng.run()
        for q in PROMPTS[1:3]:  # push p's pages to the host tier
            eng.submit(q, SamplingParams(max_new_tokens=2))
            eng.run()
        rid = eng.submit(p, SamplingParams(max_new_tokens=6))
        # Step once so the request is live with its fetched pages.
        eng.step()
        snap = snapshot_engine(eng)
        rec = next(r for r in snap.requests if r.req_id == rid)
        # The fetched pages re-entered the DEVICE trie; whatever stayed
        # host-only shows up in host_keys. Between the two tiers the full
        # two-page prompt chain must be accounted for.
        chain = []
        prev = "root"
        for i in range(0, 8, 4):
            prev = chain_next(prev, tuple(p[i : i + 4]))
            chain.append(prev)
        assert set(rec.trie_keys) | set(rec.host_keys) >= set(chain)
        # Wire round-trip.
        doc = json.loads(snap.to_json())
        back = type(snap).from_json(json.dumps(doc))
        rec2 = next(r for r in back.requests if r.req_id == rid)
        assert rec2.host_keys == rec.host_keys
        # Backward wire-compat: a pre-host-tier payload decodes to ().
        for entry in doc["requests"]:
            entry.pop("host_keys", None)
        old = type(snap).from_json(json.dumps(doc))
        assert all(r.host_keys == () for r in old.requests)
        eng.run()
        eng.close()
