"""Mesh-sharded serving tests: the exactness contract of the tentpole.

The conftest forces 8 virtual CPU devices, so every geometry the ISSUE
names runs here: (1,1) must be BITWISE identical to the unsharded engine
(the sharded factories must add no annotation the unsharded path lacks),
and (1,8)/(2,4) must be greedy-token identical across the full toggle
matrix (prefix_cache x overlap x speculative) — sharded reductions may
reorder float accumulation, argmax must not care at these scales. Plus
the satellites that ride the mesh: snapshot geometry fingerprinting,
head-divisibility refusal, mesh gauges/labels, and pool-named allocator
leak messages.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import Tracer
from distributed_pytorch_tpu.serving import (
    EngineSnapshot,
    InferenceEngine,
    PagedBlockAllocator,
    SamplingParams,
    drain_engine,
    make_serving_mesh,
    mesh_fingerprint,
    restore_engine,
)
from distributed_pytorch_tpu.serving.mesh import validate_kv_heads

# Every sharded dim divisible by 8: n_heads 8 (head_dim 4), d_model 32,
# d_ff 64, vocab 64 — so the same model serves every geometry up to 1x8.
MESH_LM = dict(
    vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64,
    dtype=jnp.float32,
)

PROMPTS = [[1, 2, 3, 4], [5, 6, 7], [1, 2, 3, 9, 10]]
MAX_NEW = 5

ENGINE_KW = dict(
    max_slots=4, max_seq_len=32, page_size=8, token_budget=32,
    max_prefill_chunk=16,
)


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(**MESH_LM)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_and_params():
    draft = TransformerLM(
        vocab_size=64, d_model=16, n_layers=1, n_heads=8, d_ff=32,
        dtype=jnp.float32,
    )
    dparams = draft.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return draft, dparams


def run_engine(model, params, *, mesh=None, prefix=True, overlap=True,
               spec=None, temperature=0.0, seed=0, tracer=None):
    kw = dict(ENGINE_KW)
    if spec is not None:
        draft, dparams = spec
        kw.update(draft_model=draft, draft_params=dparams, gamma=3)
    eng = InferenceEngine(
        model, params, mesh=mesh, prefix_cache=prefix, overlap=overlap,
        tracer=tracer, **kw,
    )
    ids = [
        eng.submit(
            p,
            SamplingParams(
                max_new_tokens=MAX_NEW, temperature=temperature, seed=seed
            ),
        )
        for p in PROMPTS
    ]
    eng.run()
    out = [eng.poll(i).generated for i in ids]
    eng.close()
    return out, eng


@pytest.fixture(scope="module")
def baseline_greedy(model_and_params):
    """Unsharded greedy output — the single truth every geometry and every
    toggle combination must reproduce (toggle-invariance of the unsharded
    engine itself is pinned by test_serving.py)."""
    out, _ = run_engine(*model_and_params)
    return out


# ------------------------------------------------------------ (1,1) bitwise


class TestMeshOneByOne:
    def test_greedy_bitwise(self, model_and_params, baseline_greedy):
        out, eng = run_engine(*model_and_params, mesh=make_serving_mesh(1, 1))
        assert out == baseline_greedy
        assert eng.mesh_fingerprint == "1x1"

    def test_sampled_bitwise(self, model_and_params):
        """temperature > 0 draws through the same categorical — a (1,1)
        mesh must reproduce the exact sampled stream, not just argmax."""
        base, _ = run_engine(*model_and_params, temperature=0.9, seed=7)
        out, _ = run_engine(
            *model_and_params, mesh=make_serving_mesh(1, 1),
            temperature=0.9, seed=7,
        )
        assert out == base


# ------------------------------------------------- toggle matrix, 1x8 / 2x4


@pytest.mark.parametrize("shape", [(1, 8), (2, 4)], ids=["1x8", "2x4"])
@pytest.mark.parametrize("prefix", [False, True], ids=["nocache", "cache"])
@pytest.mark.parametrize("overlap", [False, True], ids=["sync", "overlap"])
class TestMeshToggleMatrix:
    def test_greedy_parity_plain(
        self, model_and_params, baseline_greedy, shape, prefix, overlap
    ):
        out, eng = run_engine(
            *model_and_params, mesh=make_serving_mesh(*shape),
            prefix=prefix, overlap=overlap,
        )
        assert out == baseline_greedy
        assert eng._sharded_programs >= 3  # decode + prefill + copy_page

    def test_greedy_parity_speculative(
        self, model_and_params, draft_and_params, baseline_greedy, shape,
        prefix, overlap,
    ):
        out, eng = run_engine(
            *model_and_params, mesh=make_serving_mesh(*shape),
            prefix=prefix, overlap=overlap, spec=draft_and_params,
        )
        assert out == baseline_greedy
        assert eng.speculative


# -------------------------------------------------------- elastic round-trip


class TestShardedElastic:
    def _mid_run_snapshot(self, model, params, mesh):
        eng = InferenceEngine(model, params, mesh=mesh, **ENGINE_KW)
        ids = [
            eng.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
            for p in PROMPTS
        ]
        for _ in range(3):
            eng.step()
        snap = drain_engine(eng)
        eng.close()
        return snap, ids

    def test_drain_restore_roundtrip(
        self, model_and_params, baseline_greedy
    ):
        model, params = model_and_params
        snap, ids = self._mid_run_snapshot(
            model, params, make_serving_mesh(2, 4)
        )
        assert snap.mesh == "2x4"
        # Codec round-trip preserves the fingerprint.
        snap = EngineSnapshot.from_json(snap.to_json())
        assert snap.mesh == "2x4"
        eng2 = InferenceEngine(
            model, params, mesh=make_serving_mesh(2, 4), **ENGINE_KW
        )
        restored = restore_engine(eng2, snap)
        assert set(restored) == {r.req_id for r in snap.requests}
        eng2.run()
        out = [eng2.poll(i).generated for i in ids]
        eng2.close()
        assert out == baseline_greedy

    def test_restore_refuses_geometry_mismatch(self, model_and_params):
        model, params = model_and_params
        snap, _ = self._mid_run_snapshot(
            model, params, make_serving_mesh(2, 4)
        )
        eng_unsharded = InferenceEngine(model, params, **ENGINE_KW)
        with pytest.raises(ValueError, match="2x4 mesh.*1x1"):
            restore_engine(eng_unsharded, snap)
        eng_unsharded.close()

    def test_snapshot_backcompat_missing_mesh_field(self):
        """Version-1 snapshots written before mesh sharding existed carry
        no ``mesh`` key; they must decode as unsharded, not crash."""
        snap = EngineSnapshot(
            version=1, page_size=8, max_seq_len=32, top_k=0, top_p=0.0,
            speculative=False, next_id=0, requests=(),
        )
        doc = json.loads(snap.to_json())
        del doc["mesh"]
        old = EngineSnapshot.from_json(json.dumps(doc))
        assert old.mesh == "1x1"


# ------------------------------------------------------------- validation


class TestMeshValidation:
    def test_head_divisibility_refused(self, model_and_params):
        bad = TransformerLM(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            dtype=jnp.float32,
        )
        bp = bad.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        with pytest.raises(ValueError, match="Hkv.*model"):
            InferenceEngine(
                bad, bp, mesh=make_serving_mesh(1, 8), **ENGINE_KW
            )

    def test_validate_kv_heads_direct(self):
        mesh = make_serving_mesh(1, 8)
        good = TransformerLM(**MESH_LM)
        validate_kv_heads(good, mesh)  # no raise
        validate_kv_heads(good, None)  # unsharded: never raises

    def test_mesh_needs_enough_devices(self):
        with pytest.raises(ValueError, match="devices"):
            make_serving_mesh(4, 4)

    def test_fingerprint(self):
        assert mesh_fingerprint(None) == "1x1"
        assert mesh_fingerprint(make_serving_mesh(2, 4)) == "2x4"


# ------------------------------------------------------------ observability


class TestMeshObservability:
    def test_axis_gauges_sharded(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(
            model, params, mesh=make_serving_mesh(2, 4), **ENGINE_KW
        )
        i = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=3))
        eng.run()
        g = eng.registry.snapshot()["gauges"]
        assert g["serving_data_axis_size"] == 2
        assert g["serving_model_axis_size"] == 4
        assert g["serving_mesh_2x4_info"] == 1.0
        # decode + at least one prefill bucket (programs are lazily
        # compiled — copy_page only exists once a CoW copy happens).
        assert g["serving_sharded_program_count"] >= 2
        assert eng.poll(i).finished
        eng.close()

    def test_axis_gauges_unsharded(self, model_and_params):
        eng = InferenceEngine(*model_and_params, **ENGINE_KW)
        g = eng.registry.snapshot()["gauges"]
        assert g["serving_data_axis_size"] == 1
        assert g["serving_model_axis_size"] == 1
        assert g["serving_sharded_program_count"] == 0
        assert g["serving_mesh_1x1_info"] == 1.0
        eng.close()

    def test_tracer_process_name_carries_mesh(self, model_and_params):
        model, params = model_and_params
        tracer = Tracer()
        out, _ = run_engine(
            model, params, mesh=make_serving_mesh(2, 4), tracer=tracer
        )
        meta = tracer.to_perfetto()["traceEvents"][0]
        assert meta["name"] == "process_name"
        assert meta["args"]["name"] == "engine [mesh 2x4]"

    def test_tracer_process_name_unsharded_unchanged(self, model_and_params):
        tracer = Tracer()
        run_engine(*model_and_params, tracer=tracer)
        meta = tracer.to_perfetto()["traceEvents"][0]
        assert meta["args"]["name"] == "engine"


# -------------------------------------------------- allocator pool naming


class TestAllocatorPoolNames:
    def test_quiescent_message_names_pools(self):
        alloc = PagedBlockAllocator(4)
        alloc.pool_names = ("target", "draft")
        alloc.allocate(2)
        with pytest.raises(AssertionError, match="target/draft"):
            alloc.assert_quiescent()

    def test_default_single_pool_name(self):
        alloc = PagedBlockAllocator(4)
        alloc.allocate(1)
        with pytest.raises(AssertionError, match=r"pool\(s\) target"):
            alloc.assert_quiescent()

    def test_engine_wires_pool_names(
        self, model_and_params, draft_and_params
    ):
        eng = InferenceEngine(*model_and_params, **ENGINE_KW)
        assert eng.allocator.pool_names == ("target",)
        eng.close()
        draft, dparams = draft_and_params
        eng = InferenceEngine(
            *model_and_params, draft_model=draft, draft_params=dparams,
            gamma=2, **ENGINE_KW,
        )
        assert eng.allocator.pool_names == ("target", "draft")
        eng.close()
