"""ReplicaClient conformance suite + breaker/adopt unit tests.

One parametrized suite runs the SAME assertions against both sides of
the replica interface — ``LocalReplicaClient`` (in-process wrap, the
parity-pinned default) and ``ProcessReplicaClient`` (worker subprocess
behind the localhost control plane) — so the process boundary is proven
behaviorally invisible: same tokens, same error types, same drain
snapshot, same gauges. Process variants are marked ``slow`` (each spawns
a JAX subprocess); local variants run in tier-1.

Alongside: deterministic CircuitBreaker state-machine tests (injectable
clock, no sleeps) and the bounded-poll ``adopt_snapshot`` contract
(:class:`SnapshotUnavailable` on deadline, late publisher still adopted).
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.serving import (
    CircuitBreaker,
    InferenceEngine,
    LocalReplicaClient,
    ProcessReplicaClient,
    RequestTooLong,
    SamplingParams,
    SnapshotUnavailable,
    adopt_snapshot,
    drain_engine,
    publish_snapshot,
)
from distributed_pytorch_tpu.serving.elastic import fetch_snapshot_text

MODEL_KW = dict(
    vocab_size=48, d_model=16, n_layers=1, n_heads=2, d_ff=32,
)
ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
MAX_NEW = 6
PROMPTS = [[5, 7, 11, 2, 1, 2], [2, 2, 3, 17, 40], [6, 1, 9]]

# The worker builds this same model from the spec with the same init
# seed, so local and process replicas hold identical params — token
# parity across the process boundary is exact, not approximate.
WORKER_SPEC = {
    "name": "conformance",
    "model": dict(MODEL_KW, dtype="float32"),
    "init_seed": 0,
    "engine": ENGINE_KW,
    "trace": True,
}

KINDS = [
    pytest.param("local", id="local"),
    pytest.param("process", id="process", marks=pytest.mark.slow),
]


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(**MODEL_KW, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def ref_outputs(model_and_params):
    model, params = model_and_params
    eng = InferenceEngine(model, params, **ENGINE_KW)
    ids = [
        eng.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
        for p in PROMPTS
    ]
    eng.run()
    out = [eng.poll(rid).generated for rid in ids]
    eng.close()
    return out


@pytest.fixture(scope="module")
def shared_process_client():
    """One worker subprocess shared by the non-destructive conformance
    tests (spawn + XLA warm-up dominates; state accumulation is harmless
    because token streams are slot/batch/id-invariant)."""
    client = ProcessReplicaClient(WORKER_SPEC, name="conformance")
    yield client
    try:
        client.close()
    except Exception:
        client.abandon()


def _fresh_client(kind, model_and_params, name="fresh"):
    if kind == "local":
        model, params = model_and_params
        return LocalReplicaClient(InferenceEngine(model, params, **ENGINE_KW))
    return ProcessReplicaClient(
        dict(WORKER_SPEC, name=name), name=name
    )


@pytest.fixture(params=KINDS)
def client(request, model_and_params):
    if request.param == "local":
        c = _fresh_client("local", model_and_params)
        yield c
        c.close()
    else:
        yield request.getfixturevalue("shared_process_client")


def run_to_done(client, rids, *, max_steps=400):
    done = set()
    for _ in range(max_steps):
        done.update(client.step())
        if done >= set(rids):
            return done
    raise AssertionError(f"requests never finished: {set(rids) - done}")


# ------------------------------------------------------------- conformance


def test_submit_step_poll_token_parity(client, ref_outputs):
    """The headline invariant: a client of either kind produces the exact
    reference token streams through submit/step/poll."""
    rids = [
        client.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
        for p in PROMPTS
    ]
    run_to_done(client, rids)
    for rid, ref in zip(rids, ref_outputs):
        st = client.poll(rid)
        assert st.finished
        assert list(st.generated) == list(ref)


def test_step_reports_load_and_queue_depth(client):
    rid = client.submit(
        PROMPTS[0], SamplingParams(max_new_tokens=MAX_NEW)
    )
    client.step()
    # load() is the last step exchange's gauge (the process client
    # refreshes it from the piggybacked step response, one round stale
    # at most); after one step the request is still mid-decode.
    assert client.load() >= 1.0
    run_to_done(client, [rid])
    client.step()  # one idle step so gauges settle back
    assert client.load() == 0.0
    assert client.queue_depth() == 0.0
    assert client.read_gauge("queue_depth") == 0.0


def test_cancel_semantics(client):
    rid = client.submit(PROMPTS[1], SamplingParams(max_new_tokens=MAX_NEW))
    assert client.cancel(rid) is True
    assert client.cancel(rid) is False  # already terminal
    assert client.cancel(987_654_321) is False  # unknown id
    st = client.poll(rid)
    assert st.state == "cancelled"


def test_unknown_poll_raises_keyerror(client):
    with pytest.raises(KeyError):
        client.poll(987_654_321)


def test_admission_error_type_crosses_boundary(client):
    """A refusal must surface as the REAL admission class (process: class
    name over the wire, re-raised) and count as breaker success — an
    answer from a live worker, not a transport failure."""
    too_long = list(range(1, 40))  # prompt alone exceeds max_seq_len=32
    with pytest.raises(RequestTooLong):
        client.submit(too_long, SamplingParams(max_new_tokens=8))
    assert client.breaker.state == "closed"


def test_health_describe_and_metrics(client):
    assert client.health() == "live"
    doc = client.describe()
    assert "engine" in doc and "admission" in doc
    snap = client.metrics_snapshot()
    assert snap is not None
    assert "counters" in snap and "gauges" in snap
    assert client.slo_firing() == []
    fp = client.fingerprint()
    assert fp["page_size"] == ENGINE_KW["page_size"]
    assert fp["max_seq_len"] == ENGINE_KW["max_seq_len"]


def test_reserve_ids_namespaces_id_space(client, model_and_params):
    base = 5_000_000
    client.reserve_ids(base)
    rid = client.submit(PROMPTS[2], SamplingParams(max_new_tokens=2))
    assert rid >= base
    run_to_done(client, [rid])


@pytest.mark.parametrize("kind", KINDS)
def test_drain_restore_handoff(kind, model_and_params, ref_outputs):
    """Drain a loaded replica mid-decode, restore the snapshot into a
    fresh replica OF THE SAME KIND, finish there: every stream must match
    the uninterrupted reference (for the process kind the snapshot makes
    two trips over the control plane — /drain out, /restore in)."""
    source = _fresh_client(kind, model_and_params, name="drain-src")
    target = _fresh_client(kind, model_and_params, name="drain-dst")
    try:
        rids = [
            source.submit(p, SamplingParams(max_new_tokens=MAX_NEW))
            for p in PROMPTS
        ]
        for _ in range(3):  # partial progress only
            source.step()
        snap = source.drain(reason="conformance")
        live = [r.req_id for r in snap.requests]
        assert live, "drain mid-decode should snapshot live requests"
        restored = target.restore(snap)
        assert restored == live
        run_to_done(target, live)
        for rid, ref in zip(rids, ref_outputs):
            client = target if rid in live else source
            st = client.poll(rid)
            assert st.finished
            assert list(st.generated) == list(ref), (
                f"req {rid} diverged after {kind} drain/restore handoff"
            )
    finally:
        source.abandon()
        target.abandon()


# ------------------------------------------------- process-only contracts


@pytest.mark.slow
def test_process_submit_rid_dedup(shared_process_client):
    """The replay map behind retry-safe submit: the same client-minted
    rid admits ONCE; the replay answers with the original req_id."""
    c = shared_process_client
    body = {
        "rid": "conformance-dedup-0",
        "prompt": PROMPTS[0],
        "params": {"max_new_tokens": 2},
    }
    first = c._call("/submit", dict(body))
    second = c._call("/submit", dict(body))
    assert second["req_id"] == first["req_id"]
    assert second.get("replayed") is True
    run_to_done(c, [int(first["req_id"])])


@pytest.mark.slow
def test_process_trace_documents_survive_scrape(shared_process_client):
    docs = shared_process_client.trace_documents()
    assert docs, "worker runs with trace=True; scrape should return a doc"
    assert "traceEvents" in docs[0]


# ---------------------------------------------------------- circuit breaker


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class TestCircuitBreaker:
    def make(self, **kw):
        clock = FakeClock()
        kw.setdefault("fail_threshold", 3)
        kw.setdefault("reset_timeout_s", 1.0)
        return CircuitBreaker(clock=clock, **kw), clock

    def test_opens_after_consecutive_failures(self):
        br, clock = self.make()
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed" and br.allow()
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()
        assert br.opens_total == 1

    def test_success_resets_failure_streak(self):
        br, clock = self.make()
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == "closed", "streak must reset on success"

    def test_half_open_grants_single_probe_then_closes(self):
        br, clock = self.make()
        for _ in range(3):
            br.record_failure()
        assert br.state == "open"
        clock.advance(1.01)
        assert br.state == "half_open"
        assert br.allow(), "half-open grants one probe"
        assert not br.allow(), "second concurrent probe refused"
        br.record_success()
        assert br.state == "closed"
        assert br.allow()
        assert br.closes_total == 1

    def test_failed_probe_reopens_and_restarts_cooldown(self):
        br, clock = self.make()
        for _ in range(3):
            br.record_failure()
        clock.advance(1.01)
        assert br.allow()
        br.record_failure()  # probe failed
        assert br.state == "open"
        clock.advance(0.5)
        assert br.state == "open", "cooldown restarted by failed probe"
        clock.advance(0.6)
        assert br.state == "half_open"
        assert br.allow()
        br.record_success()
        assert br.state == "closed"

    def test_disabled_breaker_never_opens(self):
        br, clock = self.make(enabled=False)
        for _ in range(50):
            br.record_failure()
        assert br.state == "closed"
        assert br.allow()

    def test_fail_threshold_validated(self):
        with pytest.raises(ValueError):
            CircuitBreaker(fail_threshold=0)


# ------------------------------------------------------ bounded adopt poll


class _DictStore:
    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key)

    def delete(self, key):
        self.data.pop(key, None)


class TestAdoptBoundedPoll:
    def test_deadline_raises_snapshot_unavailable(self):
        store = _DictStore()
        t0 = time.monotonic()
        with pytest.raises(SnapshotUnavailable):
            fetch_snapshot_text(store, "never", timeout_s=0.2)
        assert time.monotonic() - t0 >= 0.2

    def test_late_publisher_still_fetched(self):
        store = _DictStore()

        def publish_late():
            time.sleep(0.15)
            store.set("handoff", "snapshot-text")

        t = threading.Thread(target=publish_late)
        t.start()
        try:
            text = fetch_snapshot_text(store, "handoff", timeout_s=5.0)
        finally:
            t.join()
        assert text == "snapshot-text"

    def test_adopt_without_timeout_keeps_fail_fast(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, **ENGINE_KW)
        assert adopt_snapshot(eng, _DictStore(), "missing") == []
        eng.close()

    def test_adopt_with_timeout_raises_typed_error(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngine(model, params, **ENGINE_KW)
        with pytest.raises(SnapshotUnavailable):
            adopt_snapshot(eng, _DictStore(), "missing", timeout_s=0.1)
        eng.close()

    def test_adopt_races_publisher_and_wins(self, model_and_params):
        """The race the bounded poll exists for: the adopter starts
        polling BEFORE the dying replica's snapshot lands."""
        model, params = model_and_params
        src = InferenceEngine(model, params, **ENGINE_KW)
        rid = src.submit(PROMPTS[0], SamplingParams(max_new_tokens=MAX_NEW))
        src.step()
        store = _DictStore()

        def publish_late():
            time.sleep(0.15)
            publish_snapshot(store, "handoff", drain_engine(src))

        t = threading.Thread(target=publish_late)
        t.start()
        dst = InferenceEngine(model, params, **ENGINE_KW)
        try:
            restored = adopt_snapshot(dst, store, "handoff", timeout_s=5.0)
        finally:
            t.join()
        assert restored == [rid]
        assert store.data == {}, "adopt-once must delete the key"
        dst.run()
        assert dst.poll(rid).finished
        dst.close()
        src.close()
