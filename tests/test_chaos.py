"""Chaos harness tests: deterministic fault injection (``chaos.py``), the
hardened store client (reconnect/backoff/request-id dedup), self-healing
checksummed snapshots, and the preemption drain protocol.

Everything here is CPU-only and seeded. The fast tests (unmarked beyond
``chaos``) run in tier-1; the end-to-end drills at the bottom — the seeded
kill + partition + corruption + preemption drill and the SIGTERM-mid-epoch
drain-and-resume parity drill — are also marked ``slow``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.chaos import (
    Fault,
    FaultPlan,
    FaultProxy,
    InjectedFault,
)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    """The module caches the parsed plan per process; tests that arm the env
    var need a clean slate on both sides."""
    chaos._reset()
    yield
    chaos._reset()


# ----------------------------------------------------------------- FaultPlan


class TestFaultPlan:
    def test_parse_inline_and_file_roundtrip(self, tmp_path):
        spec = {
            "seed": 7,
            "faults": [
                {"kind": "kill", "process_id": 1, "at_step": 3},
                {"kind": "corrupt_snapshot", "at_save": 2, "mode": "truncate"},
            ],
        }
        inline = FaultPlan.from_spec(json.dumps(spec))
        path = tmp_path / "plan.json"
        path.write_text(json.dumps(spec))
        from_file = FaultPlan.from_spec(str(path))
        for plan in (inline, from_file):
            assert plan.seed == 7
            assert [f.kind for f in plan.faults] == ["kill", "corrupt_snapshot"]
            assert plan.faults[0].at_step == 3
        # to_spec -> from_spec is stable (what the agent hands to workers)
        again = FaultPlan.from_spec(inline.to_spec())
        assert [vars(f) for f in again.faults] == [vars(f) for f in inline.faults]

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            Fault(kind="meteor")

    def test_from_spec_names_entry_and_field(self):
        """Bad plans fail loudly with the entry index and offending field —
        a chaos plan that silently drops a fault 'passes' every drill."""
        with pytest.raises(ValueError, match="'faults' must be a list"):
            FaultPlan.from_spec(json.dumps({"faults": {"kind": "kill"}}))
        with pytest.raises(
            ValueError, match=r"fault entry 1: expected an object, got str"
        ):
            FaultPlan.from_spec(
                json.dumps({"faults": [{"kind": "kill"}, "kill"]})
            )
        with pytest.raises(
            ValueError, match=r"fault entry 0: unknown field\(s\) 'at_stpe'"
        ) as ei:
            FaultPlan.from_spec(
                json.dumps({"faults": [{"kind": "kill", "at_stpe": 3}]})
            )
        assert "valid fields:" in str(ei.value)  # lists the accepted names
        with pytest.raises(
            ValueError, match=r"fault entry 1 \(kind='meteor'\)"
        ):
            FaultPlan.from_spec(
                json.dumps({"faults": [{"kind": "kill"}, {"kind": "meteor"}]})
            )

    def test_serving_kind_mode_and_min_queue_validation(self):
        # Serving kinds default to "hard" (real signals) and accept only
        # hard/raise — "flip" etc. are bitflip modes, not fault delivery.
        assert Fault(kind="kill_mid_verify").mode == "hard"
        with pytest.raises(ValueError, match="mode"):
            Fault(kind="drain_mid_prefill", mode="truncate")
        with pytest.raises(ValueError, match="min_queue"):
            Fault(kind="kill_mid_verify", min_queue=2)

    def test_serving_at_step_is_lower_bound(self):
        """Mid-phase hooks only occur on steps that run that phase, so
        at_step matches the FIRST occurrence at-or-after it — exact
        matching would let a fault silently never fire."""
        plan = FaultPlan(
            [Fault(kind="drain_mid_prefill", at_step=3, mode="raise")]
        )
        for _ in range(4):  # steps 1-4: no prefill phase at exactly 3
            plan.on_serving_phase("step")
        plan.on_serving_phase("mid_verify")  # wrong phase: never matches
        with pytest.raises(InjectedFault) as ei:
            plan.on_serving_phase("mid_prefill")  # first chance, step 4 > 3
        assert ei.value.kind == "drain_mid_prefill" and ei.value.step == 4
        plan.on_serving_phase("mid_prefill")  # fire-once

    def test_fleet_kind_validation(self):
        # Fleet kinds target a replica by router attach-order index and
        # are applied by the router, not by signal/raise delivery.
        f = Fault(kind="kill_replica", replica=1)
        assert f.mode == "router"
        with pytest.raises(ValueError, match="replica"):
            Fault(kind="kill_replica")
        with pytest.raises(ValueError, match="replica"):
            Fault(kind="kill", replica=0)

    def test_on_fleet_step_lower_bound_and_fire_once(self):
        plan = FaultPlan(
            [
                Fault(kind="kill_replica", replica=2, at_step=3),
                Fault(kind="slow_replica", replica=0, duration=0.5,
                      at_step=1),
            ]
        )
        due = plan.on_fleet_step()  # round 1: only the slow fault is due
        assert [(f.kind, f.replica) for f in due] == [("slow_replica", 0)]
        assert plan.on_fleet_step() == []  # round 2: nothing left due yet
        due = plan.on_fleet_step()  # round 3 >= at_step: kill fires
        assert [(f.kind, f.replica) for f in due] == [("kill_replica", 2)]
        assert plan.on_fleet_step() == []  # fire-once

    def test_on_fleet_step_unarmed_is_noop(self):
        assert chaos.on_fleet_step() == []

    def test_fleet_fault_notifies_observers(self):
        plan = FaultPlan([Fault(kind="partition_replica", replica=1)])
        seen = []
        observer = lambda kind, step, mode: seen.append((kind, step, mode))
        chaos.add_fault_observer(observer)
        try:
            plan.on_fleet_step()
        finally:
            chaos.remove_fault_observer(observer)
        assert seen == [("partition_replica", 1, "router")]

    def test_reclaim_waits_for_queue_pressure(self):
        plan = FaultPlan(
            [
                Fault(
                    kind="reclaim_under_queue_pressure",
                    min_queue=2,
                    mode="raise",
                )
            ]
        )
        plan.on_serving_phase("step", queue_depth=1)  # below threshold
        with pytest.raises(InjectedFault):
            plan.on_serving_phase("step", queue_depth=2)

    def test_kill_fires_at_exact_step_in_matching_process_only(self, tmp_path):
        script = textwrap.dedent(
            """
            import os
            from distributed_pytorch_tpu.chaos import FaultPlan
            plan = FaultPlan.from_spec(os.environ["TPURUN_FAULT_PLAN"])
            for i in range(6):
                plan.on_step()
                print("step", i + 1, flush=True)
            """
        )
        plan = json.dumps(
            {"faults": [{"kind": "kill", "process_id": 1, "at_step": 3}]}
        )

        def run(process_id):
            return subprocess.run(
                [sys.executable, "-c", script],
                env={
                    **os.environ,
                    "PYTHONPATH": REPO,
                    "TPURUN_FAULT_PLAN": plan,
                    "PROCESS_ID": process_id,
                },
                capture_output=True,
                text=True,
                timeout=60,
            )

        hit = run("1")
        assert hit.returncode == -9  # SIGKILL: uncatchable, like kill -9
        assert "[chaos] SIGKILL self at step 3" in hit.stdout
        # The loop never reached its own step-3 print (fault fires first).
        assert "\nstep 3" not in hit.stdout
        miss = run("0")  # same plan, wrong process: no fault
        assert miss.returncode == 0 and "step 6" in miss.stdout

    def test_restart_generation_matching(self, monkeypatch):
        monkeypatch.setenv("TPURUN_RESTART_COUNT", "1")
        fired = []
        plan = FaultPlan([Fault(kind="hang", at_step=1, restart=0, duration=0.2)])
        plan._fire = lambda f: fired.append(f)  # observe without sleeping
        plan.on_step()
        assert fired == []  # restart=0 fault must not fire at restart 1
        plan2 = FaultPlan([Fault(kind="hang", at_step=1, restart=1, duration=0.2)])
        plan2._fire = lambda f: fired.append(f)
        plan2.on_step()
        assert len(fired) == 1

    def test_hang_sleeps_for_duration_then_resumes(self):
        plan = FaultPlan([Fault(kind="hang", at_step=2, duration=0.3)])
        start = time.monotonic()
        plan.on_step()
        assert time.monotonic() - start < 0.2  # step 1: no fault
        plan.on_step()
        assert time.monotonic() - start >= 0.3  # step 2: slept
        plan.on_step()  # fire-once: step 3 does not sleep again
        assert time.monotonic() - start < 0.7

    def test_corrupt_file_is_deterministic(self, tmp_path):
        payload = bytes(range(256)) * 64
        a, b = tmp_path / "a.bin", tmp_path / "b.bin"
        a.write_bytes(payload)
        b.write_bytes(payload)
        chaos.corrupt_file(str(a), mode="flip", seed=5)
        chaos.corrupt_file(str(b), mode="flip", seed=5)
        assert a.read_bytes() == b.read_bytes() != payload
        chaos.corrupt_file(str(a), mode="truncate")
        assert len(a.read_bytes()) == len(payload) // 2


# -------------------------------------------------- drain / preempt faults


class TestDrainPreemptFaults:
    def test_drain_at_step_alias_normalized(self):
        fault = Fault(kind="drain_at_step", at_step=5)
        assert fault.kind == "drain"
        # And it round-trips through the spec the agent hands to workers.
        plan = FaultPlan.from_spec(FaultPlan([fault]).to_spec())
        assert plan.faults[0].kind == "drain"

    def test_drain_touches_file_and_sigterms_self(self, tmp_path):
        """The in-worker drain fault: touch TPURUN_DRAIN_FILE first (so the
        worker's SIGTERM handler reads 'snapshot and go'), then SIGTERM self.
        A handler-less subprocess just dies -15; the file proves the order."""
        drain_file = tmp_path / "drain_0"
        script = textwrap.dedent(
            """
            import os
            from distributed_pytorch_tpu.chaos import FaultPlan
            plan = FaultPlan.from_spec(os.environ["TPURUN_FAULT_PLAN"])
            for i in range(4):
                plan.on_step()
                print("step", i + 1, flush=True)
            """
        )
        result = subprocess.run(
            [sys.executable, "-c", script],
            env={
                **os.environ,
                "PYTHONPATH": REPO,
                "TPURUN_FAULT_PLAN": json.dumps(
                    {"faults": [{"kind": "drain", "at_step": 2}]}
                ),
                "TPURUN_DRAIN_FILE": str(drain_file),
            },
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert result.returncode == -15  # SIGTERM, default disposition
        assert "[chaos] drain request (self) at step 2" in result.stdout
        assert drain_file.read_text() == "chaos\n"  # written BEFORE the kill
        assert "\nstep 2" not in result.stdout

    def test_bare_sigterm_without_drain_file_still_kills_trainer(self, tmp_path):
        """The disambiguation that keeps FAILURE restarts fast: under tpurun
        (TPURUN_DRAIN_FILE exported) a SIGTERM with the file NOT touched is a
        teardown, not a drain — the Trainer's handler re-raises the default
        disposition and dies immediately instead of latching the flag."""
        script = textwrap.dedent(
            """
            import os, signal
            import optax
            from distributed_pytorch_tpu.models import ToyRegressor
            from distributed_pytorch_tpu.training.trainer import Trainer
            from distributed_pytorch_tpu.utils.data import (
                MaterializedDataset, ShardedLoader,
            )
            trainer = Trainer(
                ToyRegressor(), ShardedLoader(MaterializedDataset(32), 16),
                optax.sgd(1e-2), save_every=1, snapshot_path="s.npz",
            )
            os.kill(os.getpid(), signal.SIGTERM)
            print("survived", flush=True)  # must never be reached
            """
        )
        result = subprocess.run(
            [sys.executable, "-c", script],
            env={
                **os.environ,
                "PYTHONPATH": REPO,
                "JAX_PLATFORMS": "cpu",
                "TPURUN_DRAIN_FILE": str(tmp_path / "never_touched"),
            },
            cwd=tmp_path,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert result.returncode == -15, result.stdout + result.stderr
        assert "survived" not in result.stdout

    def test_preempt_sigterms_parent_then_escalates_to_sigkill(self, tmp_path):
        """The preempt fault models a spot reclaim: SIGTERM the PARENT (the
        agent) now, SIGKILL it after the grace window. Two-level subprocess:
        the 'agent' installs a SIGTERM handler and refuses to die — only the
        escalation can end it, and the marker proves SIGTERM came first."""
        (tmp_path / "child.py").write_text(
            textwrap.dedent(
                """
                import os, time
                from distributed_pytorch_tpu.chaos import FaultPlan
                plan = FaultPlan.from_spec(os.environ["TPURUN_FAULT_PLAN"])
                plan.on_step()  # fires preempt at step 1
                time.sleep(5)   # keep the escalation timer alive, as a live worker would
                """
            )
        )
        parent_script = textwrap.dedent(
            """
            import os, signal, subprocess, sys, time
            signal.signal(
                signal.SIGTERM,
                lambda *a: open("parent_got_sigterm", "w").write("ok"),
            )
            child = subprocess.Popen([sys.executable, "child.py"])
            child.wait()
            time.sleep(60)  # refuse to exit: only SIGKILL can end this
            """
        )
        result = subprocess.run(
            [sys.executable, "-c", parent_script],
            env={
                **os.environ,
                "PYTHONPATH": REPO,
                "TPURUN_FAULT_PLAN": json.dumps(
                    {"faults": [{"kind": "preempt", "at_step": 1, "duration": 1.0}]}
                ),
            },
            cwd=tmp_path,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert result.returncode == -9  # the escalation, not the SIGTERM
        assert (tmp_path / "parent_got_sigterm").exists()  # soft signal landed first
        assert "[chaos] preempting agent pid" in result.stdout
        assert "SIGKILL after 1s" in result.stdout


# ---------------------------------------------------------------- FaultProxy


class TestFaultProxy:
    @pytest.fixture()
    def store(self):
        from distributed_pytorch_tpu.elastic.store import KVStoreServer

        port = free_port()
        with KVStoreServer(port) as server:
            yield server, port

    def test_forwards_then_partitions_then_heals(self, store):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        _, port = store
        with FaultProxy("127.0.0.1", port) as proxy:
            client = KVStoreClient(
                proxy.host, proxy.port, retry_deadline=10.0
            )
            client.set("k", "v")
            assert client.get("k") == "v"

            proxy.partition()
            fail_fast = KVStoreClient(
                proxy.host, proxy.port, connect_timeout=2.0, retry_deadline=0.0
            )
            with pytest.raises((ConnectionError, OSError)):
                fail_fast.get("k")
            fail_fast.close()

            proxy.heal()
            # The retrying client rides out the partition transparently.
            assert client.get("k") == "v"
            client.close()

    def test_client_survives_timed_partition_mid_wait_ge(self, store):
        """A 1s partition injected while wait_ge is in flight: the hardened
        client reconnects and re-issues, and the op still completes once the
        target is reached through the REAL store."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        _, port = store
        with FaultProxy("127.0.0.1", port) as proxy:
            client = KVStoreClient(proxy.host, proxy.port, retry_deadline=15.0)
            result = {}

            def waiter():
                result["v"] = client.wait_ge("joined", 2, timeout=20.0)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.2)  # wait_ge is blocking server-side now
            proxy.partition(duration=1.0)
            time.sleep(0.3)
            with KVStoreClient("127.0.0.1", port) as direct:  # bypass proxy
                direct.add("joined", 2)
            t.join(timeout=15)
            assert result.get("v") == 2
            client.close()

    def test_apply_plan_schedules_partition(self, store):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        _, port = store
        plan = FaultPlan(
            [Fault(kind="store_partition", at_time=0.2, duration=0.5)]
        )
        with FaultProxy("127.0.0.1", port) as proxy:
            proxy.apply_plan(plan)
            client = KVStoreClient(proxy.host, proxy.port, retry_deadline=10.0)
            client.set("a", "1")
            time.sleep(0.4)  # now inside the scheduled partition window
            assert proxy._partitioned.is_set()
            assert client.get("a") == "1"  # retried through heal
            client.close()


# ------------------------------------------------------- store client hardening


class TestStoreClientHardening:
    def test_buffer_reset_after_timeout_mid_reply(self):
        """Satellite #1 regression: a server that stalls after sending HALF a
        reply must not poison the next request. The old client kept the
        partial frame in ``_buf`` and would have parsed ``VAL ha`` as the
        next reply; the hardened client drops socket + buffer on the timeout
        and answers the next request from a clean stream."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(2)
        port = listener.getsockname()[1]

        def serve():
            # Connection 1: read the request, send a partial reply, stall.
            conn1, _ = listener.accept()
            conn1.recv(1024)
            conn1.sendall(b"VAL poison")  # no newline: a torn reply
            # Connection 2 (the client's reconnect): behave correctly.
            conn2, _ = listener.accept()
            conn2.recv(1024)
            conn2.sendall(b"VAL clean\n")
            time.sleep(1.0)
            for c in (conn1, conn2):
                c.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = KVStoreClient("127.0.0.1", port, retry_deadline=0.0)
        with pytest.raises(OSError):  # socket.timeout mid-reply
            client._simple("GET", "k", timeout=0.5)
        assert client._buf == b""  # the poisoned frame is GONE
        assert client._sock is None
        assert client.get("k") == "clean"  # fresh stream, clean parse
        client.close()
        listener.close()

    def test_survives_server_restart_mid_wait_ge(self):
        """Acceptance criterion: kill and relaunch the real store process
        while a wait_ge is in flight; the client reconnects, re-issues, and
        later requests parse cleanly (no data loss, no misparsed replies)."""
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        server = KVStoreServer(port)
        client = KVStoreClient("127.0.0.1", port, retry_deadline=15.0)
        result = {}

        def waiter():
            result["v"] = client.wait_ge("done", 2, timeout=20.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.3)  # the WAITGE is blocking inside the server
        server._proc.kill()  # hard kill: no goodbye byte on the wire
        server._proc.wait()
        server.close()
        relaunched = KVStoreServer(port)
        try:
            with KVStoreClient("127.0.0.1", port) as other:
                other.add("done", 2)
            t.join(timeout=15)
            assert result.get("v") == 2
            # The surviving client's stream is clean for subsequent traffic.
            client.set("x", "y")
            assert client.get("x") == "y"
        finally:
            client.close()
            with KVStoreClient("127.0.0.1", port) as admin:
                admin.shutdown_server()
            relaunched.close()

    def test_mutating_retry_replays_instead_of_reapplying(self):
        """The dedup contract at the wire level: the same request id replays
        the recorded reply; a fresh id re-applies."""
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        with KVStoreServer(port):
            raw = socket.create_connection(("127.0.0.1", port))
            raw.sendall(b"ADD ctr 5 rid-a\n")
            assert raw.recv(64) == b"VAL 5\n"
            raw.sendall(b"ADD ctr 5 rid-a\n")  # the lost-reply retry
            assert raw.recv(64) == b"VAL 5\n"  # replayed, NOT re-applied
            raw.sendall(b"GET ctr\n")
            assert raw.recv(64) == b"VAL 5\n"
            raw.sendall(b"ADD ctr 5 rid-b\n")  # distinct id: a real add
            assert raw.recv(64) == b"VAL 10\n"
            raw.close()
            with KVStoreClient("127.0.0.1", port) as admin:
                admin.shutdown_server()

    def test_client_sends_request_ids_on_mutations_only(self):
        """SET/ADD/DEL carry a dedup token; GET stays bare (idempotent ops
        need no replay memory on the server)."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        lines = []

        def serve():
            conn, _ = listener.accept()
            buf = b""
            while len(lines) < 3:
                buf += conn.recv(1024)
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    lines.append(line.decode())
                    reply = b"VAL 1\n" if line.startswith((b"ADD", b"GET")) else b"OK\n"
                    conn.sendall(reply)
            conn.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = KVStoreClient("127.0.0.1", port, retry_deadline=0.0)
        client.set("k", "v")
        client.add("c", 1)
        client.get("k")
        t.join(timeout=5)
        client.close()
        listener.close()
        assert len(lines[0].split()) == 4  # SET key value reqid
        assert len(lines[1].split()) == 4  # ADD key delta reqid
        assert len(lines[2].split()) == 2  # GET key — bare
        assert lines[0].split()[3] != lines[1].split()[3]  # ids are unique

    def test_retry_deadline_bounds_unreachable_host(self):
        """Blip vs dead: a store that never answers surfaces ConnectionError
        only after (roughly) retry_deadline — the agent's 'rendezvous host
        dead' signal."""
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        server = KVStoreServer(port)
        client = KVStoreClient("127.0.0.1", port, retry_deadline=1.5)
        server._proc.kill()
        server._proc.wait()
        server.close()
        start = time.monotonic()
        with pytest.raises(ConnectionError, match="retry deadline"):
            client.get("k")
        elapsed = time.monotonic() - start
        assert 1.0 <= elapsed < 10.0
        client.close()

    def test_server_close_closes_stdout_pipe(self):
        """Satellite #2: the readiness PIPE must not leak an fd per store
        lifecycle."""
        from distributed_pytorch_tpu.elastic.store import KVStoreServer

        server = KVStoreServer(free_port())
        pipe = server._proc.stdout
        assert pipe is not None and not pipe.closed
        server.close()
        assert pipe.closed


# ------------------------------------------------------ snapshot self-healing


def _tree(value: float):
    return {
        "w": np.full((8, 8), value, np.float32),
        "b": np.full((8,), value, np.float32),
    }


class TestSnapshotIntegrity:
    def test_roundtrip_keeps_meta_clean(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        path = str(tmp_path / "c.npz")
        save_checkpoint(path, _tree(1.0), metadata={"epoch": 4})
        tree, meta = load_checkpoint(path, _tree(0.0))
        assert meta == {"epoch": 4}  # integrity plumbing stripped
        np.testing.assert_array_equal(tree["w"], _tree(1.0)["w"])

    def test_bitflip_and_truncation_fail_loudly(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import load_snapshot, save_snapshot

        for mode in ("flip", "truncate"):
            path = str(tmp_path / f"{mode}.npz")
            save_snapshot(path, _tree(1.0), epochs_run=1)
            chaos.corrupt_file(path, mode=mode, seed=11)
            with pytest.raises(Exception):  # zip CRC or SnapshotIntegrityError
                load_snapshot(path, _tree(0.0))

    def test_manifest_catches_tampering_the_zip_crc_misses(self, tmp_path):
        """Rewrite the npz with one array's bytes changed but internally
        consistent zip CRCs (what a buggy writer or post-hoc edit produces):
        only the embedded manifest can catch this."""
        from distributed_pytorch_tpu.checkpoint import (
            SnapshotIntegrityError,
            load_snapshot,
            save_snapshot,
        )

        path = str(tmp_path / "t.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        with np.load(path) as data:
            arrays = {k: np.array(data[k]) for k in data.files}
        arrays["w"][0, 0] += 1.0  # tamper one value
        np.savez(path, **arrays)  # fresh, self-consistent zip CRCs
        with pytest.raises(SnapshotIntegrityError, match="checksum mismatch"):
            load_snapshot(path, _tree(0.0))

    def test_rotation_keeps_previous_snapshot(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import load_snapshot, save_snapshot

        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)
        _, meta_prev = load_snapshot(path + ".prev", _tree(0.0))
        _, meta_cur = load_snapshot(path, _tree(0.0))
        assert (meta_prev["epochs_run"], meta_cur["epochs_run"]) == (1, 2)

    def test_fallback_quarantines_corrupt_latest(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
            save_snapshot,
        )

        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)
        chaos.corrupt_file(path, mode="flip", seed=1)
        state, meta, used = load_snapshot_with_fallback(path, _tree(0.0))
        assert meta["epochs_run"] == 1 and used == path + ".prev"
        np.testing.assert_array_equal(state["w"], _tree(1.0)["w"])
        assert os.path.exists(path + ".corrupt")
        assert "quarantined" in capfd.readouterr().err

    def test_all_corrupt_returns_none_with_loud_warning(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
            save_snapshot,
        )

        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)
        chaos.corrupt_file(path, mode="truncate")
        chaos.corrupt_file(path + ".prev", mode="truncate")
        assert load_snapshot_with_fallback(path, _tree(0.0)) is None
        err = capfd.readouterr().err
        assert "start FRESH" in err
        # BOTH bad files were quarantined for post-mortem, not left loadable.
        assert os.path.exists(path + ".corrupt")
        assert os.path.exists(path + ".prev.corrupt")
        assert not os.path.exists(path) and not os.path.exists(path + ".prev")

    def test_missing_snapshot_is_silent(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import load_snapshot_with_fallback

        assert (
            load_snapshot_with_fallback(str(tmp_path / "nope.npz"), _tree(0.0))
            is None
        )
        err = capfd.readouterr().err  # a first run is not an incident
        assert "WARNING" not in err and "quarantined" not in err

    def test_manager_restore_falls_back_past_corrupt_latest(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=3)
        mgr.save(_tree(1.0), step=1, epochs_run=1)
        time.sleep(0.02)  # distinct mtimes: recency order must be stable
        mgr.save(_tree(2.0), step=2, epochs_run=2)
        latest = os.path.join(str(tmp_path / "c"), "ckpt_0000000002.npz")
        chaos.corrupt_file(latest, mode="truncate")
        tree, meta = mgr.restore(_tree(0.0))
        assert meta["epochs_run"] == 1
        np.testing.assert_array_equal(tree["w"], _tree(1.0)["w"])
        assert os.path.exists(latest + ".corrupt")
        assert "quarantined" in capfd.readouterr().err

    def test_plan_corrupts_snapshot_write_via_env(self, tmp_path, monkeypatch):
        """End-to-end checkpointer hook: an armed corrupt_snapshot fault
        damages the SECOND write; the first (rotated to .prev) remains the
        recovery point."""
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
            save_snapshot,
        )

        monkeypatch.setenv(
            chaos.ENV_VAR,
            json.dumps(
                {"faults": [{"kind": "corrupt_snapshot", "at_save": 2,
                             "restart": None, "mode": "flip"}]}
            ),
        )
        chaos._reset()
        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)  # fault fires here
        state, meta, used = load_snapshot_with_fallback(path, _tree(0.0))
        assert meta["epochs_run"] == 1 and used == path + ".prev"


# --------------------------------------------------- Trainer corrupt-resume


class TestTrainerCorruptResume:
    """Satellite #3: the Trainer-level contract — quarantine the corrupt
    latest, resume from the previous rotated snapshot with a visible notice,
    and never silently start fresh while a valid older snapshot exists."""

    def _trainer(self, tmp_path, **kwargs):
        import optax

        from distributed_pytorch_tpu.models import ToyRegressor
        from distributed_pytorch_tpu.training.trainer import Trainer
        from distributed_pytorch_tpu.utils.data import (
            MaterializedDataset,
            ShardedLoader,
        )

        return Trainer(
            ToyRegressor(),
            ShardedLoader(MaterializedDataset(64), 16),
            optax.sgd(1e-2),
            save_every=1,
            snapshot_path=str(tmp_path / "snap.npz"),
            checkpoint_path=str(tmp_path / "ckpt.npz"),
            **kwargs,
        )

    def test_resume_falls_back_to_previous_rotated_snapshot(
        self, tmp_path, capfd
    ):
        trainer = self._trainer(tmp_path)
        trainer.train(2)  # snap.npz (epochs 2) + snap.npz.prev (epochs 1)
        snap = str(tmp_path / "snap.npz")
        chaos.corrupt_file(snap, mode="flip", seed=2)
        capfd.readouterr()  # drop the training chatter

        resumed = self._trainer(tmp_path)
        out = capfd.readouterr()
        assert resumed.epochs_run == 1  # .prev, not fresh
        assert os.path.exists(snap + ".corrupt")
        assert "quarantined" in out.err
        assert "fell back to" in out.out
        # And training continues to completion from the fallback point.
        resumed.train(3)
        final = self._trainer(tmp_path)
        assert final.epochs_run == 3

    def test_all_corrupt_starts_fresh_loudly(self, tmp_path, capfd):
        trainer = self._trainer(tmp_path)
        trainer.train(2)
        chaos.corrupt_file(str(tmp_path / "snap.npz"), mode="truncate")
        chaos.corrupt_file(str(tmp_path / "snap.npz.prev"), mode="truncate")
        capfd.readouterr()
        fresh = self._trainer(tmp_path)
        assert fresh.epochs_run == 0
        assert "start FRESH" in capfd.readouterr().err
        # Both corrupt files quarantined — the fresh start is loud AND leaves
        # the evidence behind.
        assert os.path.exists(str(tmp_path / "snap.npz") + ".corrupt")
        assert os.path.exists(str(tmp_path / "snap.npz.prev") + ".corrupt")

    def test_prev_only_resumes_after_crash_between_rotate_and_write(
        self, tmp_path
    ):
        """A crash in the window between rotation and the new write leaves
        only <path>.prev on disk; probe-on-init must still resume from it."""
        trainer = self._trainer(tmp_path)
        trainer.train(2)
        os.unlink(str(tmp_path / "snap.npz"))  # the interrupted write
        resumed = self._trainer(tmp_path)
        assert resumed.epochs_run == 1


# -------------------------------------------------------- agent-level drills


AGENT_TIMEOUT = 180


def run_tpurun(tmp_path, worker_src, *args, timeout=AGENT_TIMEOUT, extra_env=None):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(worker_src))
    env = dict(os.environ, PYTHONPATH=REPO)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_tpu.elastic", *args, str(worker)],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


class TestAgentStoreBlip:
    def test_two_agents_survive_store_partition(self, tmp_path):
        """Satellite #4, agent level: a 2s store partition (injected by the
        FaultProxy each agent wires up from the armed plan) mid-run is a
        BLIP — both agents retry through it, no generation bump, exit 0."""
        port = free_port()
        plan = json.dumps(
            {"faults": [{"kind": "store_partition", "restart": None,
                         "at_time": 1.0, "duration": 2.0}]}
        )
        worker_src = """
        import os, time
        time.sleep(5)  # long enough that the partition happens mid-run
        open(f"done.{os.environ['PROCESS_ID']}", "w").write("ok")
        """
        results = {}

        def launch(rank):
            results[rank] = run_tpurun(
                tmp_path,
                worker_src,
                "--nnodes", "2",
                "--node-rank", str(rank),
                "--nproc-per-node", "1",
                "--rdzv-endpoint", f"127.0.0.1:{port}",
                "--max-restarts", "1",
                "--store-retry-deadline", "20",
                extra_env={"TPURUN_FAULT_PLAN": plan},
            )

        threads = [
            threading.Thread(target=launch, args=(r,)) for r in (0, 1)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=AGENT_TIMEOUT)
        for rank in (0, 1):
            res = results[rank]
            assert res.returncode == 0, res.stdout + res.stderr
            assert "restart" not in res.stdout  # a blip, not a failure
            assert "FaultProxy" in res.stdout  # the proxy was actually used
        assert sorted(p.name for p in tmp_path.glob("done.*")) == [
            "done.0",
            "done.1",
        ]


class TestPreemptClassification:
    """The acceptance criterion 'a drain exit is never misclassified': the
    agent's log shows ``preempt`` (budget intact) for drain exits and
    ``failure`` (budget decremented) for real crashes."""

    def test_drain_exit_restarts_for_free(self, tmp_path):
        """A worker exiting with the drain code restarts the world WITHOUT
        spending budget: --max-restarts 0 still reaches the second spawn."""
        result = run_tpurun(
            tmp_path,
            """
            import os, sys
            restart = int(os.environ["TPURUN_RESTART_COUNT"])
            open(f"gen.{restart}", "w").write("ok")
            sys.exit(int(os.environ["TPURUN_DRAIN_EXIT_CODE"]) if restart == 0 else 0)
            """,
            "--standalone",
            "--nproc-per-node", "1",
            "--max-restarts", "0",
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "preempt detected" in result.stdout
        assert "restart budget intact (0/0 used)" in result.stdout
        assert "failure detected" not in result.stdout
        assert (tmp_path / "gen.0").exists() and (tmp_path / "gen.1").exists()

    def test_real_crash_still_decrements_budget(self, tmp_path):
        """A SIGKILLed worker is a FAILURE: the restart is paid for."""
        result = run_tpurun(
            tmp_path,
            """
            import os, signal, sys
            if int(os.environ["TPURUN_RESTART_COUNT"]) == 0:
                os.kill(os.getpid(), signal.SIGKILL)
            sys.exit(0)
            """,
            "--standalone",
            "--nproc-per-node", "1",
            "--max-restarts", "1",
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "failure detected" in result.stdout
        assert "restart 1/1" in result.stdout
        assert "preempt detected" not in result.stdout

    def test_persistent_failure_exhausts_budget(self, tmp_path):
        result = run_tpurun(
            tmp_path,
            "import sys\nsys.exit(7)\n",
            "--standalone",
            "--nproc-per-node", "1",
            "--max-restarts", "0",
        )
        assert result.returncode == 1
        assert "giving up after 0 restarts" in result.stderr
        assert "preempt detected" not in result.stdout

    def test_agent_sigterm_drains_workers_and_exits_143(self, tmp_path):
        """The tentpole's agent half, end to end: SIGTERM the agent; it
        forwards the soft notice (drain file + SIGTERM), the workers exit
        with the drain code, and the agent exits 143 instead of respawning."""
        worker = tmp_path / "worker.py"
        worker.write_text(
            textwrap.dedent(
                """
                import os, signal, sys, time
                flag = {"term": False}
                signal.signal(
                    signal.SIGTERM, lambda *a: flag.__setitem__("term", True)
                )
                pid = os.environ["PROCESS_ID"]
                drain_file = os.environ["TPURUN_DRAIN_FILE"]
                open(f"ready.{pid}", "w").write("ok")
                deadline = time.time() + 60
                while time.time() < deadline:
                    if flag["term"] or os.path.exists(drain_file):
                        open(f"drained.{pid}", "w").write("ok")
                        sys.exit(int(os.environ["TPURUN_DRAIN_EXIT_CODE"]))
                    time.sleep(0.05)
                sys.exit(3)  # never drained: a real failure
                """
            )
        )
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "distributed_pytorch_tpu.elastic",
                "--standalone",
                "--nproc-per-node", "2",
                "--max-restarts", "0",
                "--drain-grace", "20",
                str(worker),
            ],
            env=dict(os.environ, PYTHONPATH=REPO),
            cwd=tmp_path,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (tmp_path / "ready.0").exists() and (tmp_path / "ready.1").exists():
                    break
                assert proc.poll() is None, proc.communicate()
                time.sleep(0.1)
            else:
                pytest.fail("workers never became ready")
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        assert proc.returncode == 143, out + err
        assert "[tpurun] drain: SIGTERM received" in out
        assert "[tpurun] drain complete; exiting (node preempted)" in out
        assert (tmp_path / "drained.0").exists()
        assert (tmp_path / "drained.1").exists()


class TestWorkerGroupTerminate:
    def test_sigterm_ignorer_escalated_to_sigkill_within_grace(self, tmp_path):
        """Satellite #1: terminate() must not hang on a worker that ignores
        SIGTERM — past the grace deadline it escalates to SIGKILL."""
        from distributed_pytorch_tpu.elastic.agent import (
            ElasticConfig,
            WorkerGroup,
        )

        marker = tmp_path / "ignoring"
        script = (
            "import signal, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            f"open({str(marker)!r}, 'w').write('ok')\n"
            "time.sleep(600)\n"
        )
        group = WorkerGroup(
            ElasticConfig(), [sys.executable, "-c", script], 0
        )
        try:
            deadline = time.monotonic() + 30
            while not marker.exists():
                assert time.monotonic() < deadline, "worker never started"
                time.sleep(0.05)
            start = time.monotonic()
            group.terminate(grace=1.0)
            elapsed = time.monotonic() - start
        finally:
            for p in group.procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        assert group.procs[0].poll() == -9, "SIGTERM ignorer was not SIGKILLed"
        assert elapsed < 8.0, f"terminate took {elapsed:.1f}s for grace=1.0"


class TestAsyncCheckpointerKilledMidWrite:
    def test_prev_survives_sigkill_between_rotate_and_write(self, tmp_path):
        """Satellite #3: SIGKILL a process whose AsyncCheckpointer has rotated
        the old snapshot to .prev but not finished the new write — the .prev
        must remain loadable (the drain/resume recovery point)."""
        script = textwrap.dedent(
            """
            import time
            import numpy as np
            from distributed_pytorch_tpu import checkpoint
            from distributed_pytorch_tpu.checkpoint import (
                AsyncCheckpointer,
                save_snapshot,
            )

            tree1 = {"w": np.full((4,), 1.0, np.float32)}
            tree2 = {"w": np.full((4,), 2.0, np.float32)}
            save_snapshot("snap.npz", tree1, epochs_run=1)

            def stalled_write(path, arrays):
                # Rotation already happened on this (writer) thread; signal
                # the parent, then model a write that never completes.
                open("rotated", "w").write("ok")
                time.sleep(600)

            checkpoint._write_npz = stalled_write
            ck = AsyncCheckpointer()
            ck.save("snap.npz", tree2, metadata={"epochs_run": 2},
                    keep_previous=True)
            time.sleep(600)
            """
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script],
            env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
            cwd=tmp_path,
        )
        try:
            deadline = time.monotonic() + 120
            while not (tmp_path / "rotated").exists():
                assert proc.poll() is None, "checkpoint writer died early"
                assert time.monotonic() < deadline, "writer never reached rotate"
                time.sleep(0.1)
            proc.kill()  # mid-write: the torn state a real preemption leaves
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.wait()

        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
        )

        path = str(tmp_path / "snap.npz")
        result = load_snapshot_with_fallback(
            path, {"w": np.zeros((4,), np.float32)}
        )
        assert result is not None, "no loadable snapshot survived the kill"
        state, meta, used = result
        assert used == path + ".prev"
        assert meta["epochs_run"] == 1
        np.testing.assert_array_equal(state["w"], np.full((4,), 1.0, np.float32))


DRILL_WORKER = """
'''The acceptance drill's worker: a REAL rung-4 training process. All fault
injection comes from the seeded TPURUN_FAULT_PLAN in the environment — the
worker body contains no sabotage.'''
import os, runpy, sys

pid = os.environ["PROCESS_ID"]
restart = os.environ["TPURUN_RESTART_COUNT"]
open(f"gen.{pid}.{restart}", "w").write("ok")

sys.argv = [
    "multihost_pod.py", "3", "1",
    "--snapshot_path", "drill.npz",
    "--fake_devices", "2",
]
runpy.run_path(os.environ["POD_EXAMPLE"], run_name="__main__")
"""

# The seeded acceptance plan. Per-process epochs are 16 steps (2048 samples /
# 2 shards / batch 64); snapshots save every epoch.
#  gen 0: worker 1 SIGKILLed at step 21 (6 steps into epoch 1)
#  gen 1: resumes from the epoch-1 snapshot; process 0's first save there
#         (epochs_run=2) is bit-flipped right after the write; worker 1 is
#         killed again at step 21 (5 steps into epoch 2); a 2s store
#         partition also hits each agent's store client at t=3s
#  gen 2: the corrupt latest is quarantined, resume falls back to .prev
#         (epochs_run=1), training replays epoch 1 — and 5 steps in, worker 1
#         is drain-preempted: both ranks agree on the step (the per-batch
#         allgather), snapshot at (epoch 1, step 5), exit with the drain
#         code. The agent classifies it as a PREEMPTION: free restart.
#  gen 3: resumes mid-epoch at (epoch 1, step 5), finishes epochs 1-2.
DRILL_PLAN = {
    "seed": 42,
    "faults": [
        {"kind": "kill", "process_id": 1, "restart": 0, "at_step": 21},
        {"kind": "corrupt_snapshot", "process_id": 0, "restart": 1,
         "at_save": 1, "mode": "flip"},
        {"kind": "kill", "process_id": 1, "restart": 1, "at_step": 21},
        {"kind": "store_partition", "restart": None, "at_time": 3.0,
         "duration": 2.0},
        {"kind": "drain_at_step", "process_id": 1, "restart": 2, "at_step": 5},
    ],
}


def epoch_losses(text):
    """Parse the JSON metric lines a drill run prints; last write per epoch
    wins (exactly what a resumed run produces)."""
    losses = {}
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "epoch_loss" in rec:
                losses[int(rec["epoch"])] = rec["epoch_loss"]
    return losses


def run_clean_reference(tmp_path, name="clean.npz"):
    """The un-faulted reference workload: one process, 4 virtual chips, same
    global batch of 128 — bit-identical epoch losses to the faulted runs."""
    return subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "examples", "multihost_pod.py"),
            "3", "1",
            "--snapshot_path", str(tmp_path / name),
            "--fake_devices", "4",
        ],
        cwd=tmp_path,
        env={
            **os.environ,
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        },
        capture_output=True,
        text=True,
        timeout=AGENT_TIMEOUT,
    )


class TestSeededDrill:
    @pytest.mark.slow
    def test_kill_partition_corruption_preemption_drill_deterministic(
        self, tmp_path
    ):
        """ISSUE acceptance: a seeded FaultPlan composing worker kill, a 2s
        store partition, snapshot corruption, AND a mid-epoch drain
        preemption completes training with the correct final epoch count on
        CPU, and the surviving epoch losses match an uninterrupted run
        (rtol 1e-6). The drain restart is FREE: the --max-restarts 2 budget
        is fully consumed by the two kills alone."""
        start = time.monotonic()
        result = run_tpurun(
            tmp_path,
            DRILL_WORKER,
            "--standalone",
            "--nproc-per-node", "2",
            "--max-restarts", "2",
            "--store-retry-deadline", "20",
            timeout=AGENT_TIMEOUT,
            extra_env={
                "POD_EXAMPLE": os.path.join(REPO, "examples", "multihost_pod.py"),
                "TPURUN_FAULT_PLAN": json.dumps(DRILL_PLAN),
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "cpu",
            },
        )
        drill_elapsed = time.monotonic() - start
        assert result.returncode == 0, result.stdout + result.stderr
        assert drill_elapsed < 90, f"drill took {drill_elapsed:.1f}s"

        # FOUR generations ran on a budget of two: the two kills paid, the
        # preemption was free.
        markers = {p.name for p in tmp_path.glob("gen.*")}
        assert {"gen.0.0", "gen.0.1", "gen.0.2", "gen.0.3"} <= markers
        assert "restart 2/2" in result.stdout
        assert "preempt detected" in result.stdout
        assert "restart budget intact" in result.stdout
        # Generation 2 resumed via the fallback chain, not fresh.
        assert "fell back to" in result.stdout
        assert (tmp_path / "drill.npz.corrupt").exists()
        # The drain snapshotted at the agreed step and gen 3 resumed there.
        assert "[drain] just-in-time snapshot at epoch 1, step 5" in result.stdout
        assert "Resuming training from snapshot at Epoch 1, step 5" in result.stdout
        # The final epoch count is correct: all 3 epochs trained.
        losses = epoch_losses(result.stdout)
        assert set(losses) == {0, 1, 2}, f"epochs seen: {sorted(losses)}"

        # Determinism: identical to the same workload with no faults at all.
        clean = run_clean_reference(tmp_path)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        clean_losses = epoch_losses(clean.stdout)
        assert set(clean_losses) == {0, 1, 2}
        for epoch, loss in clean_losses.items():
            np.testing.assert_allclose(losses[epoch], loss, rtol=1e-6)

    @pytest.mark.slow
    def test_sigterm_mid_epoch_drains_and_resumed_run_matches_clean(
        self, tmp_path
    ):
        """ISSUE acceptance, the external-reclaim flavor: the AGENT receives
        SIGTERM mid-epoch (a chaos ``preempt`` fault with a 30s SIGKILL
        grace, standing in for a spot reclaim notice). The workers snapshot
        at the current step and exit with the drain code; the agent exits
        143 without respawning. A SECOND launch resumes from the exact batch
        and the combined loss trajectory matches an un-preempted run."""
        reclaimed = run_tpurun(
            tmp_path,
            DRILL_WORKER,
            "--standalone",
            "--nproc-per-node", "2",
            "--max-restarts", "0",
            "--drain-grace", "30",
            timeout=AGENT_TIMEOUT,
            extra_env={
                "POD_EXAMPLE": os.path.join(REPO, "examples", "multihost_pod.py"),
                # Worker 0, 5 steps into epoch 1, SIGTERMs its agent (ppid)
                # and arms the 30s SIGKILL escalation a real reclaim carries.
                "TPURUN_FAULT_PLAN": json.dumps({
                    "seed": 43,
                    "faults": [{"kind": "preempt", "process_id": 0,
                                "restart": 0, "at_step": 21, "duration": 30.0}],
                }),
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "cpu",
            },
        )
        assert reclaimed.returncode == 143, reclaimed.stdout + reclaimed.stderr
        assert "[tpurun] drain: SIGTERM received" in reclaimed.stdout
        assert "[drain] just-in-time snapshot at epoch 1" in reclaimed.stdout
        assert "[tpurun] drain complete; exiting (node preempted)" in reclaimed.stdout
        # Budget untouched on the way out: no failure path ran.
        assert "failure detected" not in reclaimed.stdout
        assert "giving up" not in reclaimed.stderr

        # The just-in-time snapshot is step-granular and mid-epoch.
        meta = json.loads(
            bytes(
                np.load(tmp_path / "drill.npz")["__checkpoint_meta__"].tobytes()
            ).decode("utf-8")
        )
        assert meta["epochs_run"] == 1
        assert 0 < meta["step_in_epoch"] < 16, meta

        # Relaunch (the replacement capacity): resumes at the exact batch.
        resumed = run_tpurun(
            tmp_path,
            DRILL_WORKER,
            "--standalone",
            "--nproc-per-node", "2",
            "--max-restarts", "0",
            timeout=AGENT_TIMEOUT,
            extra_env={
                "POD_EXAMPLE": os.path.join(REPO, "examples", "multihost_pod.py"),
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "cpu",
            },
        )
        assert resumed.returncode == 0, resumed.stdout + resumed.stderr
        assert (
            f"Resuming training from snapshot at Epoch 1, step "
            f"{meta['step_in_epoch']}" in resumed.stdout
        )

        # Loss-trajectory parity: epoch 0 from the reclaimed run, epochs 1-2
        # from the resumed one, against the un-preempted reference.
        losses = epoch_losses(reclaimed.stdout)
        losses.update(epoch_losses(resumed.stdout))
        assert set(losses) == {0, 1, 2}, f"epochs seen: {sorted(losses)}"
        clean = run_clean_reference(tmp_path, name="clean2.npz")
        assert clean.returncode == 0, clean.stdout + clean.stderr
        for epoch, loss in epoch_losses(clean.stdout).items():
            np.testing.assert_allclose(losses[epoch], loss, rtol=1e-6)
