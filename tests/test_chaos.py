"""Chaos harness tests: deterministic fault injection (``chaos.py``), the
hardened store client (reconnect/backoff/request-id dedup), and self-healing
checksummed snapshots.

Everything here is CPU-only and seeded. The fast tests (unmarked beyond
``chaos``) run in tier-1; the end-to-end drill at the bottom — the ISSUE's
acceptance drill: worker kill + 2s store partition + snapshot corruption in
one seeded plan — is also marked ``slow``.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.chaos import Fault, FaultPlan, FaultProxy

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    """The module caches the parsed plan per process; tests that arm the env
    var need a clean slate on both sides."""
    chaos._reset()
    yield
    chaos._reset()


# ----------------------------------------------------------------- FaultPlan


class TestFaultPlan:
    def test_parse_inline_and_file_roundtrip(self, tmp_path):
        spec = {
            "seed": 7,
            "faults": [
                {"kind": "kill", "process_id": 1, "at_step": 3},
                {"kind": "corrupt_snapshot", "at_save": 2, "mode": "truncate"},
            ],
        }
        inline = FaultPlan.from_spec(json.dumps(spec))
        path = tmp_path / "plan.json"
        path.write_text(json.dumps(spec))
        from_file = FaultPlan.from_spec(str(path))
        for plan in (inline, from_file):
            assert plan.seed == 7
            assert [f.kind for f in plan.faults] == ["kill", "corrupt_snapshot"]
            assert plan.faults[0].at_step == 3
        # to_spec -> from_spec is stable (what the agent hands to workers)
        again = FaultPlan.from_spec(inline.to_spec())
        assert [vars(f) for f in again.faults] == [vars(f) for f in inline.faults]

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            Fault(kind="meteor")

    def test_kill_fires_at_exact_step_in_matching_process_only(self, tmp_path):
        script = textwrap.dedent(
            """
            import os
            from distributed_pytorch_tpu.chaos import FaultPlan
            plan = FaultPlan.from_spec(os.environ["TPURUN_FAULT_PLAN"])
            for i in range(6):
                plan.on_step()
                print("step", i + 1, flush=True)
            """
        )
        plan = json.dumps(
            {"faults": [{"kind": "kill", "process_id": 1, "at_step": 3}]}
        )

        def run(process_id):
            return subprocess.run(
                [sys.executable, "-c", script],
                env={
                    **os.environ,
                    "PYTHONPATH": REPO,
                    "TPURUN_FAULT_PLAN": plan,
                    "PROCESS_ID": process_id,
                },
                capture_output=True,
                text=True,
                timeout=60,
            )

        hit = run("1")
        assert hit.returncode == -9  # SIGKILL: uncatchable, like kill -9
        assert "[chaos] SIGKILL self at step 3" in hit.stdout
        # The loop never reached its own step-3 print (fault fires first).
        assert "\nstep 3" not in hit.stdout
        miss = run("0")  # same plan, wrong process: no fault
        assert miss.returncode == 0 and "step 6" in miss.stdout

    def test_restart_generation_matching(self, monkeypatch):
        monkeypatch.setenv("TPURUN_RESTART_COUNT", "1")
        fired = []
        plan = FaultPlan([Fault(kind="hang", at_step=1, restart=0, duration=0.2)])
        plan._fire = lambda f: fired.append(f)  # observe without sleeping
        plan.on_step()
        assert fired == []  # restart=0 fault must not fire at restart 1
        plan2 = FaultPlan([Fault(kind="hang", at_step=1, restart=1, duration=0.2)])
        plan2._fire = lambda f: fired.append(f)
        plan2.on_step()
        assert len(fired) == 1

    def test_hang_sleeps_for_duration_then_resumes(self):
        plan = FaultPlan([Fault(kind="hang", at_step=2, duration=0.3)])
        start = time.monotonic()
        plan.on_step()
        assert time.monotonic() - start < 0.2  # step 1: no fault
        plan.on_step()
        assert time.monotonic() - start >= 0.3  # step 2: slept
        plan.on_step()  # fire-once: step 3 does not sleep again
        assert time.monotonic() - start < 0.7

    def test_corrupt_file_is_deterministic(self, tmp_path):
        payload = bytes(range(256)) * 64
        a, b = tmp_path / "a.bin", tmp_path / "b.bin"
        a.write_bytes(payload)
        b.write_bytes(payload)
        chaos.corrupt_file(str(a), mode="flip", seed=5)
        chaos.corrupt_file(str(b), mode="flip", seed=5)
        assert a.read_bytes() == b.read_bytes() != payload
        chaos.corrupt_file(str(a), mode="truncate")
        assert len(a.read_bytes()) == len(payload) // 2


# ---------------------------------------------------------------- FaultProxy


class TestFaultProxy:
    @pytest.fixture()
    def store(self):
        from distributed_pytorch_tpu.elastic.store import KVStoreServer

        port = free_port()
        with KVStoreServer(port) as server:
            yield server, port

    def test_forwards_then_partitions_then_heals(self, store):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        _, port = store
        with FaultProxy("127.0.0.1", port) as proxy:
            client = KVStoreClient(
                proxy.host, proxy.port, retry_deadline=10.0
            )
            client.set("k", "v")
            assert client.get("k") == "v"

            proxy.partition()
            fail_fast = KVStoreClient(
                proxy.host, proxy.port, connect_timeout=2.0, retry_deadline=0.0
            )
            with pytest.raises((ConnectionError, OSError)):
                fail_fast.get("k")
            fail_fast.close()

            proxy.heal()
            # The retrying client rides out the partition transparently.
            assert client.get("k") == "v"
            client.close()

    def test_client_survives_timed_partition_mid_wait_ge(self, store):
        """A 1s partition injected while wait_ge is in flight: the hardened
        client reconnects and re-issues, and the op still completes once the
        target is reached through the REAL store."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        _, port = store
        with FaultProxy("127.0.0.1", port) as proxy:
            client = KVStoreClient(proxy.host, proxy.port, retry_deadline=15.0)
            result = {}

            def waiter():
                result["v"] = client.wait_ge("joined", 2, timeout=20.0)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.2)  # wait_ge is blocking server-side now
            proxy.partition(duration=1.0)
            time.sleep(0.3)
            with KVStoreClient("127.0.0.1", port) as direct:  # bypass proxy
                direct.add("joined", 2)
            t.join(timeout=15)
            assert result.get("v") == 2
            client.close()

    def test_apply_plan_schedules_partition(self, store):
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        _, port = store
        plan = FaultPlan(
            [Fault(kind="store_partition", at_time=0.2, duration=0.5)]
        )
        with FaultProxy("127.0.0.1", port) as proxy:
            proxy.apply_plan(plan)
            client = KVStoreClient(proxy.host, proxy.port, retry_deadline=10.0)
            client.set("a", "1")
            time.sleep(0.4)  # now inside the scheduled partition window
            assert proxy._partitioned.is_set()
            assert client.get("a") == "1"  # retried through heal
            client.close()


# ------------------------------------------------------- store client hardening


class TestStoreClientHardening:
    def test_buffer_reset_after_timeout_mid_reply(self):
        """Satellite #1 regression: a server that stalls after sending HALF a
        reply must not poison the next request. The old client kept the
        partial frame in ``_buf`` and would have parsed ``VAL ha`` as the
        next reply; the hardened client drops socket + buffer on the timeout
        and answers the next request from a clean stream."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(2)
        port = listener.getsockname()[1]

        def serve():
            # Connection 1: read the request, send a partial reply, stall.
            conn1, _ = listener.accept()
            conn1.recv(1024)
            conn1.sendall(b"VAL poison")  # no newline: a torn reply
            # Connection 2 (the client's reconnect): behave correctly.
            conn2, _ = listener.accept()
            conn2.recv(1024)
            conn2.sendall(b"VAL clean\n")
            time.sleep(1.0)
            for c in (conn1, conn2):
                c.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = KVStoreClient("127.0.0.1", port, retry_deadline=0.0)
        with pytest.raises(OSError):  # socket.timeout mid-reply
            client._simple("GET", "k", timeout=0.5)
        assert client._buf == b""  # the poisoned frame is GONE
        assert client._sock is None
        assert client.get("k") == "clean"  # fresh stream, clean parse
        client.close()
        listener.close()

    def test_survives_server_restart_mid_wait_ge(self):
        """Acceptance criterion: kill and relaunch the real store process
        while a wait_ge is in flight; the client reconnects, re-issues, and
        later requests parse cleanly (no data loss, no misparsed replies)."""
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        server = KVStoreServer(port)
        client = KVStoreClient("127.0.0.1", port, retry_deadline=15.0)
        result = {}

        def waiter():
            result["v"] = client.wait_ge("done", 2, timeout=20.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.3)  # the WAITGE is blocking inside the server
        server._proc.kill()  # hard kill: no goodbye byte on the wire
        server._proc.wait()
        server.close()
        relaunched = KVStoreServer(port)
        try:
            with KVStoreClient("127.0.0.1", port) as other:
                other.add("done", 2)
            t.join(timeout=15)
            assert result.get("v") == 2
            # The surviving client's stream is clean for subsequent traffic.
            client.set("x", "y")
            assert client.get("x") == "y"
        finally:
            client.close()
            with KVStoreClient("127.0.0.1", port) as admin:
                admin.shutdown_server()
            relaunched.close()

    def test_mutating_retry_replays_instead_of_reapplying(self):
        """The dedup contract at the wire level: the same request id replays
        the recorded reply; a fresh id re-applies."""
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        with KVStoreServer(port):
            raw = socket.create_connection(("127.0.0.1", port))
            raw.sendall(b"ADD ctr 5 rid-a\n")
            assert raw.recv(64) == b"VAL 5\n"
            raw.sendall(b"ADD ctr 5 rid-a\n")  # the lost-reply retry
            assert raw.recv(64) == b"VAL 5\n"  # replayed, NOT re-applied
            raw.sendall(b"GET ctr\n")
            assert raw.recv(64) == b"VAL 5\n"
            raw.sendall(b"ADD ctr 5 rid-b\n")  # distinct id: a real add
            assert raw.recv(64) == b"VAL 10\n"
            raw.close()
            with KVStoreClient("127.0.0.1", port) as admin:
                admin.shutdown_server()

    def test_client_sends_request_ids_on_mutations_only(self):
        """SET/ADD/DEL carry a dedup token; GET stays bare (idempotent ops
        need no replay memory on the server)."""
        from distributed_pytorch_tpu.elastic.store import KVStoreClient

        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        lines = []

        def serve():
            conn, _ = listener.accept()
            buf = b""
            while len(lines) < 3:
                buf += conn.recv(1024)
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    lines.append(line.decode())
                    reply = b"VAL 1\n" if line.startswith((b"ADD", b"GET")) else b"OK\n"
                    conn.sendall(reply)
            conn.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = KVStoreClient("127.0.0.1", port, retry_deadline=0.0)
        client.set("k", "v")
        client.add("c", 1)
        client.get("k")
        t.join(timeout=5)
        client.close()
        listener.close()
        assert len(lines[0].split()) == 4  # SET key value reqid
        assert len(lines[1].split()) == 4  # ADD key delta reqid
        assert len(lines[2].split()) == 2  # GET key — bare
        assert lines[0].split()[3] != lines[1].split()[3]  # ids are unique

    def test_retry_deadline_bounds_unreachable_host(self):
        """Blip vs dead: a store that never answers surfaces ConnectionError
        only after (roughly) retry_deadline — the agent's 'rendezvous host
        dead' signal."""
        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )

        port = free_port()
        server = KVStoreServer(port)
        client = KVStoreClient("127.0.0.1", port, retry_deadline=1.5)
        server._proc.kill()
        server._proc.wait()
        server.close()
        start = time.monotonic()
        with pytest.raises(ConnectionError, match="retry deadline"):
            client.get("k")
        elapsed = time.monotonic() - start
        assert 1.0 <= elapsed < 10.0
        client.close()

    def test_server_close_closes_stdout_pipe(self):
        """Satellite #2: the readiness PIPE must not leak an fd per store
        lifecycle."""
        from distributed_pytorch_tpu.elastic.store import KVStoreServer

        server = KVStoreServer(free_port())
        pipe = server._proc.stdout
        assert pipe is not None and not pipe.closed
        server.close()
        assert pipe.closed


# ------------------------------------------------------ snapshot self-healing


def _tree(value: float):
    return {
        "w": np.full((8, 8), value, np.float32),
        "b": np.full((8,), value, np.float32),
    }


class TestSnapshotIntegrity:
    def test_roundtrip_keeps_meta_clean(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        path = str(tmp_path / "c.npz")
        save_checkpoint(path, _tree(1.0), metadata={"epoch": 4})
        tree, meta = load_checkpoint(path, _tree(0.0))
        assert meta == {"epoch": 4}  # integrity plumbing stripped
        np.testing.assert_array_equal(tree["w"], _tree(1.0)["w"])

    def test_bitflip_and_truncation_fail_loudly(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import load_snapshot, save_snapshot

        for mode in ("flip", "truncate"):
            path = str(tmp_path / f"{mode}.npz")
            save_snapshot(path, _tree(1.0), epochs_run=1)
            chaos.corrupt_file(path, mode=mode, seed=11)
            with pytest.raises(Exception):  # zip CRC or SnapshotIntegrityError
                load_snapshot(path, _tree(0.0))

    def test_manifest_catches_tampering_the_zip_crc_misses(self, tmp_path):
        """Rewrite the npz with one array's bytes changed but internally
        consistent zip CRCs (what a buggy writer or post-hoc edit produces):
        only the embedded manifest can catch this."""
        from distributed_pytorch_tpu.checkpoint import (
            SnapshotIntegrityError,
            load_snapshot,
            save_snapshot,
        )

        path = str(tmp_path / "t.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        with np.load(path) as data:
            arrays = {k: np.array(data[k]) for k in data.files}
        arrays["w"][0, 0] += 1.0  # tamper one value
        np.savez(path, **arrays)  # fresh, self-consistent zip CRCs
        with pytest.raises(SnapshotIntegrityError, match="checksum mismatch"):
            load_snapshot(path, _tree(0.0))

    def test_rotation_keeps_previous_snapshot(self, tmp_path):
        from distributed_pytorch_tpu.checkpoint import load_snapshot, save_snapshot

        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)
        _, epochs_prev = load_snapshot(path + ".prev", _tree(0.0))
        _, epochs_cur = load_snapshot(path, _tree(0.0))
        assert (epochs_prev, epochs_cur) == (1, 2)

    def test_fallback_quarantines_corrupt_latest(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
            save_snapshot,
        )

        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)
        chaos.corrupt_file(path, mode="flip", seed=1)
        state, epochs, used = load_snapshot_with_fallback(path, _tree(0.0))
        assert epochs == 1 and used == path + ".prev"
        np.testing.assert_array_equal(state["w"], _tree(1.0)["w"])
        assert os.path.exists(path + ".corrupt")
        assert "quarantined" in capfd.readouterr().err

    def test_all_corrupt_returns_none_with_loud_warning(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
            save_snapshot,
        )

        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)
        chaos.corrupt_file(path, mode="truncate")
        chaos.corrupt_file(path + ".prev", mode="truncate")
        assert load_snapshot_with_fallback(path, _tree(0.0)) is None
        err = capfd.readouterr().err
        assert "start FRESH" in err

    def test_missing_snapshot_is_silent(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import load_snapshot_with_fallback

        assert (
            load_snapshot_with_fallback(str(tmp_path / "nope.npz"), _tree(0.0))
            is None
        )
        err = capfd.readouterr().err  # a first run is not an incident
        assert "WARNING" not in err and "quarantined" not in err

    def test_manager_restore_falls_back_past_corrupt_latest(self, tmp_path, capfd):
        from distributed_pytorch_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "c"), keep=3)
        mgr.save(_tree(1.0), step=1, epochs_run=1)
        time.sleep(0.02)  # distinct mtimes: recency order must be stable
        mgr.save(_tree(2.0), step=2, epochs_run=2)
        latest = os.path.join(str(tmp_path / "c"), "ckpt_0000000002.npz")
        chaos.corrupt_file(latest, mode="truncate")
        tree, meta = mgr.restore(_tree(0.0))
        assert meta["epochs_run"] == 1
        np.testing.assert_array_equal(tree["w"], _tree(1.0)["w"])
        assert os.path.exists(latest + ".corrupt")
        assert "quarantined" in capfd.readouterr().err

    def test_plan_corrupts_snapshot_write_via_env(self, tmp_path, monkeypatch):
        """End-to-end checkpointer hook: an armed corrupt_snapshot fault
        damages the SECOND write; the first (rotated to .prev) remains the
        recovery point."""
        from distributed_pytorch_tpu.checkpoint import (
            load_snapshot_with_fallback,
            save_snapshot,
        )

        monkeypatch.setenv(
            chaos.ENV_VAR,
            json.dumps(
                {"faults": [{"kind": "corrupt_snapshot", "at_save": 2,
                             "restart": None, "mode": "flip"}]}
            ),
        )
        chaos._reset()
        path = str(tmp_path / "s.npz")
        save_snapshot(path, _tree(1.0), epochs_run=1)
        save_snapshot(path, _tree(2.0), epochs_run=2)  # fault fires here
        state, epochs, used = load_snapshot_with_fallback(path, _tree(0.0))
        assert epochs == 1 and used == path + ".prev"


# --------------------------------------------------- Trainer corrupt-resume


class TestTrainerCorruptResume:
    """Satellite #3: the Trainer-level contract — quarantine the corrupt
    latest, resume from the previous rotated snapshot with a visible notice,
    and never silently start fresh while a valid older snapshot exists."""

    def _trainer(self, tmp_path, **kwargs):
        import optax

        from distributed_pytorch_tpu.models import ToyRegressor
        from distributed_pytorch_tpu.training.trainer import Trainer
        from distributed_pytorch_tpu.utils.data import (
            MaterializedDataset,
            ShardedLoader,
        )

        return Trainer(
            ToyRegressor(),
            ShardedLoader(MaterializedDataset(64), 16),
            optax.sgd(1e-2),
            save_every=1,
            snapshot_path=str(tmp_path / "snap.npz"),
            checkpoint_path=str(tmp_path / "ckpt.npz"),
            **kwargs,
        )

    def test_resume_falls_back_to_previous_rotated_snapshot(
        self, tmp_path, capfd
    ):
        trainer = self._trainer(tmp_path)
        trainer.train(2)  # snap.npz (epochs 2) + snap.npz.prev (epochs 1)
        snap = str(tmp_path / "snap.npz")
        chaos.corrupt_file(snap, mode="flip", seed=2)
        capfd.readouterr()  # drop the training chatter

        resumed = self._trainer(tmp_path)
        out = capfd.readouterr()
        assert resumed.epochs_run == 1  # .prev, not fresh
        assert os.path.exists(snap + ".corrupt")
        assert "quarantined" in out.err
        assert "fell back to" in out.out
        # And training continues to completion from the fallback point.
        resumed.train(3)
        final = self._trainer(tmp_path)
        assert final.epochs_run == 3

    def test_all_corrupt_starts_fresh_loudly(self, tmp_path, capfd):
        trainer = self._trainer(tmp_path)
        trainer.train(2)
        chaos.corrupt_file(str(tmp_path / "snap.npz"), mode="truncate")
        chaos.corrupt_file(str(tmp_path / "snap.npz.prev"), mode="truncate")
        capfd.readouterr()
        fresh = self._trainer(tmp_path)
        assert fresh.epochs_run == 0
        assert "start FRESH" in capfd.readouterr().err

    def test_prev_only_resumes_after_crash_between_rotate_and_write(
        self, tmp_path
    ):
        """A crash in the window between rotation and the new write leaves
        only <path>.prev on disk; probe-on-init must still resume from it."""
        trainer = self._trainer(tmp_path)
        trainer.train(2)
        os.unlink(str(tmp_path / "snap.npz"))  # the interrupted write
        resumed = self._trainer(tmp_path)
        assert resumed.epochs_run == 1


# -------------------------------------------------------- agent-level drills


AGENT_TIMEOUT = 180


def run_tpurun(tmp_path, worker_src, *args, timeout=AGENT_TIMEOUT, extra_env=None):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(worker_src))
    env = dict(os.environ, PYTHONPATH=REPO)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_tpu.elastic", *args, str(worker)],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


class TestAgentStoreBlip:
    def test_two_agents_survive_store_partition(self, tmp_path):
        """Satellite #4, agent level: a 2s store partition (injected by the
        FaultProxy each agent wires up from the armed plan) mid-run is a
        BLIP — both agents retry through it, no generation bump, exit 0."""
        port = free_port()
        plan = json.dumps(
            {"faults": [{"kind": "store_partition", "restart": None,
                         "at_time": 1.0, "duration": 2.0}]}
        )
        worker_src = """
        import os, time
        time.sleep(5)  # long enough that the partition happens mid-run
        open(f"done.{os.environ['PROCESS_ID']}", "w").write("ok")
        """
        results = {}

        def launch(rank):
            results[rank] = run_tpurun(
                tmp_path,
                worker_src,
                "--nnodes", "2",
                "--node-rank", str(rank),
                "--nproc-per-node", "1",
                "--rdzv-endpoint", f"127.0.0.1:{port}",
                "--max-restarts", "1",
                "--store-retry-deadline", "20",
                extra_env={"TPURUN_FAULT_PLAN": plan},
            )

        threads = [
            threading.Thread(target=launch, args=(r,)) for r in (0, 1)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=AGENT_TIMEOUT)
        for rank in (0, 1):
            res = results[rank]
            assert res.returncode == 0, res.stdout + res.stderr
            assert "restart" not in res.stdout  # a blip, not a failure
            assert "FaultProxy" in res.stdout  # the proxy was actually used
        assert sorted(p.name for p in tmp_path.glob("done.*")) == [
            "done.0",
            "done.1",
        ]


DRILL_WORKER = """
'''The acceptance drill's worker: a REAL rung-4 training process. All fault
injection comes from the seeded TPURUN_FAULT_PLAN in the environment — the
worker body contains no sabotage.'''
import os, runpy, sys

pid = os.environ["PROCESS_ID"]
restart = os.environ["TPURUN_RESTART_COUNT"]
open(f"gen.{pid}.{restart}", "w").write("ok")

sys.argv = [
    "multihost_pod.py", "3", "1",
    "--snapshot_path", "drill.npz",
    "--fake_devices", "2",
]
runpy.run_path(os.environ["POD_EXAMPLE"], run_name="__main__")
"""

# The seeded acceptance plan. Per-process epochs are 16 steps (2048 samples /
# 2 shards / batch 64); snapshots save every epoch.
#  gen 0: worker 1 SIGKILLed at step 21 (6 steps into epoch 1)
#  gen 1: resumes from the epoch-1 snapshot; process 0's first save there
#         (epochs_run=2) is bit-flipped right after the write; worker 1 is
#         killed again at step 21 (5 steps into epoch 2); a 2s store
#         partition also hits each agent's store client at t=3s
#  gen 2: the corrupt latest is quarantined, resume falls back to .prev
#         (epochs_run=1), training re-runs epochs 1-2 and completes.
DRILL_PLAN = {
    "seed": 42,
    "faults": [
        {"kind": "kill", "process_id": 1, "restart": 0, "at_step": 21},
        {"kind": "corrupt_snapshot", "process_id": 0, "restart": 1,
         "at_save": 1, "mode": "flip"},
        {"kind": "kill", "process_id": 1, "restart": 1, "at_step": 21},
        {"kind": "store_partition", "restart": None, "at_time": 3.0,
         "duration": 2.0},
    ],
}


class TestSeededDrill:
    @pytest.mark.slow
    def test_kill_partition_corruption_drill_completes_deterministically(
        self, tmp_path
    ):
        """ISSUE acceptance: a seeded FaultPlan combining worker kill, a 2s
        store partition, and snapshot corruption completes training with the
        correct final epoch count on CPU in < 60s, and the surviving epoch
        losses match an uninterrupted run bit-for-bit (rtol 1e-6)."""
        start = time.monotonic()
        result = run_tpurun(
            tmp_path,
            DRILL_WORKER,
            "--standalone",
            "--nproc-per-node", "2",
            "--max-restarts", "2",
            "--store-retry-deadline", "20",
            timeout=AGENT_TIMEOUT,
            extra_env={
                "POD_EXAMPLE": os.path.join(REPO, "examples", "multihost_pod.py"),
                "TPURUN_FAULT_PLAN": json.dumps(DRILL_PLAN),
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "JAX_PLATFORMS": "cpu",
            },
        )
        drill_elapsed = time.monotonic() - start
        assert result.returncode == 0, result.stdout + result.stderr
        assert drill_elapsed < 60, f"drill took {drill_elapsed:.1f}s"

        # Three generations ran (two restarts used).
        markers = {p.name for p in tmp_path.glob("gen.*")}
        assert {"gen.0.0", "gen.0.1", "gen.0.2"} <= markers
        assert "restart 2/2" in result.stdout
        # Generation 2 resumed via the fallback chain, not fresh.
        assert "fell back to" in result.stdout
        assert (tmp_path / "drill.npz.corrupt").exists()
        # The final epoch count is correct: all 3 epochs trained.
        losses = {}
        for line in result.stdout.splitlines():
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "epoch_loss" in rec:
                    losses[int(rec["epoch"])] = rec["epoch_loss"]
        assert set(losses) == {0, 1, 2}, f"epochs seen: {sorted(losses)}"

        # Determinism: identical to the same workload with no faults at all
        # (one process, 4 virtual chips, same global batch of 128).
        clean = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "examples", "multihost_pod.py"),
                "3", "1",
                "--snapshot_path", str(tmp_path / "clean.npz"),
                "--fake_devices", "4",
            ],
            cwd=tmp_path,
            env={
                **os.environ,
                "PYTHONPATH": REPO,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            },
            capture_output=True,
            text=True,
            timeout=AGENT_TIMEOUT,
        )
        assert clean.returncode == 0, clean.stdout + clean.stderr
        clean_losses = {}
        for line in clean.stdout.splitlines():
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "epoch_loss" in rec:
                    clean_losses[int(rec["epoch"])] = rec["epoch_loss"]
        for epoch, loss in clean_losses.items():
            np.testing.assert_allclose(losses[epoch], loss, rtol=1e-6)
