"""Router crash-recovery drills, in-process edition.

The journal unit tests (``test_journal.py``) prove the byte-level WAL
properties without JAX; the process drills (``test_router_procs.py``)
SIGKILL a real router process. This file covers the middle layer on CPU
in one process: a journaled :class:`FleetRouter` is dropped without
``close()`` (the in-process stand-in for SIGKILL — nothing it held is
consulted again) and ``FleetRouter.recover`` rebuilds a new router from
the journal alone, re-attaching the surviving replica clients the way
the process path re-adopts live workers.

The acceptance bar matches the fleet story everywhere else: greedy
tokens identical to an uninterrupted single-engine reference, exactly
once, across the crash.
"""

import json
import os
import random

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.chaos import InjectedFault
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import Tracer
from distributed_pytorch_tpu.serving import (
    FleetRouter,
    FrontDoor,
    InferenceEngine,
    LocalReplicaClient,
    SamplingParams,
    replay_journal,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    chaos._reset()
    yield
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


def tiny_lm():
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def model_and_params():
    model = tiny_lm()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)
MAX_NEW = 6

PREFIX = [5, 7, 11, 2]
AFFINITY_PROMPTS = [PREFIX + [t, t + 1] for t in (1, 9, 17, 25, 33)]
OTHER_PROMPTS = [[2, 2, 3, 17, 40], [6, 1, 9], [40, 41], [3, 3, 3, 3, 8]]
DRILL_PROMPTS = AFFINITY_PROMPTS + OTHER_PROMPTS


def params_for(i):
    return SamplingParams(max_new_tokens=MAX_NEW)


def make_clients(model, params, n=3):
    return [
        LocalReplicaClient(InferenceEngine(model, params, **ENGINE_KW))
        for _ in range(n)
    ]


@pytest.fixture(scope="module")
def ref_outputs(model_and_params):
    model, params = model_and_params
    eng = InferenceEngine(model, params, **ENGINE_KW)
    ids = [
        eng.submit(p, params_for(i)) for i, p in enumerate(DRILL_PROMPTS)
    ]
    eng.run()
    out = {i: eng.poll(rid).generated for i, rid in enumerate(ids)}
    eng.close()
    return out


def submit_all(router):
    return {
        idx: router.submit(DRILL_PROMPTS[idx], params_for(idx))
        for idx in range(len(DRILL_PROMPTS))
    }


def run_to_completion(router, limit=500):
    rounds = 0
    while not all(s.finished for s in router._shadows.values()):
        router.step()
        rounds += 1
        assert rounds < limit, "drill did not converge"


def assert_parity(router, fids, ref_outputs):
    for idx, fid in fids.items():
        st = router.poll(fid)
        assert st.finished, f"prompt {idx} (fid {fid}) never finished"
        assert list(st.generated) == list(ref_outputs[idx]), (
            f"prompt {idx}: fleet produced {st.generated}, "
            f"reference {ref_outputs[idx]}"
        )


# --------------------------------------------------------- re-adoption


def test_recover_readopts_live_workers(
    tmp_path, model_and_params, ref_outputs
):
    """Router crashes mid-decode; every worker survives. Recovery
    re-attaches all three from the journal, reconciles committed tokens
    from the workers (worker wins), and finishes with exact parity."""
    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    clients = make_clients(model, params)
    router = FleetRouter(clients, journal_dir=jdir)
    fids = submit_all(router)
    for _ in range(3):
        router.step()
    unfinished = sum(
        1 for s in router._shadows.values() if not s.finished
    )
    finished = len(fids) - unfinished
    assert unfinished, "crash must land mid-decode"
    del router  # SIGKILL stand-in: no close(), no flush, nothing reused

    recovered = FleetRouter.recover(
        jdir, replicas={f"r{i}": c for i, c in enumerate(clients)}
    )
    try:
        summary = recovered.last_recovery
        assert summary is not None
        assert sorted(summary["re_adopted_workers"]) == ["r0", "r1", "r2"]
        assert summary["lost_workers"] == []
        assert summary["re_adopted"] == unfinished
        assert summary["re_admitted"] == 0 and summary["lost"] == 0
        assert summary["corrupt_segments"] == []
        # Finished requests replay from their journaled finish records.
        done_now = sum(
            1 for s in recovered._shadows.values() if s.finished
        )
        assert done_now >= finished
        # The reconciliation summary is the /statusz recovery block.
        assert recovered.describe()["recovery"] == summary

        run_to_completion(recovered)
        assert_parity(recovered, fids, ref_outputs)
        # Same fid namespace continues: no id reuse after recovery.
        new_fid = recovered.submit([1, 2, 3], params_for(0))
        assert new_fid not in fids.values()
    finally:
        recovered.close()


def test_recover_readmits_dead_workers_requests(
    tmp_path, model_and_params, ref_outputs
):
    """Router AND one worker die together. The dead worker's requests
    re-admit on survivors through the same token-identical re-prefill
    as failover; parity holds for every request."""
    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    clients = make_clients(model, params)
    router = FleetRouter(clients, journal_dir=jdir)
    fids = submit_all(router)
    for _ in range(3):
        router.step()
    lost_name = "r2"
    orphaned = sum(
        1
        for s in router._shadows.values()
        if not s.finished and s.replica == lost_name
    )
    assert orphaned, "the dead worker must hold live work"
    del router

    # r2 is not offered back (its "process" died with the router and no
    # registry entry points at a live pid).
    recovered = FleetRouter.recover(
        jdir,
        replicas={"r0": clients[0], "r1": clients[1]},
    )
    try:
        summary = recovered.last_recovery
        assert summary["lost_workers"] == [lost_name]
        assert sorted(summary["re_adopted_workers"]) == ["r0", "r1"]
        assert summary["re_admitted"] == orphaned
        assert summary["lost"] == 0
        moved = [
            s for s in recovered._shadows.values() if s.failovers > 0
        ]
        assert len(moved) == orphaned
        assert all(s.replica != lost_name for s in moved)

        run_to_completion(recovered)
        assert_parity(recovered, fids, ref_outputs)
    finally:
        recovered.close()


def test_recover_with_no_workers_declares_lost(tmp_path, model_and_params):
    """Everything died: no worker to re-adopt, no survivor to re-admit
    on. Unfinished requests are declared lost (terminal, cancelled) —
    never silently dropped, never resurrected from garbage."""
    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    clients = make_clients(model, params, n=2)
    router = FleetRouter(clients, journal_dir=jdir)
    fids = submit_all(router)
    router.step()
    inflight = sum(
        1 for s in router._shadows.values() if not s.finished
    )
    del router

    recovered = FleetRouter.recover(jdir, replicas={})
    try:
        summary = recovered.last_recovery
        assert sorted(summary["lost_workers"]) == ["r0", "r1"]
        assert summary["lost"] == inflight
        assert summary["re_adopted"] == 0 and summary["re_admitted"] == 0
        for fid in fids.values():
            st = recovered.poll(fid)
            assert st.finished  # terminal either way: finished or lost
    finally:
        recovered.close()


def test_recover_quarantines_torn_journal_tail(
    tmp_path, model_and_params, ref_outputs
):
    """A torn record at the journal tail (the router died mid-append)
    quarantines to ``*.corrupt``, recovery proceeds from the last good
    record, and the drill still converges with parity."""
    from distributed_pytorch_tpu.serving.journal import journal_segments

    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    clients = make_clients(model, params)
    router = FleetRouter(clients, journal_dir=jdir)
    fids = submit_all(router)
    for _ in range(3):
        router.step()
    del router

    seg = journal_segments(jdir)[-1]
    whole = open(seg, "rb").read()
    open(seg, "wb").write(whole[:-9])  # tear mid-record

    recovered = FleetRouter.recover(
        jdir, replicas={f"r{i}": c for i, c in enumerate(clients)}
    )
    try:
        summary = recovered.last_recovery
        assert len(summary["corrupt_segments"]) == 1
        assert summary["corrupt_segments"][0].endswith(".corrupt")
        assert os.path.exists(summary["corrupt_segments"][0])
        run_to_completion(recovered)
        assert_parity(recovered, fids, ref_outputs)
    finally:
        recovered.close()


# ------------------------------------------------------ chaos router kill


def test_chaos_kill_router_fault_then_recover(
    tmp_path, model_and_params, ref_outputs
):
    """The armed ``kill_router`` fault (raise mode — the in-process
    drill form of SIGKILL) fires at the step boundary AFTER the batched
    journal flush, so recovery sees every delivered mark; the drill then
    recovers and converges with parity."""
    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    os.environ[chaos.ENV_VAR] = json.dumps({
        "seed": 7,
        "faults": [
            {"kind": "kill_router", "at_step": 3, "mode": "raise"}
        ],
    })
    chaos._reset()
    clients = make_clients(model, params)
    router = FleetRouter(clients, journal_dir=jdir)
    fids = submit_all(router)
    killed_at = None
    for rnd in range(10):
        try:
            router.step()
        except InjectedFault as exc:
            assert exc.kind == "kill_router"
            killed_at = rnd
            break
    assert killed_at is not None, "armed kill_router never fired"
    del router
    chaos._reset()
    os.environ.pop(chaos.ENV_VAR, None)

    recovered = FleetRouter.recover(
        jdir, replicas={f"r{i}": c for i, c in enumerate(clients)}
    )
    try:
        run_to_completion(recovered)
        assert_parity(recovered, fids, ref_outputs)
    finally:
        recovered.close()


def test_restart_router_under_load_gates_on_queue(
    tmp_path, model_and_params
):
    """``restart_router_under_load`` holds fire until the router holds
    at least ``min_queue`` in-flight requests."""
    model, params = model_and_params
    os.environ[chaos.ENV_VAR] = json.dumps({
        "faults": [
            {"kind": "restart_router_under_load", "at_step": 1,
             "min_queue": 4, "mode": "raise"}
        ],
    })
    chaos._reset()
    clients = make_clients(model, params, n=2)
    router = FleetRouter(clients, journal_dir=str(tmp_path / "j"))
    try:
        router.submit(DRILL_PROMPTS[0], params_for(0))
        router.step()  # 1 in flight < min_queue 4: no fire
        for idx in range(1, 5):
            router.submit(DRILL_PROMPTS[idx], params_for(idx))
        with pytest.raises(InjectedFault) as exc_info:
            for _ in range(10):
                router.step()
        assert exc_info.value.kind == "restart_router_under_load"
    finally:
        chaos._reset()
        os.environ.pop(chaos.ENV_VAR, None)
        router.close()


# ------------------------------------------- exactly-once streaming


def test_exactly_once_streaming_across_restart(
    tmp_path, model_and_params, ref_outputs
):
    """The headline delivery guarantee: streams interrupted by a router
    crash resume at the journaled delivered high-water mark — across
    both incarnations each client sees its reference token sequence
    exactly once (no duplicate, no gap), under one trace_id."""
    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    clients = make_clients(model, params)
    router = FleetRouter(clients, journal_dir=jdir)
    door = FrontDoor(router)
    streams = {
        idx: door.open_stream(DRILL_PROMPTS[idx], params=params_for(idx))
        for idx in range(len(DRILL_PROMPTS))
    }
    # Deliver a PARTIAL prefix of some streams: uneven high-waters make
    # duplicate-vs-gap failures distinguishable after the restart.
    taken = {idx: [] for idx in streams}
    for _ in range(4):
        door.pump()
    for idx, want in ((0, 3), (1, 1), (5, 2)):
        stream = streams[idx]
        for _ in range(want):
            taken[idx].append(next(stream))
    # One more pump: the next router step's leading flush journals the
    # delivered marks noted above (the crash model is a kill at a step
    # boundary, exactly where chaos injects it).
    door.pump()
    fid_of = {idx: s.req_id for idx, s in streams.items()}
    trace_of = {idx: s.trace_id for idx, s in streams.items()}
    pre_delivered = {idx: len(t) for idx, t in taken.items()}
    del door
    del router  # crash

    recovered = FleetRouter.recover(
        jdir, replicas={f"r{i}": c for i, c in enumerate(clients)}
    )
    door2 = FrontDoor(recovered)
    try:
        adopted = door2.adopt_streams()
        # Every stream with an undelivered remainder is re-adopted at
        # its journaled high-water mark.
        for idx, fid in fid_of.items():
            if fid is None:
                continue
            assert fid in adopted, f"stream {idx} (fid {fid}) not adopted"
            assert adopted[fid].delivered == pre_delivered[idx]
            # One trace identity spans both router incarnations.
            shadow = recovered._shadows[fid]
            assert shadow.trace_id == trace_of[idx]
        for idx, fid in fid_of.items():
            taken[idx].extend(adopted[fid].drain())
        for idx in streams:
            assert taken[idx] == list(ref_outputs[idx]), (
                f"stream {idx}: delivered {taken[idx]}, "
                f"reference {ref_outputs[idx]}"
            )
        # The recovery block rides the door's /statusz document.
        assert door2.status()["fleet"]["recovery"] is not None
    finally:
        door2 = None
        recovered.close()


def test_recovery_journal_is_compacted_and_reusable(
    tmp_path, model_and_params, ref_outputs
):
    """After recovery the journal directory holds ONE fresh segment
    (the compacted base — old incarnation segments deleted once
    captured) and it can seed a SECOND recovery: crash-of-the-recovered
    -router works the same as crash-of-the-original."""
    from distributed_pytorch_tpu.serving.journal import journal_segments

    model, params = model_and_params
    jdir = str(tmp_path / "journal")
    clients = make_clients(model, params)
    router = FleetRouter(clients, journal_dir=jdir)
    fids = submit_all(router)
    for _ in range(2):
        router.step()
    del router

    second = FleetRouter.recover(
        jdir, replicas={f"r{i}": c for i, c in enumerate(clients)}
    )
    assert len(journal_segments(jdir)) == 1
    second.step()
    del second  # crash again

    third = FleetRouter.recover(
        jdir, replicas={f"r{i}": c for i, c in enumerate(clients)}
    )
    try:
        assert third.last_recovery["records_replayed"] > 0
        run_to_completion(third)
        assert_parity(third, fids, ref_outputs)
        state = replay_journal(jdir)  # live journal stays replayable
        assert state.corrupt == []
    finally:
        third.close()
