"""True multi-process training tests (SURVEY.md §4: "multi-process without a
cluster") — N local processes rendezvous through ``jax.distributed``, each
with its own virtual CPU devices, exercising the full rung-4 path: env
bootstrap, per-process loader shards, global-batch assembly, process-0-only
snapshotting, and loss parity against the serial rung.

Each subprocess runs ``examples/multihost_pod.py`` exactly as a pod host
would; this file is the automated twin of the verify-skill's manual rung-4
drive.
"""

import json
import os
import re
import subprocess
import sys
import socket

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_workers(n_procs, args, *, fake_devices, port, extra_env=None):
    """Run n_procs copies of the rung-4 example; return their stdouts."""
    procs = []
    for pid in range(n_procs):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES=str(n_procs),
            PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        env.pop("XLA_FLAGS", None)  # the example sets device count itself
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    os.path.join(REPO, "examples", "multihost_pod.py"),
                    *args,
                    "--fake_devices",
                    str(fake_devices),
                ],
                cwd=REPO,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out}"
    finally:
        # A hung rendezvous must not leak workers (they hold the coordinator
        # port and would poison subsequent runs).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def epoch_losses(text):
    """epoch -> epoch_loss parsed from the metric JSON lines."""
    losses = {}
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "epoch_loss" in record:
                losses[int(record["epoch"])] = record["epoch_loss"]
    return losses


@pytest.mark.slow
def test_two_process_parity_and_single_writer(tmp_path):
    """2 processes x 4 fake chips == the 8-chip single-process run, epoch for
    epoch; snapshot written once (by global process 0)."""
    snap = tmp_path / "mp.npz"
    outs = launch_workers(
        2,
        ["2", "1", "--snapshot_path", str(snap)],
        fake_devices=4,
        port=free_port(),
    )
    assert snap.exists()
    mp_losses = epoch_losses(outs[0]) or epoch_losses(outs[1])
    assert set(mp_losses) == {0, 1}

    # Reference: the same global run in ONE process over 8 virtual chips.
    single = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "examples", "multihost_pod.py"),
            "2", "1",
            "--snapshot_path", str(tmp_path / "sp.npz"),
            "--fake_devices", "8",
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert single.returncode == 0, single.stdout + single.stderr
    sp_losses = epoch_losses(single.stdout)
    for epoch, loss in sp_losses.items():
        np.testing.assert_allclose(mp_losses[epoch], loss, rtol=1e-5)

    # Single-writer contract: only process 0 printed the snapshot banner.
    writers = sum("snapshot saved" in out.lower() for out in outs)
    assert writers == 1, f"{writers} processes claimed the snapshot write"


@pytest.mark.slow
def test_two_process_snapshot_resume(tmp_path):
    """Kill-and-relaunch elasticity across processes: second launch resumes
    from the snapshot's epoch offset (reference multigpu_torchrun.py:30-40)."""
    snap = tmp_path / "resume.npz"
    launch_workers(
        2, ["1", "1", "--snapshot_path", str(snap)], fake_devices=2,
        port=free_port(),
    )
    assert snap.exists()
    outs = launch_workers(
        2, ["3", "1", "--snapshot_path", str(snap)], fake_devices=2,
        port=free_port(),
    )
    combined = "\n".join(outs)
    assert re.search(r"Resuming training from snapshot at Epoch 1", combined)
    losses = epoch_losses(outs[0]) or epoch_losses(outs[1])
    assert set(losses) == {1, 2}  # epochs 1..2 ran; epoch 0 skipped


RING_WORKER = '''
"""2-process x 4-device ring-attention parity worker: the ppermute ring
crosses PROCESS boundaries (the configuration a real pod uses — and the one
most likely to deadlock), each process holding 2 of the 4 sequence shards."""
import numpy as np

from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices

use_fake_cpu_devices(2)

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu import setup_distributed, shutdown_distributed
from distributed_pytorch_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
)

setup_distributed()
assert jax.device_count() == 4 and jax.process_count() == 2
from jax.sharding import Mesh

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("sequence",))

rng = np.random.default_rng(0)
b, t, h, d = 2, 64, 4, 16
full = [rng.standard_normal((b, t, h, d)).astype(np.float32) for _ in range(3)]
sharding = NamedSharding(mesh, P(None, "sequence"))
q, k, v = (
    jax.make_array_from_callback((b, t, h, d), sharding, lambda idx, a=a: a[idx])
    for a in full
)


def ring_loss(q, k, v):
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    return jnp.sum(out.astype(jnp.float32) ** 2), out


(loss, out), dq = jax.jit(
    jax.value_and_grad(ring_loss, has_aux=True),
    in_shardings=(sharding,) * 3,
    out_shardings=((None, sharding), sharding),
)(q, k, v)

from jax.experimental import multihost_utils

out_full = np.asarray(multihost_utils.process_allgather(out, tiled=True))
dq_full = np.asarray(multihost_utils.process_allgather(dq, tiled=True))

# Dense single-host reference on the identical full arrays.
ref = dot_product_attention(*map(jnp.asarray, full), causal=True)


def ref_loss(q):
    return jnp.sum(
        dot_product_attention(q, jnp.asarray(full[1]), jnp.asarray(full[2]),
                              causal=True) ** 2
    )


ref_dq = jax.grad(ref_loss)(jnp.asarray(full[0]))
np.testing.assert_allclose(out_full, np.asarray(ref), rtol=1e-4, atol=1e-4)
np.testing.assert_allclose(dq_full, np.asarray(ref_dq), rtol=1e-3, atol=1e-3)
print("RING_PARITY_OK", flush=True)
shutdown_distributed()
'''


@pytest.mark.slow
def test_cross_process_ring_attention_parity(tmp_path):
    """The ring's ppermute rotation crosses process boundaries: 2 processes x
    2 fake devices each, sequence axis of 4 spanning both. Output AND dq must
    match the dense single-host reference on the same arrays."""
    import textwrap

    worker = tmp_path / "ring_worker.py"
    worker.write_text(textwrap.dedent(RING_WORKER))
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        env.pop("XLA_FLAGS", None)  # the worker sets device count itself
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker)],
                cwd=tmp_path,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"ring worker failed:\n{out}"
    assert all("RING_PARITY_OK" in out for out in outs)


ULYSSES_WORKER = '''
"""2-process x 4-device ulysses parity worker: the seq<->head all_to_all
crosses PROCESS boundaries (a real pod's configuration), each process
holding 2 of the 4 sequence shards; heads redistribute across both."""
import numpy as np

from distributed_pytorch_tpu.utils.platform import use_fake_cpu_devices

use_fake_cpu_devices(2)

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_tpu import setup_distributed, shutdown_distributed
from distributed_pytorch_tpu.ops.attention import (
    dot_product_attention,
    ulysses_attention,
)

setup_distributed()
assert jax.device_count() == 4 and jax.process_count() == 2
from jax.sharding import Mesh

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("sequence",))

rng = np.random.default_rng(0)
b, t, h, d = 2, 64, 4, 16
full = [rng.standard_normal((b, t, h, d)).astype(np.float32) for _ in range(3)]
sharding = NamedSharding(mesh, P(None, "sequence"))
q, k, v = (
    jax.make_array_from_callback((b, t, h, d), sharding, lambda idx, a=a: a[idx])
    for a in full
)


def uly_loss(q, k, v):
    out = ulysses_attention(q, k, v, mesh=mesh, causal=True)
    return jnp.sum(out.astype(jnp.float32) ** 2), out


(loss, out), dq = jax.jit(
    jax.value_and_grad(uly_loss, has_aux=True),
    in_shardings=(sharding,) * 3,
    out_shardings=((None, sharding), sharding),
)(q, k, v)

from jax.experimental import multihost_utils

out_full = np.asarray(multihost_utils.process_allgather(out, tiled=True))
dq_full = np.asarray(multihost_utils.process_allgather(dq, tiled=True))

ref = dot_product_attention(*map(jnp.asarray, full), causal=True)


def ref_loss(q):
    return jnp.sum(
        dot_product_attention(q, jnp.asarray(full[1]), jnp.asarray(full[2]),
                              causal=True) ** 2
    )


ref_dq = jax.grad(ref_loss)(jnp.asarray(full[0]))
np.testing.assert_allclose(out_full, np.asarray(ref), rtol=1e-4, atol=1e-4)
np.testing.assert_allclose(dq_full, np.asarray(ref_dq), rtol=1e-3, atol=1e-3)
print("ULYSSES_PARITY_OK", flush=True)
shutdown_distributed()
'''


@pytest.mark.slow
def test_cross_process_ulysses_parity(tmp_path):
    """The ulysses all_to_all crosses process boundaries: 2 processes x 2
    fake devices, sequence axis of 4 spanning both; output AND dq must match
    the dense single-host reference on the same arrays."""
    import textwrap

    worker = tmp_path / "ulysses_worker.py"
    worker.write_text(textwrap.dedent(ULYSSES_WORKER))
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        env.pop("XLA_FLAGS", None)  # the worker sets device count itself
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker)],
                cwd=tmp_path,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"ulysses worker failed:\n{out}"
    assert all("ULYSSES_PARITY_OK" in out for out in outs)


ZERO1_WORKER = '''
"""2-process x 2-device ZeRO-1 worker: Trainer(partition_specs=) with Adam
moments sharded over a data axis that SPANS PROCESS BOUNDARIES — each
process holds half the moments, the update all-gather crosses processes.
Prints the per-epoch loss JSON and, on process 0, shard metadata."""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)

import numpy as np
import optax

jax.distributed.initialize(
    os.environ["COORDINATOR_ADDRESS"],
    int(os.environ["NUM_PROCESSES"]),
    int(os.environ["PROCESS_ID"]),
)

from distributed_pytorch_tpu import MaterializedDataset, ShardedLoader, Trainer
from distributed_pytorch_tpu.models import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import make_zero1_state_specs
from distributed_pytorch_tpu.training.train_step import create_train_state

mesh = make_mesh({"data": 4})
dataset = MaterializedDataset(256)
optimizer = optax.adam(1e-2)
probe = create_train_state(ToyRegressor(), optimizer, dataset.inputs[:1])
specs = make_zero1_state_specs(probe, mesh=mesh)
loader = ShardedLoader(
    dataset, 32, num_shards=jax.process_count(),
    shard_index=jax.process_index(),
)
snap = os.path.join(sys.argv[1], "zero1_snap.npz")
# save_every is irrelevant here: the worker drives epochs via _run_epoch and
# writes the snapshot explicitly below.
trainer = Trainer(
    ToyRegressor(), loader, optimizer, save_every=0,
    mesh=mesh, partition_specs=specs,
    snapshot_path=snap,
)
for epoch in range(2):
    loss = trainer._run_epoch(epoch)
    print(json.dumps({"epoch": epoch, "epoch_loss": loss}), flush=True)

# Snapshot of the SHARDED state: gathering the non-addressable Adam moments
# is a cross-host collective (checkpoint._to_host process_allgather). Write
# it, then reload into the sharded template and verify placement + values.
trainer._save_snapshot(1)
from distributed_pytorch_tpu.checkpoint import load_snapshot
import numpy as _np
restored, snap_meta = load_snapshot(snap, trainer.state)
restored = jax.device_put(restored, trainer.state_sharding)
def _local(tree):
    return [_np.asarray(m.addressable_shards[0].data)
            for m in jax.tree_util.tree_leaves(tree)]
values_match = all(
    _np.allclose(a, b, rtol=1e-6)
    for a, b in zip(_local(restored.opt_state[0].mu),
                    _local(trainer.state.opt_state[0].mu))
)
kmu = next(m for m in jax.tree_util.tree_leaves(restored.opt_state[0].mu) if m.ndim == 2)
print(json.dumps({
    "snapshot_epochs_run": int(snap_meta["epochs_run"]),
    "restored_mu_sharded": not kmu.sharding.is_fully_replicated,
    "restored_mu_values_match": values_match,
}), flush=True)

mu = jax.tree_util.tree_leaves(trainer.state.opt_state[0].mu)
kernel_mu = next(m for m in mu if m.ndim == 2)  # the (20, 1) kernel moment
print(json.dumps({
    "mu_fully_replicated": bool(kernel_mu.sharding.is_fully_replicated),
    "mu_local_rows": int(kernel_mu.addressable_shards[0].data.shape[0]),
    "mu_global_rows": int(kernel_mu.shape[0]),
}), flush=True)
'''


@pytest.mark.slow
def test_two_process_zero1_training(tmp_path):
    """ZeRO-1 across process boundaries: 2 procs x 2 devices, Adam moments
    sharded over the 4-way data axis (each process holds 2 of the 4 shard
    rows), loss identical to the replicated single-process run."""
    worker = tmp_path / "zero1_worker.py"
    worker.write_text(ZERO1_WORKER)
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            PYTHONPATH=REPO,
        )
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), str(tmp_path)],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"zero1 worker failed:\n{out}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    mp_losses = epoch_losses(outs[0])
    assert set(mp_losses) == {0, 1}

    # The moments must actually be distributed: 20-row kernel moment, 4-way
    # sharded -> 5 rows per device shard (2 such shards per process).
    meta = None
    for line in outs[0].splitlines():
        if "mu_fully_replicated" in line:
            meta = json.loads(line)
    assert meta is not None
    assert not meta["mu_fully_replicated"]
    assert meta["mu_global_rows"] == 20 and meta["mu_local_rows"] == 5

    # The sharded-state snapshot round-trip ran inside the workers: the
    # cross-host moment gather happened, and the reload re-sharded.
    assert '"snapshot_epochs_run": 2' in outs[0]
    assert '"restored_mu_sharded": true' in outs[0]
    assert '"restored_mu_values_match": true' in outs[0]

    # Replicated single-process reference over the same 4 virtual chips.
    single = subprocess.run(
        [
            sys.executable, "-c", SINGLE_ZERO1_REF,
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=300,
    )
    assert single.returncode == 0, single.stdout + single.stderr
    ref = {}
    for line in single.stdout.splitlines():
        if line.startswith("{"):
            record = json.loads(line)
            ref[record["epoch"]] = record["epoch_loss"]
    for epoch, loss in ref.items():
        np.testing.assert_allclose(mp_losses[epoch], loss, rtol=1e-5)


SINGLE_ZERO1_REF = '''
import json
import jax
jax.config.update("jax_platforms", "cpu")
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
import optax
from distributed_pytorch_tpu import MaterializedDataset, ShardedLoader, Trainer
from distributed_pytorch_tpu.models import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh

mesh = make_mesh({"data": 4})
loader = ShardedLoader(MaterializedDataset(256), 64)
trainer = Trainer(ToyRegressor(), loader, optax.adam(1e-2), save_every=0, mesh=mesh)
for epoch in range(2):
    loss = trainer._run_epoch(epoch)
    print(json.dumps({"epoch": epoch, "epoch_loss": loss}), flush=True)
'''


FSDP_WORKER = '''
"""2-process x 2-device FSDP (ZeRO-3) worker: Trainer(partition_specs=) with
the PARAMETERS sharded over a data axis that SPANS PROCESS BOUNDARIES — each
process holds a quarter of each sharded param, and XLA's all-gather-before-
use + reduce-scatter-of-grads cross processes every step. Prints per-epoch
loss JSON and shard metadata."""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)

import numpy as np
import optax

jax.distributed.initialize(
    os.environ["COORDINATOR_ADDRESS"],
    int(os.environ["NUM_PROCESSES"]),
    int(os.environ["PROCESS_ID"]),
)

from distributed_pytorch_tpu import MaterializedDataset, ShardedLoader, Trainer
from distributed_pytorch_tpu.models import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.parallel.partitioning import make_fsdp_specs
from distributed_pytorch_tpu.training.train_step import create_train_state

mesh = make_mesh({"data": 4})
dataset = MaterializedDataset(256)
optimizer = optax.adam(1e-2)
probe = create_train_state(ToyRegressor(), optimizer, dataset.inputs[:1])
# ZeRO-3 proper: params shard over the SAME axis the batch shards over.
specs = make_fsdp_specs(probe.params, mesh=mesh, axis="data")
loader = ShardedLoader(
    dataset, 32, num_shards=jax.process_count(),
    shard_index=jax.process_index(),
)
snap = os.path.join(sys.argv[1], "fsdp_snap.npz")
trainer = Trainer(
    ToyRegressor(), loader, optimizer, save_every=0,
    mesh=mesh, partition_specs=specs,
    snapshot_path=snap,
)
for epoch in range(2):
    loss = trainer._run_epoch(epoch)
    print(json.dumps({"epoch": epoch, "epoch_loss": loss}), flush=True)

# Snapshot the param-sharded state (gathering non-addressable PARAMS is a
# cross-host collective), reload into the sharded template, verify
# placement + values.
trainer._save_snapshot(1)
from distributed_pytorch_tpu.checkpoint import load_snapshot
restored, snap_meta = load_snapshot(snap, trainer.state)
restored = jax.device_put(restored, trainer.state_sharding)
def _local(tree):
    return [np.asarray(m.addressable_shards[0].data)
            for m in jax.tree_util.tree_leaves(tree)]
values_match = all(
    np.allclose(a, b, rtol=1e-6)
    for a, b in zip(_local(restored.params), _local(trainer.state.params))
)
kernel = next(
    p for p in jax.tree_util.tree_leaves(trainer.state.params) if p.ndim == 2
)
print(json.dumps({
    "snapshot_epochs_run": int(snap_meta["epochs_run"]),
    "restored_params_values_match": values_match,
    "kernel_fully_replicated": bool(kernel.sharding.is_fully_replicated),
    "kernel_local_rows": int(kernel.addressable_shards[0].data.shape[0]),
    "kernel_global_rows": int(kernel.shape[0]),
}), flush=True)
'''


@pytest.mark.slow
def test_two_process_fsdp_training(tmp_path):
    """FSDP/ZeRO-3 across process boundaries (VERDICT r04 item 4's
    cross-process leg): 2 procs x 2 devices, every 4-divisible parameter
    sharded over the 4-way data axis (each process holds 2 of the 4 shard
    rows), loss identical to the replicated single-process run."""
    worker = tmp_path / "fsdp_worker.py"
    worker.write_text(FSDP_WORKER)
    port = free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            PYTHONPATH=REPO,
        )
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), str(tmp_path)],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"fsdp worker failed:\n{out}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    mp_losses = epoch_losses(outs[0])
    assert set(mp_losses) == {0, 1}

    meta = None
    for line in outs[0].splitlines():
        if "kernel_fully_replicated" in line:
            meta = json.loads(line)
    assert meta is not None
    assert not meta["kernel_fully_replicated"]
    assert meta["kernel_global_rows"] == 20 and meta["kernel_local_rows"] == 5
    assert meta["snapshot_epochs_run"] == 2
    assert meta["restored_params_values_match"] is True

    # Replicated single-process reference over the same 4 virtual chips:
    # FSDP is a memory layout, not a different algorithm — losses match.
    single = subprocess.run(
        [sys.executable, "-c", SINGLE_ZERO1_REF],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=300,
    )
    assert single.returncode == 0, single.stdout + single.stderr
    ref = {}
    for line in single.stdout.splitlines():
        if line.startswith("{"):
            record = json.loads(line)
            ref[record["epoch"]] = record["epoch_loss"]
    for epoch, loss in ref.items():
        np.testing.assert_allclose(mp_losses[epoch], loss, rtol=1e-5)
