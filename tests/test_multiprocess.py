"""True multi-process training tests (SURVEY.md §4: "multi-process without a
cluster") — N local processes rendezvous through ``jax.distributed``, each
with its own virtual CPU devices, exercising the full rung-4 path: env
bootstrap, per-process loader shards, global-batch assembly, process-0-only
snapshotting, and loss parity against the serial rung.

Each subprocess runs ``examples/multihost_pod.py`` exactly as a pod host
would; this file is the automated twin of the verify-skill's manual rung-4
drive.
"""

import json
import os
import re
import subprocess
import sys
import socket

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_workers(n_procs, args, *, fake_devices, port, extra_env=None):
    """Run n_procs copies of the rung-4 example; return their stdouts."""
    procs = []
    for pid in range(n_procs):
        env = dict(
            os.environ,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES=str(n_procs),
            PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        env.pop("XLA_FLAGS", None)  # the example sets device count itself
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    os.path.join(REPO, "examples", "multihost_pod.py"),
                    *args,
                    "--fake_devices",
                    str(fake_devices),
                ],
                cwd=REPO,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out}"
    finally:
        # A hung rendezvous must not leak workers (they hold the coordinator
        # port and would poison subsequent runs).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def epoch_losses(text):
    """epoch -> epoch_loss parsed from the metric JSON lines."""
    losses = {}
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "epoch_loss" in record:
                losses[int(record["epoch"])] = record["epoch_loss"]
    return losses


@pytest.mark.slow
def test_two_process_parity_and_single_writer(tmp_path):
    """2 processes x 4 fake chips == the 8-chip single-process run, epoch for
    epoch; snapshot written once (by global process 0)."""
    snap = tmp_path / "mp.npz"
    outs = launch_workers(
        2,
        ["2", "1", "--snapshot_path", str(snap)],
        fake_devices=4,
        port=free_port(),
    )
    assert snap.exists()
    mp_losses = epoch_losses(outs[0]) or epoch_losses(outs[1])
    assert set(mp_losses) == {0, 1}

    # Reference: the same global run in ONE process over 8 virtual chips.
    single = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "examples", "multihost_pod.py"),
            "2", "1",
            "--snapshot_path", str(tmp_path / "sp.npz"),
            "--fake_devices", "8",
        ],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert single.returncode == 0, single.stdout + single.stderr
    sp_losses = epoch_losses(single.stdout)
    for epoch, loss in sp_losses.items():
        np.testing.assert_allclose(mp_losses[epoch], loss, rtol=1e-5)

    # Single-writer contract: only process 0 printed the snapshot banner.
    writers = sum("snapshot saved" in out.lower() for out in outs)
    assert writers == 1, f"{writers} processes claimed the snapshot write"


@pytest.mark.slow
def test_two_process_snapshot_resume(tmp_path):
    """Kill-and-relaunch elasticity across processes: second launch resumes
    from the snapshot's epoch offset (reference multigpu_torchrun.py:30-40)."""
    snap = tmp_path / "resume.npz"
    launch_workers(
        2, ["1", "1", "--snapshot_path", str(snap)], fake_devices=2,
        port=free_port(),
    )
    assert snap.exists()
    outs = launch_workers(
        2, ["3", "1", "--snapshot_path", str(snap)], fake_devices=2,
        port=free_port(),
    )
    combined = "\n".join(outs)
    assert re.search(r"Resuming training from snapshot at Epoch 1", combined)
    losses = epoch_losses(outs[0]) or epoch_losses(outs[1])
    assert set(losses) == {1, 2}  # epochs 1..2 ran; epoch 0 skipped
