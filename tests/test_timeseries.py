"""Performance-observatory unit/property tests: the TSDB's exactness
contract and memory bound, the roofline math, and the stratified CUSUM
regression detector. Pure Python — no JAX, no engine (the engine-level
integration drill lives in ``tests/test_perfwatch.py``)."""

import math

import pytest

from distributed_pytorch_tpu.obs.regress import RegressionDetector
from distributed_pytorch_tpu.obs.registry import MetricsRegistry
from distributed_pytorch_tpu.obs.roofline import (
    HBM_BYTES_PER_SEC,
    RooflineModel,
    hbm_bandwidth_per_chip,
    roofline_point,
)
from distributed_pytorch_tpu.obs.timeseries import (
    DEFAULT_RESOLUTIONS,
    TimeSeriesDB,
    sparkline,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def make_db(**kw):
    clock = FakeClock()
    kw.setdefault("raw_capacity", 64)
    kw.setdefault("resolutions", ((5.0, 12), (20.0, 24)))
    db = TimeSeriesDB(clock=clock, **kw)
    return db, clock


# --------------------------------------------------------------- TSDB core


class TestTimeSeriesDB:
    def test_counter_rate_exact_within_raw_window(self):
        db, clock = make_db()
        import random

        rng = random.Random(0)
        shadow = []
        total = 0.0
        for _ in range(50):
            total += rng.uniform(0, 5)
            t = clock.advance(0.5)
            db.record("toks", total, kind="counter", now=t)
            shadow.append((t, total))
        window = 10.0
        since = clock.t - window
        win = [p for p in shadow if p[0] >= since]
        expect = (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])
        got = db.rate("toks", window, now=clock.t)
        assert got == pytest.approx(expect, rel=1e-12)

    def test_rate_exact_after_raw_ring_wrap(self):
        """The headline exactness contract: once the window outgrows the
        wrapped raw ring, rate() answers from downsampled buckets — and
        because buckets keep REAL first/last samples, the answer equals
        the brute-force delta over the same covered span of the full
        (unbounded) shadow history."""
        db, clock = make_db(raw_capacity=16, resolutions=((5.0, 1000),))
        import random

        rng = random.Random(1)
        shadow = []
        total = 0.0
        for _ in range(400):  # raw keeps 16 samples = 8s; run 200s
            total += rng.uniform(0, 3)
            t = clock.advance(0.5)
            db.record("toks", total, kind="counter", now=t)
            shadow.append((t, total))
        window = 100.0  # far beyond raw retention -> bucket path
        since = clock.t - window
        # Brute force over the documented covered span: all samples in
        # buckets intersecting [since, now] (bucket width 5s).
        covered = [
            p for p in shadow
            if math.floor(p[0] / 5.0) * 5.0 >= since - 5.0
        ]
        expect = (
            (covered[-1][1] - covered[0][1])
            / (covered[-1][0] - covered[0][0])
        )
        got = db.rate("toks", window, now=clock.t)
        assert got == pytest.approx(expect, rel=1e-12)

    def test_avg_over_time_exact_after_wrap(self):
        db, clock = make_db(raw_capacity=16, resolutions=((5.0, 1000),))
        import random

        rng = random.Random(2)
        shadow = []
        for _ in range(400):
            t = clock.advance(0.5)
            v = rng.gauss(10.0, 2.0)
            db.record("g", v, kind="gauge", now=t)
            shadow.append((t, v))
        window = 100.0
        since = clock.t - window
        covered = [
            p for p in shadow
            if math.floor(p[0] / 5.0) * 5.0 >= since - 5.0
        ]
        expect = sum(v for _t, v in covered) / len(covered)
        got = db.avg_over_time("g", window, now=clock.t)
        assert got == pytest.approx(expect, rel=1e-12)

    def test_quantile_exact_over_raw(self):
        db, clock = make_db()
        import random

        rng = random.Random(3)
        vals = []
        for _ in range(40):
            t = clock.advance(0.5)
            v = rng.uniform(0, 100)
            db.record("g", v, kind="gauge", now=t)
            vals.append(v)
        window = 10.0
        since = clock.t - window
        win = sorted(
            v for t, v in zip(
                [0.5 * (i + 1) for i in range(40)], vals
            ) if t >= since
        )
        got = db.quantile_over_time("g", 0.5, window, now=clock.t)
        assert got == win[min(len(win) - 1, int(0.5 * len(win)))]

    def test_memory_flat_over_10k_steps(self):
        """Every ring wraps, then memory NEVER grows again — the fixed-
        memory property the module docstring promises."""
        db, clock = make_db(raw_capacity=32, resolutions=((2.0, 8), (8.0, 8)))
        for i in range(2000):  # 1000s: wraps raw (16s), 2s (16s), 8s (64s)
            t = clock.advance(0.5)
            db.record("a", float(i), kind="counter", now=t)
            db.record("b", math.sin(i / 10.0), kind="gauge", now=t)
        plateau = db.memory_bytes()
        peak = plateau
        for i in range(10000):
            t = clock.advance(0.5)
            db.record("a", 2000.0 + i, kind="counter", now=t)
            db.record("b", math.cos(i / 10.0), kind="gauge", now=t)
            peak = max(peak, db.memory_bytes())
        assert peak == plateau, (peak, plateau)
        assert db.samples_taken == 0  # record() is not the sampling tick
        assert db.status()["memory_bytes"] == db.memory_bytes()

    def test_sample_tracks_registry_scalars_not_reservoirs(self):
        db, clock = make_db()
        reg = MetricsRegistry(namespace="t")
        c = reg.counter("reqs_total")
        g = reg.gauge("depth")
        db.track_registry(reg)
        c.inc(3)
        g.set(7.0)
        db.sample(now=clock.advance(1.0), step_wall_seconds=0.002)
        c.inc(2)
        db.sample(now=clock.advance(1.0), step_wall_seconds=0.003)
        assert db.samples_taken == 2
        assert db.kind_of("t_reqs_total") == "counter"
        assert db.latest("t_reqs_total")[1] == 5.0
        assert db.latest("t_depth")[1] == 7.0
        assert db.latest("step_wall_seconds")[1] == 0.003
        # scalars(): the cheap per-step read — counters+gauges only,
        # qualified exactly like snapshot().
        scal = reg.scalars()
        snap = reg.snapshot()
        assert scal["counters"] == snap["counters"]
        assert scal["gauges"] == snap["gauges"]
        assert set(scal) == {"counters", "gauges"}

    def test_merge_fleet_counter_rate_sums(self):
        docs = []
        per_engine_rates = []
        for k in range(2):
            db, clock = make_db(resolutions=((5.0, 100),))
            db.wall_epoch = 0.0  # align both engines on one timeline
            total = 0.0
            for i in range(40):
                total += 2.0 + k  # engine 0: 2 tok/sample, engine 1: 3
                db.record(
                    "toks", total, kind="counter", now=clock.advance(0.5)
                )
            docs.append(db.export_state())
            per_engine_rates.append(db.rate("toks", 15.0, now=clock.t))
        merged = TimeSeriesDB.merge(docs)
        rows = merged["series"]["toks"]["rings"]["5.0"]
        # Fully-covered interior buckets: cumulative endpoints summed.
        assert merged["series"]["toks"]["kind"] == "counter"
        interior = rows[1]
        first_v, last_v = interior[2], interior[4]
        span = interior[3] - interior[1]
        assert span > 0
        fleet_rate = (last_v - first_v) / span
        assert fleet_rate == pytest.approx(
            sum(per_engine_rates), rel=0.25
        )

    def test_points_counter_plots_rate(self):
        db, clock = make_db()
        for i in range(10):
            db.record(
                "toks", 10.0 * i, kind="counter", now=clock.advance(1.0)
            )
        pts = db.points("toks")
        assert len(pts) == 9
        assert all(v == pytest.approx(10.0) for _t, v in pts)

    def test_series_kind_conflict_raises(self):
        db, clock = make_db()
        db.record("x", 1.0, kind="counter", now=clock.advance(1.0))
        with pytest.raises(ValueError):
            db.record("x", 1.0, kind="gauge", now=clock.advance(1.0))

    def test_dump_shape(self):
        db, clock = make_db()
        for i in range(5):
            db.record("g", float(i), kind="gauge", now=clock.advance(1.0))
        doc = db.dump(["g", "missing"])
        assert set(doc["series"]) == {"g"}
        assert doc["series"]["g"]["kind"] == "gauge"
        assert len(doc["series"]["g"]["points"]) == 5
        # Wall-epoch shift applied to every timestamp.
        assert doc["series"]["g"]["points"][0][0] == pytest.approx(
            db.wall_epoch + 1.0
        )

    def test_default_resolutions_memory_docstring_bound(self):
        # ~30 KB/series at the defaults — keep the docstring honest.
        per_series = 32 * (
            2 * 512 + 9 * sum(c for _s, c in DEFAULT_RESOLUTIONS)
        )
        assert per_series < 600_000


class TestSparkline:
    def test_empty_is_spaces(self):
        assert sparkline([], width=8) == " " * 8

    def test_flat_is_mid_height(self):
        out = sparkline([5.0, 5.0, 5.0], width=8)
        assert out.strip() == "▄▄▄"

    def test_resamples_to_width(self):
        out = sparkline(list(range(100)), width=16)
        assert len(out) == 16
        assert out[0] == "▁" and out[-1] == "█"


# ---------------------------------------------------------------- roofline


class TestRoofline:
    def test_point_bandwidth_bound(self):
        p = roofline_point(
            flops=1e9, hbm_bytes=1e9, peak_flops=100e12, peak_bw=800e9
        )
        assert p["bound"] == "bandwidth"
        assert p["floor_s"] == pytest.approx(1e9 / 800e9)
        assert p["intensity_flops_per_byte"] == pytest.approx(1.0)

    def test_point_compute_bound(self):
        p = roofline_point(
            flops=1e12, hbm_bytes=1e6, peak_flops=100e12, peak_bw=800e9
        )
        assert p["bound"] == "compute"
        assert p["floor_s"] == pytest.approx(1e12 / 100e12)

    def test_point_degenerate(self):
        p = roofline_point(0.0, 0.0, 100e12, 800e9)
        assert p["bound"] == "unknown" and p["floor_s"] == 0.0

    def test_bandwidth_table_lookup(self):
        class Dev:
            device_kind = "TPU v5 lite"

        assert hbm_bandwidth_per_chip(Dev()) == HBM_BYTES_PER_SEC["v5 lite"]

        class Unknown:
            device_kind = "mystery"

        assert hbm_bandwidth_per_chip(Unknown()) == 819e9

    def test_model_joins_ledger_and_tsdb(self):
        class Rec:
            def __init__(self, flops, argb, outb, tmpb, calls):
                self.name = "prog"
                self.flops = flops
                self.argument_bytes = argb
                self.output_bytes = outb
                self.temp_bytes = tmpb
                self.calls = calls

        class Ledger:
            programs = {
                "a": Rec(1e6, 8e6, 1e6, 1e6, 90),
                "b": Rec(0.0, 1e6, 1e6, 0.0, 10),  # analytic fallback
            }

        db, clock = make_db()
        for _ in range(10):
            db.record(
                "step_wall_seconds", 0.001, kind="gauge",
                now=clock.advance(0.1),
            )
        m = RooflineModel(
            Ledger(), db, peak_flops=100e12, peak_bw=800e9,
            fallback_flops_fn=lambda r: 2e6, window_s=60.0,
        )
        rows = m.program_rows()
        assert rows[0]["calls"] == 90
        assert rows[1]["flops_source"] == "analytic"
        assert rows[1]["flops"] == 2e6
        floor = m.step_floor_s()
        assert floor == pytest.approx(
            (rows[0]["floor_s"] * 90 + rows[1]["floor_s"] * 10) / 100
        )
        rep = m.report()
        assert rep["measured_step_s"] == pytest.approx(0.001)
        assert 0.0 < rep["achieved_fraction"] <= 1.0
        assert rep["dominant_bound"] == "bandwidth"

    def test_gauges_serve_from_ttl_cache(self):
        class Ledger:
            programs = {}

        m = RooflineModel(
            Ledger(), None, peak_flops=1e12, peak_bw=1e12, cache_ttl_s=3600
        )
        reg = MetricsRegistry(namespace="t")
        m.register_into(reg)
        snap = reg.snapshot()
        assert snap["gauges"]["t_roofline_step_floor_seconds"] == 0.0
        # Mutating the ledger does NOT move the cached gauge inside TTL…
        class Rec:
            name, flops, calls = "p", 1e9, 1
            argument_bytes = output_bytes = temp_bytes = 1e6

        Ledger.programs = {"p": Rec()}
        assert reg.snapshot()["gauges"]["t_roofline_step_floor_seconds"] == 0.0
        # …but report() always recomputes exactly.
        assert m.report()["step_floor_s"] > 0.0


# --------------------------------------------------- regression detection


def feed_clean(det, n, *, rows=4, wall=0.004, jitter=0.0002, seed=0,
               phases=None):
    import random

    rng = random.Random(seed)
    ev = None
    for _ in range(n):
        w = wall + rng.uniform(-jitter, jitter)
        ph = dict(phases or {"dispatch": w * 0.5, "schedule": w * 0.2})
        ev = det.observe(
            step_wall_seconds=w, tpot_step_seconds=w / rows,
            decode_rows=rows, prefill_tokens=0, phases=ph,
        )
    return ev


class TestRegressionDetector:
    def test_quiet_at_steady_state(self):
        det = RegressionDetector()
        feed_clean(det, 200)
        assert det.alerts == 0 and not det.firing

    def test_fires_on_sustained_shift_and_blames_phase(self):
        det = RegressionDetector()
        feed_clean(det, 60)
        fired_at = None
        for i in range(20):
            w = 0.004 + 0.05  # persistent dispatch stall
            ev = det.observe(
                step_wall_seconds=w, tpot_step_seconds=w / 4,
                decode_rows=4, prefill_tokens=0,
                phases={"dispatch": 0.002 + 0.05, "schedule": 0.0008},
            )
            if ev is not None:
                fired_at = i + 1
                break
        assert fired_at is not None and fired_at <= 4, fired_at
        assert det.alerts == 1 and det.firing
        event = det.events[-1]
        assert event["attributed_phase"] == "dispatch"
        assert event["decode_rows"] == 4
        assert event["stratum_samples"] > 0
        det.acknowledge()
        assert not det.firing and det.alerts == 1

    def test_single_spike_never_fires(self):
        det = RegressionDetector()
        feed_clean(det, 60)
        ev = det.observe(
            step_wall_seconds=10.0, tpot_step_seconds=2.5,
            decode_rows=4, prefill_tokens=0,
            phases={"dispatch": 9.0, "schedule": 0.5},
        )
        assert ev is None
        feed_clean(det, 30)
        assert det.alerts == 0

    def test_load_shift_between_strata_stays_quiet(self):
        """The stratification headline: traffic moving from 2-row steps
        (fast) to 8-row steps (slow) is a LOAD change, not a regression —
        an unstratified detector would page on it."""
        det = RegressionDetector()
        feed_clean(det, 60, rows=2, wall=0.002)
        feed_clean(det, 60, rows=8, wall=0.008)  # 4x the level, new stratum
        feed_clean(det, 60, rows=2, wall=0.002)
        assert det.alerts == 0
        assert sorted(det.state()["strata"]) == [2, 8]

    def test_prefill_steps_skipped(self):
        det = RegressionDetector()
        det.observe(
            step_wall_seconds=0.1, decode_rows=4, prefill_tokens=32
        )
        det.observe(step_wall_seconds=0.1, decode_rows=0)
        assert det.steps == 2 and det.skipped_steps == 2
        assert det.state()["strata"] == []

    def test_refires_after_second_shift(self):
        det = RegressionDetector()
        feed_clean(det, 60)
        feed_clean(
            det, 20, wall=0.054,
            phases={"dispatch": 0.052, "schedule": 0.0008},
        )
        assert det.alerts == 1  # rebaselined onto the new level
        feed_clean(
            det, 20, wall=0.104,
            phases={"dispatch": 0.102, "schedule": 0.0008},
        )
        assert det.alerts == 2

    def test_registry_export(self):
        det = RegressionDetector()
        reg = MetricsRegistry(namespace="t")
        det.register_into(reg)
        feed_clean(det, 60)
        feed_clean(
            det, 20, wall=0.054,
            phases={"dispatch": 0.052, "schedule": 0.0008},
        )
        snap = reg.snapshot()
        assert snap["counters"]["t_perf_regressions_total"] == 1.0
        assert snap["gauges"]["t_perf_regression_firing"] == 1.0

    def test_max_strata_bounds_memory(self):
        det = RegressionDetector(max_strata=4)
        for rows in range(1, 20):
            det.observe(
                step_wall_seconds=0.001 * rows, decode_rows=rows,
                prefill_tokens=0,
            )
        assert len(det.state()["strata"]) == 4
