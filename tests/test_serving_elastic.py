"""Elastic serving tests: drain/snapshot/restore for the inference engine.

The headline drill: arm one of the serving fault kinds, kill or drain the
engine mid-step, restore the snapshot into a FRESH engine, and assert every
request admitted before the fault finishes with greedy output token-identical
to an uninterrupted run — across every prefix_cache x overlap x speculative
combination. Restore is re-admission (prompt + generated tokens re-prefilled
through the prefix cache), so parity here exercises the whole determinism
story: fold-index sampling, pending-token rollback, CoW page sharing.

Also covers the satellites: snapshot-codec round-trips over randomized
mid-flight states, per-request deadlines (including rebasing across
restore), and ``close()`` / context-manager teardown with leak detection.
All on CPU (conftest pins JAX_PLATFORMS=cpu).
"""

import dataclasses
import itertools
import json
import os
import random

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu import chaos
from distributed_pytorch_tpu.models.transformer import TransformerLM
from distributed_pytorch_tpu.obs import Tracer
from distributed_pytorch_tpu.serving import (
    DrainController,
    EngineDraining,
    EngineSnapshot,
    InferenceEngine,
    SamplingParams,
    drain_engine,
    restore_engine,
    snapshot_engine,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_chaos_plan():
    """Arming tests set the env var themselves; reset the cached plan on
    both sides so no plan leaks across tests (or from the environment)."""
    chaos._reset()
    yield
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


def tiny_lm(n_layers=2, **kw):
    return TransformerLM(
        vocab_size=48, d_model=16, n_layers=n_layers, n_heads=2, d_ff=32,
        dtype=jnp.float32, **kw,
    )


@pytest.fixture(scope="module")
def target_and_params():
    # One layer (not the two the parity modules use): this module builds
    # ~80 engines and each one re-jits its programs, so compile time — not
    # step count — dominates its wall clock.
    model = tiny_lm(n_layers=1)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_and_params():
    model = tiny_lm(n_layers=1)
    params = model.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


# Five prompts on two slots: real queue pressure, and the first two share a
# page-aligned prefix so snapshots cover CoW/prefix-cache-shared pages.
PROMPTS = [
    [5, 7, 11, 2, 9, 3],
    [5, 7, 11, 2, 1],
    [2, 2, 3, 17, 40],
    [6, 1, 9, 9],
]
MAX_NEW = 6
ENGINE_KW = dict(
    max_slots=2, max_seq_len=32, page_size=4, token_budget=16,
    max_prefill_chunk=8, debug=True,
)


def make_engine(model, params, *, draft=None, **kw):
    opts = dict(ENGINE_KW)
    opts.update(kw)
    if draft is not None:
        dmodel, dparams = draft
        opts.update(draft_model=dmodel, draft_params=dparams)
    return InferenceEngine(model, params, **opts)


def submit_all(eng, prompts=PROMPTS, **params_kw):
    return [
        eng.submit(p, SamplingParams(max_new_tokens=MAX_NEW, **params_kw))
        for p in prompts
    ]


def counters(eng):
    return eng.registry.snapshot()["counters"]


@pytest.fixture(scope="module")
def ref_outputs(target_and_params):
    """Greedy outputs from one uninterrupted run. Output streams are
    batch-, slot-, and toggle-invariant (the repo's parity tests prove it),
    so a single reference serves every drill combination."""
    model, params = target_and_params
    eng = make_engine(model, params)
    ids = submit_all(eng)
    eng.run()
    return {i: eng.poll(i).generated for i in ids}


def arm(plan):
    os.environ[chaos.ENV_VAR] = json.dumps(plan)
    chaos._reset()


def disarm():
    os.environ.pop(chaos.ENV_VAR, None)
    chaos._reset()


# -------------------------------------------------------------- chaos drill


FAULT_SPECS = {
    # No notice: recovery point is the rolling snapshot. mode="raise" keeps
    # the kill in-process (the hard mode — real SIGKILL — is exercised by
    # tools/chaos_smoke.sh serving).
    "kill_mid_verify": {"kind": "kill_mid_verify", "at_step": 4,
                        "mode": "raise"},
    # Notice kinds: hard mode sends a real SIGTERM to this process; the
    # DrainController handler turns it into a clean between-steps drain.
    "drain_mid_prefill": {"kind": "drain_mid_prefill", "at_step": 2},
    "reclaim_under_queue_pressure": {
        "kind": "reclaim_under_queue_pressure", "min_queue": 2,
    },
}

COMBOS = list(itertools.product([True, False], repeat=3))


class TestChaosDrill:
    """The acceptance invariant: fault mid-step, restore on a fresh engine,
    every admitted request token-identical to the uninterrupted run."""

    @pytest.mark.parametrize(
        "prefix_cache,overlap,speculative", COMBOS,
        ids=[f"pc{int(a)}-ov{int(b)}-sp{int(c)}" for a, b, c in COMBOS],
    )
    @pytest.mark.parametrize("kind", sorted(FAULT_SPECS))
    def test_fault_then_restore_token_parity(
        self, tmp_path, target_and_params, draft_and_params, ref_outputs,
        kind, prefix_cache, overlap, speculative,
    ):
        model, params = target_and_params
        draft = draft_and_params if speculative else None
        snap_path = str(tmp_path / "snap.json")

        arm({"faults": [FAULT_SPECS[kind]]})
        eng = make_engine(
            model, params, draft=draft, prefix_cache=prefix_cache,
            overlap=overlap,
        )
        ids = submit_all(eng)
        faulted = False
        try:
            with DrainController(
                eng, snapshot_path=snap_path, install_signal=True
            ) as ctl:
                ctl.drive(snapshot_every=2)
        except chaos.InjectedFault as e:
            assert e.kind == kind
            faulted = True
        disarm()

        if kind == "kill_mid_verify":
            # Engine died with no notice: recover from the last rolling
            # snapshot (strictly older than the fault).
            assert faulted, "kill_mid_verify never fired"
            snap = EngineSnapshot.load(snap_path)
        else:
            # Notice kinds drain cleanly: no exception, snapshot written,
            # admission closed, drain counted.
            assert not faulted and ctl.drained
            snap = ctl.snapshot
            assert counters(eng)["serving_drains_total"] == 1
            with pytest.raises(EngineDraining):
                eng.submit([1, 2], SamplingParams(max_new_tokens=2))

        assert snap.requests, "drill degenerate: nothing left to recover"
        assert snap == EngineSnapshot.load(snap_path)

        fresh = make_engine(
            model, params, draft=draft, prefix_cache=prefix_cache,
            overlap=overlap,
        )
        restored = restore_engine(fresh, snap)
        fresh.run()
        c = counters(fresh)
        assert c["serving_restores_total"] == 1
        assert c["serving_requests_recovered_total"] == len(restored)

        # Union parity: ids still live at the snapshot finish on the fresh
        # engine; ids that finished before it are polled where they died.
        for i in ids:
            src = fresh if i in restored else eng
            st = src.poll(i)
            assert st.state == "finished", (kind, i, st.state)
            assert st.generated == ref_outputs[i], (
                kind, prefix_cache, overlap, speculative, i,
            )
        assert fresh.allocator.num_allocated == 0
        fresh.allocator.check_invariants()


# ------------------------------------------------------- drain + codec


class TestDrainAndCodec:
    def test_clean_drain_restore_parity(self, target_and_params, ref_outputs):
        model, params = target_and_params
        eng = make_engine(model, params)
        ids = submit_all(eng)
        for _ in range(3):
            eng.step()

        snap = drain_engine(eng)
        # The codec round-trips exactly (frozen dataclasses + JSON).
        assert EngineSnapshot.from_json(snap.to_json()) == snap
        assert snap.version == 1 and snap.next_id == len(ids)
        assert counters(eng)["serving_drains_total"] == 1
        with pytest.raises(EngineDraining):
            eng.submit([3], SamplingParams(max_new_tokens=1))
        assert counters(eng)["serving_admission_rejected_draining_total"] == 1

        # KV metadata: committed counts bounded by the trimmed token count,
        # trie keys bounded by the full pages of the prefix (the trie only
        # holds pages the cache has registered so far).
        for rec in snap.requests:
            tokens = len(rec.prompt) + len(rec.generated)
            assert 0 <= rec.kv_committed <= tokens
            assert len(rec.trie_keys) <= tokens // ENGINE_KW["page_size"]

        fresh = make_engine(model, params)
        restored = restore_engine(fresh, snap)
        fresh.run()
        for i in ids:
            src = fresh if i in restored else eng
            assert src.poll(i).generated == ref_outputs[i]
        # next_id carried over: new requests never outrank recovered ones.
        assert fresh.submit([1, 2], SamplingParams(max_new_tokens=1)) >= len(
            ids
        )

    def test_drain_idle_engine_is_empty_snapshot(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        snap = drain_engine(eng)
        assert snap.requests == ()
        assert eng.drains == 1

    def test_restore_refuses_fingerprint_mismatch(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        submit_all(eng, prompts=PROMPTS[:1])
        snap = drain_engine(eng)
        fresh = make_engine(model, params)
        bad = dataclasses.replace(snap, top_k=7)
        with pytest.raises(ValueError, match="top_k"):
            restore_engine(fresh, bad)
        with pytest.raises(ValueError, match="version"):
            EngineSnapshot.from_json(
                snap.to_json().replace('"version":1', '"version":99')
            )

    def test_restore_refuses_duplicate_ids(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        submit_all(eng, prompts=PROMPTS[:2])
        snap = drain_engine(eng)
        fresh = make_engine(model, params)
        restore_engine(fresh, snap)
        with pytest.raises(ValueError, match="already"):
            restore_engine(fresh, snap)

    def test_restore_emits_tracer_events(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        submit_all(eng, prompts=PROMPTS[:2])
        snap = drain_engine(eng)
        tr = Tracer()
        fresh = make_engine(model, params, tracer=tr)
        restored = restore_engine(fresh, snap)
        names = [e.get("name") for e in tr.events]
        assert "restore" in names
        assert tr.spans_opened == len(restored)


# ----------------------------------------------- property: random states


class TestSnapshotRoundTripProperty:
    """Randomized engine states — mid-prefill chunks, live overlapped
    dispatches (pending rollback), speculative rows, CoW-shared prefix
    pages — must codec-round-trip and restore to token parity, leaking
    nothing on the drained side."""

    @pytest.mark.parametrize("trial", range(3))
    def test_round_trip(
        self, trial, target_and_params, draft_and_params,
    ):
        rng = random.Random(1000 + trial)
        model, params = target_and_params
        speculative = rng.random() < 0.5
        kw = dict(
            prefix_cache=rng.random() < 0.7,
            overlap=rng.random() < 0.7,
            draft=draft_and_params if speculative else None,
        )
        prompts = rng.sample(PROMPTS, rng.randint(2, len(PROMPTS)))
        # Duplicate one prompt: identical prefixes force shared pages (and
        # CoW splits once the copies diverge... they don't under greedy, so
        # sharing persists into the snapshot).
        prompts.append(list(prompts[0]))

        ref_eng = make_engine(model, params, **kw)
        ref_ids = submit_all(ref_eng, prompts=prompts)
        ref_eng.run()
        ref = {i: ref_eng.poll(i).generated for i in ref_ids}

        eng = make_engine(model, params, **kw)
        ids = submit_all(eng, prompts=prompts)
        for _ in range(rng.randint(1, 6)):
            if eng.scheduler.has_work or eng._inflight is not None:
                eng.step()

        # Snapshot WITHOUT finishing the in-flight dispatch: pending
        # placeholder tokens must be rolled back, not serialized.
        snap = snapshot_engine(eng)
        assert EngineSnapshot.from_json(snap.to_json()) == snap
        for rec in snap.requests:
            assert -1 not in rec.generated  # PENDING_TOKEN never escapes

        finished_before = [i for i in ids if eng.poll(i).state == "finished"]
        eng.close()  # asserts zero leaked pages via allocator gauges

        fresh = make_engine(model, params, **kw)
        restored = restore_engine(fresh, snap)
        assert sorted(restored + finished_before) == sorted(ids)
        fresh.run()
        for i in ids:
            src = fresh if i in restored else eng
            assert src.poll(i).generated == ref[i], (trial, i)
        fresh.close()


# ------------------------------------------------------------- deadlines


class TestDeadlines:
    def test_deadline_zero_expires_before_any_token(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        doomed = eng.submit(
            PROMPTS[0], SamplingParams(max_new_tokens=MAX_NEW, deadline_s=0.0)
        )
        alive = eng.submit(PROMPTS[1], SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.poll(doomed).state == "expired"
        assert eng.poll(doomed).generated == []
        assert eng.poll(alive).state == "finished"
        assert counters(eng)["serving_requests_expired_total"] == 1
        assert eng.allocator.num_allocated == 0

    def test_mid_flight_expiry_frees_pages(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        rid = eng.submit(
            PROMPTS[0],
            SamplingParams(max_new_tokens=MAX_NEW, deadline_s=3600.0),
        )
        for _ in range(3):
            eng.step()
        req = next(r for r in eng.scheduler.running if r.req_id == rid)
        assert req.n_generated > 0
        req.submit_time -= 7200.0  # age the request past its deadline
        eng.run()
        st = eng.poll(rid)
        assert st.state == "expired"
        assert 0 < len(st.generated) < MAX_NEW  # partial output retained
        assert eng.allocator.num_allocated == 0

    def test_deadline_rebased_across_restore(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        eng.submit(
            PROMPTS[0],
            SamplingParams(max_new_tokens=MAX_NEW, deadline_s=3600.0),
        )
        eng.step()
        snap = drain_engine(eng)
        (rec,) = snap.requests
        assert rec.deadline_s == 3600.0 and rec.age_s >= 0.0

        # A request restored OLDER than its deadline expires immediately:
        # restore rebases submit_time to (now - age_s), not to now.
        stale = dataclasses.replace(
            snap, requests=(dataclasses.replace(rec, age_s=7200.0),)
        )
        fresh = make_engine(model, params)
        (rid,) = restore_engine(fresh, stale)
        fresh.run()
        assert fresh.poll(rid).state == "expired"
        assert counters(fresh)["serving_requests_expired_total"] == 1


# ---------------------------------------------------------- close/teardown


class TestClose:
    def test_close_cancels_live_requests_and_quiesces(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        ids = submit_all(eng)
        for _ in range(3):
            eng.step()
        eng.close()
        states = {eng.poll(i).state for i in ids}
        assert states <= {"finished", "cancelled"} and "cancelled" in states
        assert eng.allocator.num_allocated == 0
        assert counters(eng)["serving_requests_cancelled_total"] > 0
        with pytest.raises(EngineDraining):
            eng.submit([1], SamplingParams(max_new_tokens=1))
        eng.close()  # idempotent

    def test_context_manager_drains_overlap_pipeline(self, target_and_params):
        model, params = target_and_params
        with make_engine(model, params, overlap=True) as eng:
            submit_all(eng, prompts=PROMPTS[:2])
            for _ in range(4):
                eng.step()
            assert eng._inflight is not None or eng.scheduler.has_work
        assert eng._inflight is None
        assert eng.allocator.num_allocated == 0

    def test_close_flushes_trace(self, tmp_path, target_and_params):
        model, params = target_and_params
        path = str(tmp_path / "trace.json")
        with make_engine(
            model, params, tracer=Tracer(), trace_path=path
        ) as eng:
            submit_all(eng, prompts=PROMPTS[:2])
            eng.run()
        with open(path) as f:
            trace = json.load(f)
        assert any(
            e.get("name") == "step" for e in trace["traceEvents"]
        )

    def test_close_detects_leaked_pages(self, target_and_params):
        model, params = target_and_params
        eng = make_engine(model, params)
        leak = eng.allocator.allocate(1)  # page the scheduler doesn't own
        with pytest.raises(AssertionError, match="leak"):
            eng.close()
        eng.allocator.free(leak)


# ----------------------------------------------------------- peer handoff


class TestPeerHandoff:
    @pytest.mark.slow
    def test_publish_adopt_via_store(self, target_and_params, ref_outputs):
        import socket

        from distributed_pytorch_tpu.elastic.store import (
            KVStoreClient,
            KVStoreServer,
        )
        from distributed_pytorch_tpu.serving import (
            adopt_snapshot,
            publish_snapshot,
        )

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        model, params = target_and_params
        with KVStoreServer(port):
            client = KVStoreClient("127.0.0.1", port)
            eng = make_engine(model, params)
            ids = submit_all(eng)
            for _ in range(3):
                eng.step()
            snap = drain_engine(eng)
            publish_snapshot(client, "drained/engine-0", snap)

            peer = make_engine(model, params)
            restored = adopt_snapshot(peer, client, "x-no-such-key")
            assert restored == []
            restored = adopt_snapshot(peer, client, "drained/engine-0")
            peer.run()
            for i in ids:
                src = peer if i in restored else eng
                assert src.poll(i).generated == ref_outputs[i]
            # Adopt-once: the key is consumed.
            assert client.get("drained/engine-0") is None
