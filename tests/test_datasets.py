"""Real-data module tests: CIFAR-10 loading, normalization, synthetic stand-in."""

import os
import pickle

import numpy as np
import pytest

from distributed_pytorch_tpu.utils.datasets import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    cifar10_or_synthetic,
    load_cifar10,
    normalize_images,
    synthetic_cifar10,
)


def write_fake_cifar_pickles(data_dir):
    """The standard cifar-10-batches-py layout with tiny deterministic data."""
    batches = os.path.join(data_dir, "cifar-10-batches-py")
    os.makedirs(batches)
    rng = np.random.default_rng(0)

    def write(name, n, seed):
        r = np.random.default_rng(seed)
        data = r.integers(0, 256, size=(n, 3072), dtype=np.int64).astype(np.uint8)
        labels = r.integers(0, 10, size=n).tolist()
        with open(os.path.join(batches, name), "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
        return data, labels

    train = [write(f"data_batch_{i}", 20, i) for i in range(1, 6)]
    test = write("test_batch", 10, 99)
    assert rng is not None
    return train, test


class TestLoadCifar10:
    def test_loads_pickle_layout_and_caches_npz(self, tmp_path):
        train, test = write_fake_cifar_pickles(tmp_path)
        x_train, y_train, x_test, y_test = load_cifar10(str(tmp_path))
        assert x_train.shape == (100, 32, 32, 3) and x_train.dtype == np.uint8
        assert y_train.shape == (100,) and y_train.dtype == np.int32
        assert x_test.shape == (10, 32, 32, 3)
        # CHW->HWC transpose correctness: red channel of sample 0 comes from
        # the first 1024 bytes of the row.
        row = train[0][0][0]
        np.testing.assert_array_equal(
            x_train[0, :, :, 0], row[:1024].reshape(32, 32)
        )
        np.testing.assert_array_equal(y_test, np.asarray(test[1], np.int32))
        # Second load comes from the npz cache and is identical.
        assert os.path.exists(tmp_path / "cifar10.npz")
        again = load_cifar10(str(tmp_path))
        np.testing.assert_array_equal(again[0], x_train)

    def test_missing_data_raises_with_instructions(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="cs.toronto.edu"):
            load_cifar10(str(tmp_path / "nope"))

    def test_fallback_is_labeled_synthetic(self, tmp_path, capsys):
        arrays, is_real = cifar10_or_synthetic(
            str(tmp_path / "nope"), n_train=50, n_test=10
        )
        assert not is_real
        assert "synthetic" in capsys.readouterr().out.lower()
        assert arrays[0].shape == (50, 32, 32, 3)


class TestSyntheticCifar10:
    def test_deterministic_and_shaped_like_cifar(self):
        a = synthetic_cifar10(n_train=64, n_test=16)
        b = synthetic_cifar10(n_train=64, n_test=16)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        x_train, y_train, x_test, y_test = a
        assert x_train.dtype == np.uint8 and y_train.dtype == np.int32
        assert x_train.shape == (64, 32, 32, 3)
        assert set(np.unique(y_train)) <= set(range(10))

    def test_classes_are_separable(self):
        """A nearest-template classifier must solve it — the stand-in's whole
        point is that accuracy is a meaningful end-to-end signal."""
        x_train, y_train, x_test, y_test = synthetic_cifar10(
            n_train=500, n_test=100
        )
        means = np.stack(
            [x_train[y_train == c].mean(axis=0) for c in range(10)]
        )
        d = ((x_test.astype(np.float32)[:, None] - means[None]) ** 2).sum(
            axis=(2, 3, 4)
        )
        accuracy = (d.argmin(axis=1) == y_test).mean()
        assert accuracy > 0.95


class TestNormalize:
    def test_standardizes_per_channel(self):
        rng = np.random.default_rng(0)
        images = rng.integers(0, 256, size=(4, 32, 32, 3), dtype=np.int64).astype(
            np.uint8
        )
        out = normalize_images(images)
        assert out.dtype == np.float32
        expected = (images[0, 0, 0].astype(np.float32) / 255.0 - CIFAR10_MEAN) / (
            CIFAR10_STD
        )
        np.testing.assert_allclose(out[0, 0, 0], expected, rtol=1e-6)


class TestAugmentedDataset:
    def _base(self):
        from distributed_pytorch_tpu.utils.data import ArrayDataset

        rng = np.random.default_rng(0)
        return ArrayDataset(
            rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=(8,)).astype(np.int32),
        )

    def test_deterministic_per_epoch_and_index(self):
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        a, b = AugmentedDataset(self._base()), AugmentedDataset(self._base())
        a.set_epoch(3)
        b.set_epoch(3)
        xa, ya = a[5]
        xb, yb = b[5]
        np.testing.assert_array_equal(xa, xb)
        assert ya == yb

    def test_epoch_changes_augmentation(self):
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        ds = AugmentedDataset(self._base())
        ds.set_epoch(0)
        x0, _ = ds[2]
        ds.set_epoch(1)
        x1, _ = ds[2]
        assert x0.shape == (32, 32, 3)
        assert not np.array_equal(x0, x1), "epochs must see fresh crops/flips"

    def test_loader_forwards_epoch(self):
        from distributed_pytorch_tpu.utils.data import ShardedLoader
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        ds = AugmentedDataset(self._base())
        loader = ShardedLoader(ds, 4)
        loader.set_epoch(7)
        assert ds._epoch == 7

    def test_label_and_shape_preserved(self):
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        base = self._base()
        ds = AugmentedDataset(base)
        for i in range(len(ds)):
            x, y = ds[i]
            assert x.shape == base.inputs[i].shape
            assert y == base.targets[i]
            assert x.flags["C_CONTIGUOUS"]
