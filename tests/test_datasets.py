"""Real-data module tests: CIFAR-10 loading, normalization, synthetic stand-in."""

import os
import pickle

import numpy as np
import pytest

from distributed_pytorch_tpu.utils.datasets import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    cifar10_or_synthetic,
    load_cifar10,
    normalize_images,
    synthetic_cifar10,
)


def write_fake_cifar_pickles(data_dir):
    """The standard cifar-10-batches-py layout with tiny deterministic data."""
    batches = os.path.join(data_dir, "cifar-10-batches-py")
    os.makedirs(batches)
    rng = np.random.default_rng(0)

    def write(name, n, seed):
        r = np.random.default_rng(seed)
        data = r.integers(0, 256, size=(n, 3072), dtype=np.int64).astype(np.uint8)
        labels = r.integers(0, 10, size=n).tolist()
        with open(os.path.join(batches, name), "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
        return data, labels

    train = [write(f"data_batch_{i}", 20, i) for i in range(1, 6)]
    test = write("test_batch", 10, 99)
    assert rng is not None
    return train, test


class TestLoadCifar10:
    def test_loads_pickle_layout_and_caches_npz(self, tmp_path):
        train, test = write_fake_cifar_pickles(tmp_path)
        x_train, y_train, x_test, y_test = load_cifar10(str(tmp_path))
        assert x_train.shape == (100, 32, 32, 3) and x_train.dtype == np.uint8
        assert y_train.shape == (100,) and y_train.dtype == np.int32
        assert x_test.shape == (10, 32, 32, 3)
        # CHW->HWC transpose correctness: red channel of sample 0 comes from
        # the first 1024 bytes of the row.
        row = train[0][0][0]
        np.testing.assert_array_equal(
            x_train[0, :, :, 0], row[:1024].reshape(32, 32)
        )
        np.testing.assert_array_equal(y_test, np.asarray(test[1], np.int32))
        # Second load comes from the npz cache and is identical.
        assert os.path.exists(tmp_path / "cifar10.npz")
        again = load_cifar10(str(tmp_path))
        np.testing.assert_array_equal(again[0], x_train)

    def test_missing_data_raises_with_instructions(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="cs.toronto.edu"):
            load_cifar10(str(tmp_path / "nope"))

    def test_fallback_is_labeled_synthetic(self, tmp_path, capsys):
        arrays, is_real = cifar10_or_synthetic(
            str(tmp_path / "nope"), n_train=50, n_test=10
        )
        assert not is_real
        assert "synthetic" in capsys.readouterr().out.lower()
        assert arrays[0].shape == (50, 32, 32, 3)


class TestSyntheticCifar10:
    def test_deterministic_and_shaped_like_cifar(self):
        a = synthetic_cifar10(n_train=64, n_test=16)
        b = synthetic_cifar10(n_train=64, n_test=16)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        x_train, y_train, x_test, y_test = a
        assert x_train.dtype == np.uint8 and y_train.dtype == np.int32
        assert x_train.shape == (64, 32, 32, 3)
        assert set(np.unique(y_train)) <= set(range(10))

    def test_oracle_accuracy_in_design_band(self):
        """The Bayes-optimal (true nearest-template) classifier lands in the
        designed ~5-10%-error band: the stand-in is hard enough to test
        learning but solvable enough that accuracy is a real signal."""
        from distributed_pytorch_tpu.utils.datasets import (
            synthetic_oracle_accuracy,
        )

        _, _, x_test, y_test = synthetic_cifar10(n_train=1, n_test=2000)
        oracle = synthetic_oracle_accuracy(x_test, y_test)
        assert 0.90 <= oracle <= 0.96, oracle

    def test_smooth_templates_keep_oracle_band_and_determinism(self):
        """``smooth_frac`` redistributes template variance across spatial
        frequencies without moving the Bayes ceiling (expected pairwise
        template distances are correlation-invariant), so the design band
        holds at any mix — and generation stays deterministic."""
        from distributed_pytorch_tpu.utils.datasets import (
            synthetic_oracle_accuracy,
        )

        a = synthetic_cifar10(n_train=32, n_test=2000, smooth_frac=0.5)
        b = synthetic_cifar10(n_train=32, n_test=2000, smooth_frac=0.5)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        oracle = synthetic_oracle_accuracy(
            a[2], a[3], smooth_frac=0.5
        )
        assert 0.90 <= oracle <= 0.96, oracle

    def test_smooth_component_is_low_frequency_unit_std(self):
        """The low-pass helper: unit per-template std (so ``contrast``
        keeps meaning) and energy actually concentrated at low spatial
        frequencies."""
        from distributed_pytorch_tpu.utils.datasets import _lowpass

        rng = np.random.default_rng(3)
        white = rng.standard_normal((4, 32, 32, 3)).astype(np.float32)
        smooth = _lowpass(white, 6.0)
        np.testing.assert_allclose(
            smooth.std(axis=(1, 2, 3)), 1.0, rtol=1e-5
        )
        spec = np.abs(np.fft.fft2(smooth, axes=(1, 2))) ** 2
        # Everything beyond the first few spatial harmonics is gone.
        low = spec[:, :4, :4, :].sum() + spec[:, -3:, :4, :].sum() \
            + spec[:, :4, -3:, :].sum() + spec[:, -3:, -3:, :].sum()
        assert low / spec.sum() > 0.95

    def test_conv_reachable_ceiling_justifies_smooth_default(self):
        """The round-5 finding, as an executable claim: classify with ONLY
        the low-frequency template component (the part a weight-shared
        conv stack + GAP can express) and accuracy must still clear the
        real-data rung's >=0.5 bar by a wide margin at the 0.5 default —
        while the full oracle needs the white part too, keeping the task
        multi-epoch for linear learners."""
        from distributed_pytorch_tpu.utils.datasets import (
            _synthetic_template_components,
            synthetic_oracle_accuracy,
        )

        sf = 0.5
        _, smooth_only = _synthetic_template_components(0, 2.6, sf)
        smooth_only = smooth_only.reshape(10, -1)
        _, _, x, y = synthetic_cifar10(n_train=1, n_test=2000, smooth_frac=sf)
        xb = x.astype(np.float32).reshape(len(x), -1)
        d = (
            (xb**2).sum(1, keepdims=True)
            - 2.0 * xb @ smooth_only.T
            + (smooth_only**2).sum(1)[None, :]
        )
        partial = float((d.argmin(1) == y).mean())
        full = synthetic_oracle_accuracy(x, y, smooth_frac=sf)
        assert partial >= 0.70, partial  # conv-reachable headroom over 0.5
        assert full - partial >= 0.08, (full, partial)  # white part matters

    def test_learning_takes_multiple_epochs(self):
        """The round-3 stand-in hit accuracy 1.0 in epoch 1, proving only
        plumbing. Here a linear learner (nearest-template is linear, so it
        can solve the task) must IMPROVE over epochs and end well above
        chance but below the oracle — i.e. the rung now measures learning
        dynamics, not shape compatibility."""
        import jax
        import jax.numpy as jnp
        import optax

        from distributed_pytorch_tpu.utils.datasets import normalize_images

        x_tr, y_tr, x_te, y_te = synthetic_cifar10(n_train=4000, n_test=1000)
        xt = normalize_images(x_tr).reshape(len(x_tr), -1)
        xe = jnp.asarray(normalize_images(x_te).reshape(len(x_te), -1))
        ye = jnp.asarray(y_te)

        opt = optax.sgd(2e-3, momentum=0.9)
        params = (jnp.zeros((3072, 10)), jnp.zeros((10,)))
        opt_state = opt.init(params)

        def loss_fn(p, x, y):
            return optax.softmax_cross_entropy_with_integer_labels(
                x @ p[0] + p[1], y
            ).mean()

        @jax.jit
        def step(p, s, x, y):
            grads = jax.grad(loss_fn)(p, x, y)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s

        rng = np.random.default_rng(0)
        accs = []
        for _ in range(6):
            order = rng.permutation(len(xt))
            for i in range(0, len(xt), 128):
                idx = order[i : i + 128]
                params, opt_state = step(
                    params, opt_state, jnp.asarray(xt[idx]),
                    jnp.asarray(y_tr[idx]),
                )
            logits = xe @ params[0] + params[1]
            accs.append(float((jnp.argmax(logits, 1) == ye).mean()))
        # Epoch 1 must NOT already be at the ceiling...
        assert accs[0] < 0.75, accs
        # ...later epochs keep improving into the band (above chance=0.1,
        # below the ~0.92 oracle; 4k samples cap a linear learner ~0.78)...
        best_late = max(accs[3:])
        assert 0.75 <= best_late <= 0.88, accs
        # ...and the multi-epoch gain is real, not noise.
        assert best_late - accs[0] >= 0.03, accs


class TestNormalize:
    def test_standardizes_per_channel(self):
        rng = np.random.default_rng(0)
        images = rng.integers(0, 256, size=(4, 32, 32, 3), dtype=np.int64).astype(
            np.uint8
        )
        out = normalize_images(images)
        assert out.dtype == np.float32
        expected = (images[0, 0, 0].astype(np.float32) / 255.0 - CIFAR10_MEAN) / (
            CIFAR10_STD
        )
        np.testing.assert_allclose(out[0, 0, 0], expected, rtol=1e-6)


class TestAugmentedDataset:
    def _base(self):
        from distributed_pytorch_tpu.utils.data import ArrayDataset

        rng = np.random.default_rng(0)
        return ArrayDataset(
            rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=(8,)).astype(np.int32),
        )

    def test_deterministic_per_epoch_and_index(self):
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        a, b = AugmentedDataset(self._base()), AugmentedDataset(self._base())
        a.set_epoch(3)
        b.set_epoch(3)
        xa, ya = a[5]
        xb, yb = b[5]
        np.testing.assert_array_equal(xa, xb)
        assert ya == yb

    def test_epoch_changes_augmentation(self):
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        ds = AugmentedDataset(self._base())
        ds.set_epoch(0)
        x0, _ = ds[2]
        ds.set_epoch(1)
        x1, _ = ds[2]
        assert x0.shape == (32, 32, 3)
        assert not np.array_equal(x0, x1), "epochs must see fresh crops/flips"

    def test_loader_forwards_epoch(self):
        from distributed_pytorch_tpu.utils.data import ShardedLoader
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        ds = AugmentedDataset(self._base())
        loader = ShardedLoader(ds, 4)
        loader.set_epoch(7)
        assert ds._epoch == 7

    def test_label_and_shape_preserved(self):
        from distributed_pytorch_tpu.utils.datasets import AugmentedDataset

        base = self._base()
        ds = AugmentedDataset(base)
        for i in range(len(ds)):
            x, y = ds[i]
            assert x.shape == base.inputs[i].shape
            assert y == base.targets[i]
            assert x.flags["C_CONTIGUOUS"]
