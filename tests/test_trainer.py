"""Trainer integration tests: end-to-end epochs, DP parity, snapshot resume
(the reference's elasticity contract, ``multigpu_torchrun.py:30-40,57-65``)."""

import pytest
import jax
import numpy as np
import optax

from distributed_pytorch_tpu.models.toy import ToyRegressor
from distributed_pytorch_tpu.parallel.mesh import make_mesh
from distributed_pytorch_tpu.training.trainer import Trainer
from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader


def _loader(batch=32, n=256, seed=0, **kw):
    return ShardedLoader(MaterializedDataset(n, seed=seed), batch, **kw)


def test_trainer_serial_end_to_end(tmp_path):
    trainer = Trainer(
        ToyRegressor(),
        _loader(),
        optax.sgd(1e-2),
        save_every=2,
        checkpoint_path=str(tmp_path / "ckpt.npz"),
    )
    first = trainer._run_epoch(0)
    trainer.train(4)
    last = trainer._run_epoch(99)
    assert last < first
    assert (tmp_path / "ckpt.npz").exists()


def test_trainer_dp_matches_serial(tmp_path):
    """Same seed + same global batch: 8-way DP Trainer == serial Trainer."""
    mesh = make_mesh()
    serial = Trainer(
        ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
        checkpoint_path=str(tmp_path / "a.npz"),
    )
    dp = Trainer(
        ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
        checkpoint_path=str(tmp_path / "b.npz"), mesh=mesh,
    )
    l1 = serial._run_epoch(0)
    l2 = dp._run_epoch(0)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(serial.state.params),
        jax.tree_util.tree_leaves(dp.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_snapshot_resume_contract(tmp_path):
    """Train 2 epochs with snapshots -> new Trainer resumes at epoch 2 and
    finishes to 4 with state identical to an uninterrupted 4-epoch run."""
    snap = str(tmp_path / "snapshot.npz")

    t1 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    t1.train(2)

    # "Crash" and restart: fresh Trainer probes the snapshot on init.
    t2 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    assert t2.epochs_run == 2
    t2.train(4)

    # Uninterrupted reference run.
    t3 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
                 snapshot_path=None, checkpoint_path=str(tmp_path / "c.npz"))
    t3.train(4)

    for a, b in zip(
        jax.tree_util.tree_leaves(t2.state.params),
        jax.tree_util.tree_leaves(t3.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_snapshot_resume_with_adam_opt_state(tmp_path):
    """Optimizer state survives resume (the gap the reference leaves open)."""
    snap = str(tmp_path / "snap.npz")
    t1 = Trainer(ToyRegressor(), _loader(), optax.adam(1e-3), save_every=1,
                 snapshot_path=snap)
    t1.train(2)
    t2 = Trainer(ToyRegressor(), _loader(), optax.adam(1e-3), save_every=1,
                 snapshot_path=snap)
    t2.train(4)
    t3 = Trainer(ToyRegressor(), _loader(), optax.adam(1e-3), save_every=0,
                 snapshot_path=None, checkpoint_path=str(tmp_path / "c.npz"))
    t3.train(4)
    for a, b in zip(
        jax.tree_util.tree_leaves(t2.state.params),
        jax.tree_util.tree_leaves(t3.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_trainer_mesh_auto_pads_ragged_batches(tmp_path):
    """Non-divisible dataset on a mesh: Trainer wrap-pads the final batch so
    shapes stay static and P('data') placement works."""
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    mesh = make_mesh()
    loader = _loader(batch=32, n=100)
    trainer = Trainer(ToyRegressor(), loader, optax.sgd(1e-2), save_every=0,
                      checkpoint_path=str(tmp_path / "c.npz"), mesh=mesh)
    assert loader.pad_final_batch
    trainer.train(1)  # would crash on the 4-row final batch without padding


def test_trainer_mesh_rejects_indivisible_batch(tmp_path):
    from distributed_pytorch_tpu.parallel.mesh import make_mesh
    import pytest
    mesh = make_mesh()
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(ToyRegressor(), _loader(batch=12), optax.sgd(1e-2), save_every=0,
                mesh=mesh)


@pytest.mark.slow
def test_checkpoint_includes_model_state(tmp_path):
    """Plain checkpoints carry BatchNorm running stats (reference parity:
    state_dict includes them)."""
    import numpy as np
    from distributed_pytorch_tpu.checkpoint import load_checkpoint
    from distributed_pytorch_tpu.models import ResNet18
    from distributed_pytorch_tpu.training.losses import softmax_cross_entropy_loss
    from distributed_pytorch_tpu.utils.data import RandomDataset

    ds = RandomDataset(16, (16, 16, 3), num_classes=10)
    loader = ShardedLoader(ds, 8)
    path = str(tmp_path / "ckpt.npz")
    trainer = Trainer(ResNet18(num_classes=10), loader, optax.sgd(1e-2),
                      save_every=1, checkpoint_path=path,
                      loss_fn=softmax_cross_entropy_loss)
    trainer.train(1)
    template = {"params": trainer.state.params, "model_state": trainer.state.model_state}
    restored, meta = load_checkpoint(path, template)
    stats = jax.tree_util.tree_leaves(restored["model_state"])
    assert stats and any(not np.allclose(np.asarray(s), 0) for s in stats)


@pytest.mark.slow
def test_trainer_partition_specs_zero1_and_fsdp(tmp_path):
    """The sharding zoo through the flagship API: Trainer(partition_specs=)
    with ZeRO-1 (TrainState-shaped specs) and FSDP (params-shaped specs)
    both match the replicated-DP loss, shard what they claim on device, and
    survive the snapshot-resume contract under sharded placement."""
    import optax as _optax

    from distributed_pytorch_tpu.parallel.partitioning import (
        make_fsdp_specs,
        make_zero1_state_specs,
    )

    def make(partition_specs=None, mesh=None, snap=None):
        return Trainer(
            ToyRegressor(), _loader(), _optax.adam(1e-2), save_every=1,
            mesh=mesh, partition_specs=partition_specs,
            snapshot_path=snap,
            checkpoint_path=str(tmp_path / "unused.npz"),
        )

    mesh8 = make_mesh({"data": 8})
    dp = make(mesh=mesh8)
    base = dp._run_epoch(0)

    # ZeRO-1 on a 4-device mesh (the toy kernel's dim 20 shards 4-way; it
    # has no 8-divisible dim): Adam mu sharded, params not.
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    z1_specs = make_zero1_state_specs(make(mesh=mesh).state, mesh=mesh)
    z1 = make(partition_specs=z1_specs, mesh=mesh)
    np.testing.assert_allclose(z1._run_epoch(0), base, rtol=1e-5)
    assert all(
        leaf.sharding.is_fully_replicated
        for leaf in jax.tree_util.tree_leaves(z1.state.params)
    )
    assert any(
        not a.sharding.is_fully_replicated
        for a in jax.tree_util.tree_leaves(z1.state.opt_state[0].mu)
    )

    # FSDP: params-shaped specs, lifted onto the state internally.
    fsdp_mesh = make_mesh({"data": 2, "fsdp": 4})
    probe = make(mesh=fsdp_mesh)
    fsdp_specs = make_fsdp_specs(probe.state.params, mesh=fsdp_mesh)
    fsdp = make(partition_specs=fsdp_specs, mesh=fsdp_mesh)
    np.testing.assert_allclose(fsdp._run_epoch(0), base, rtol=1e-5)

    # Snapshot round-trip under sharded placement: resume keeps the specs.
    snap = str(tmp_path / "z1.npz")
    t1 = make(partition_specs=z1_specs, mesh=mesh, snap=snap)
    t1.train(2)
    t2 = make(partition_specs=z1_specs, mesh=mesh, snap=snap)
    assert t2.epochs_run == 2
    assert any(
        not a.sharding.is_fully_replicated
        for a in jax.tree_util.tree_leaves(t2.state.opt_state[0].mu)
    )


def test_trainer_partition_specs_requires_mesh():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="mesh"):
        Trainer(
            ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
            partition_specs={"linear": None},
        )


def test_trainer_evaluate_with_partition_specs(tmp_path):
    """Exact eval runs against a ZeRO-1-sharded state and matches the
    replicated-DP eval (the eval steps inherit state_sharding)."""
    import optax as _optax

    from distributed_pytorch_tpu.parallel.partitioning import (
        make_zero1_state_specs,
    )

    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    eval_loader = _loader(batch=32, n=96, seed=7)

    def make(specs=None):
        return Trainer(
            ToyRegressor(), _loader(), _optax.adam(1e-2), save_every=0,
            mesh=mesh, partition_specs=specs,
            checkpoint_path=str(tmp_path / "unused.npz"),
        )

    dp = make()
    dp._run_epoch(0)
    base = dp.evaluate(eval_loader)

    # dp.state already has the TrainState structure the specs need.
    z1 = make(make_zero1_state_specs(dp.state, mesh=mesh))
    z1._run_epoch(0)
    np.testing.assert_allclose(z1.evaluate(eval_loader), base, rtol=1e-5)


def test_trainer_rotating_checkpoints(tmp_path):
    """keep_checkpoints=K: checkpoint_path becomes a rotating directory —
    newest K survive, best-by-epoch-loss protected, contents restorable."""
    import optax

    from distributed_pytorch_tpu.checkpoint import CheckpointManager
    from distributed_pytorch_tpu.models.toy import ToyRegressor
    from distributed_pytorch_tpu.training.losses import mse_loss
    from distributed_pytorch_tpu.training.trainer import Trainer
    from distributed_pytorch_tpu.utils.data import MaterializedDataset, ShardedLoader

    data = MaterializedDataset(64)
    loader = ShardedLoader(data, 16)
    ckpt_dir = str(tmp_path / "rotated")
    trainer = Trainer(
        ToyRegressor(),
        loader,
        optax.sgd(1e-2),
        save_every=1,
        checkpoint_path=ckpt_dir,
        loss_fn=mse_loss,
        keep_checkpoints=2,
    )
    trainer.train(5)
    import os as _os

    files = sorted(_os.listdir(ckpt_dir))
    # 2 newest; best may coincide with a newest file (loss usually falls).
    assert 2 <= len(files) <= 3, files
    mgr = CheckpointManager(ckpt_dir, keep=2)
    template = {
        "params": trainer.state.params,
        "model_state": trainer.state.model_state,
    }
    restored, meta = mgr.restore(template)
    assert meta["epochs_run"] == 5
    assert "metric" in meta


# ------------------------------------------------------------ graceful drain


@pytest.fixture
def _restore_sigterm():
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    yield
    signal.signal(signal.SIGTERM, prev)


def _drain_after(trainer, n_batches):
    """Arm trainer to raise its drain flag after the Nth _run_batch call."""
    orig = trainer._run_batch
    calls = {"n": 0}

    def wrapped(batch):
        loss = orig(batch)
        calls["n"] += 1
        if calls["n"] == n_batches:
            trainer._drain_flag = True
        return loss

    trainer._run_batch = wrapped


def test_drain_mid_epoch_snapshot_and_exact_resume(tmp_path, capsys, _restore_sigterm):
    """The tentpole contract, in-process: a drain request lands mid-epoch,
    the trainer finishes the in-flight batch, snapshots at (epoch, step),
    exits with the drain code — and a fresh Trainer resumes at that exact
    batch, finishing with params identical to an uninterrupted run."""
    from distributed_pytorch_tpu.checkpoint import load_snapshot

    snap = str(tmp_path / "snapshot.npz")
    t1 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    # 8 batches/epoch; drain on the 11th batch = epoch 1, steps_done 3.
    _drain_after(t1, 11)
    with pytest.raises(SystemExit) as exc:
        t1.train(3)
    assert exc.value.code == 121  # default TPURUN_DRAIN_EXIT_CODE
    out = capsys.readouterr().out
    assert "[drain] just-in-time snapshot at epoch 1, step 3" in out

    restored, meta = load_snapshot(snap, t1.state)
    assert meta["epochs_run"] == 1
    assert meta["step_in_epoch"] == 3
    assert meta["order"] == t1.train_data.order_state()
    assert meta["loss_count"] == 3

    t2 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    assert t2.epochs_run == 1
    out = capsys.readouterr().out
    assert "Resuming training from snapshot at Epoch 1, step 3" in out
    t2.train(3)

    t3 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
                 snapshot_path=None, checkpoint_path=str(tmp_path / "c.npz"))
    t3.train(3)
    for a, b in zip(
        jax.tree_util.tree_leaves(t2.state.params),
        jax.tree_util.tree_leaves(t3.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_drain_epoch_loss_parity_across_resume(tmp_path, capsys, _restore_sigterm):
    """The interrupted epoch's reported mean loss (carry + tail) matches the
    uninterrupted run's mean for the same epoch."""
    import re

    snap = str(tmp_path / "snapshot.npz")
    t1 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    _drain_after(t1, 5)  # epoch 0, steps_done 5 of 8
    with pytest.raises(SystemExit):
        t1.train(2)
    capsys.readouterr()

    t2 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    resumed_loss = t2._run_epoch(0)

    t3 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
                 snapshot_path=None, checkpoint_path=str(tmp_path / "c.npz"))
    full_loss = t3._run_epoch(0)
    np.testing.assert_allclose(resumed_loss, full_loss, rtol=1e-6)


def test_drain_file_poll_and_exit_code_override(tmp_path, monkeypatch, capsys, _restore_sigterm):
    """The agent-side signal: touching TPURUN_DRAIN_FILE drains the very next
    batch, and TPURUN_DRAIN_EXIT_CODE overrides the exit status."""
    drain_file = tmp_path / "drain.0"
    monkeypatch.setenv("TPURUN_DRAIN_FILE", str(drain_file))
    monkeypatch.setenv("TPURUN_DRAIN_EXIT_CODE", "77")
    snap = str(tmp_path / "snapshot.npz")
    t = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                snapshot_path=snap)
    drain_file.write_text("drain\n")
    with pytest.raises(SystemExit) as exc:
        t.train(2)
    assert exc.value.code == 77
    assert "[drain] just-in-time snapshot at epoch 0, step 1" in capsys.readouterr().out


def test_sigterm_with_drain_file_present_sets_flag(tmp_path, monkeypatch, _restore_sigterm):
    """Under tpurun (TPURUN_DRAIN_FILE set), SIGTERM with the drain file
    touched means 'snapshot and go' — the handler latches the flag instead
    of killing the process."""
    import os
    import signal

    drain_file = tmp_path / "drain.0"
    drain_file.write_text("drain\n")
    monkeypatch.setenv("TPURUN_DRAIN_FILE", str(drain_file))
    t = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                snapshot_path=str(tmp_path / "s.npz"))
    assert not t._drain_flag
    os.kill(os.getpid(), signal.SIGTERM)
    assert t._drain_flag  # delivered synchronously at the next bytecode


def test_drain_without_snapshot_path_is_inert(tmp_path, _restore_sigterm):
    """No snapshot_path -> nothing to drain to: the flag is ignored and the
    run completes normally (matches a plain, non-elastic launch)."""
    t = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=0,
                checkpoint_path=str(tmp_path / "c.npz"))
    t._drain_flag = True
    t.train(1)  # must not raise SystemExit
    assert t.epochs_run == 1


def test_drain_resume_geometry_mismatch_replays_epoch(tmp_path, capsys, _restore_sigterm):
    """A snapshot taken mid-epoch under a different loader geometry (elastic
    scale-down) cannot be resumed at the saved step: the epoch replays from
    step 0, loudly."""
    snap = str(tmp_path / "snapshot.npz")
    t1 = Trainer(ToyRegressor(), _loader(), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    _drain_after(t1, 3)
    with pytest.raises(SystemExit):
        t1.train(2)
    capsys.readouterr()

    t2 = Trainer(ToyRegressor(), _loader(batch=16), optax.sgd(1e-2), save_every=1,
                 snapshot_path=snap)
    out = capsys.readouterr().out
    assert "different loader geometry" in out
    assert "Resuming training from snapshot at Epoch 0" in out
    assert t2._resume_step == 0
    t2.train(1)  # replays epoch 0 from scratch, completes
    assert t2.epochs_run == 1
