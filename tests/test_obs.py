"""Unified observability layer tests: the request-lifecycle Tracer and
engine step timeline (Perfetto trace_event export), the NullTracer
zero-cost-when-disabled contract, and the MetricsRegistry
(counters/gauges/reservoirs, JSON snapshot, Prometheus text exposition,
cross-host merge) — plus the engine integration acceptance criteria:
tracing on/off yields bitwise-identical tokens, per-request span count
equals completed requests, and registry counters equal engine ground truth.
"""

import json
import math

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_tpu.metrics import ReservoirGroup, ReservoirHistogram
from distributed_pytorch_tpu.obs import (
    MetricsRegistry,
    NULL_TRACER,
    NullTracer,
    Tracer,
)
from distributed_pytorch_tpu.serving import InferenceEngine, SamplingParams


class FakeClock:
    """Deterministic tracer clock: advances a fixed tick per call."""

    def __init__(self, tick: float = 0.001):
        self.t = 0.0
        self.tick = tick

    def __call__(self) -> float:
        self.t += self.tick
        return self.t


# ------------------------------------------------------------------ tracer


class TestTracer:
    def test_step_slice_records_duration_and_gauges(self):
        tr = Tracer(clock=FakeClock())
        tr.begin_step()
        tr.end_step(queue_depth=3, pages_free=7)
        steps = [e for e in tr.events if e["name"] == "step"]
        assert len(steps) == 1
        (step,) = steps
        assert step["ph"] == "X" and step["dur"] > 0
        assert step["args"]["step"] == 0
        assert step["args"]["queue_depth"] == 3
        counters = [e for e in tr.events if e["ph"] == "C"]
        assert {e["name"] for e in counters} == {"queue_depth", "pages_free"}
        tr.begin_step()
        tr.end_step()
        assert [
            e for e in tr.events if e["name"] == "step"
        ][1]["args"]["step"] == 1

    def test_phase_slices_nest_inside_step(self):
        tr = Tracer(clock=FakeClock())
        tr.begin_step()
        with tr.phase("schedule"):
            pass
        with tr.phase("dispatch"):
            with tr.phase("stage"):
                pass
        tr.end_step()
        phases = {
            e["name"]: e for e in tr.events
            if e["ph"] == "X" and e["name"] != "step"
        }
        assert set(phases) == {"schedule", "dispatch", "stage"}
        assert all(e["args"]["step"] == 0 for e in phases.values())
        # nesting is by time containment: stage inside dispatch
        d, s = phases["dispatch"], phases["stage"]
        assert d["ts"] <= s["ts"]
        assert s["ts"] + s["dur"] <= d["ts"] + d["dur"]

    def test_request_span_lifecycle(self):
        tr = Tracer(clock=FakeClock())
        tr.request_begin(7, prompt_len=5, max_new_tokens=4)
        tr.request_event(7, "admit", slot=0, hit=False, cached_tokens=0)
        tr.request_event(7, "decode_token", n_generated=1)
        tr.request_end(7, n_generated=4, preempt_count=0)
        assert tr.spans_opened == 1 and tr.spans_closed == 1
        phs = [e["ph"] for e in tr.events]
        assert phs == ["b", "n", "n", "e"]
        assert all(e["id"] == 7 for e in tr.events)
        assert all(e["cat"] == "request" for e in tr.events)
        begin = tr.events[0]
        assert begin["args"]["prompt_len"] == 5

    def test_to_perfetto_is_json_with_named_lanes(self):
        tr = Tracer(clock=FakeClock())
        tr.begin_step()
        tr.instant("page_evict", page=3)
        tr.end_step()
        doc = json.loads(json.dumps(tr.to_perfetto()))
        assert "traceEvents" in doc
        names = {
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert names == {"engine", "requests"}
        assert any(e.get("ph") == "i" for e in doc["traceEvents"])

    def test_save_writes_loadable_trace(self, tmp_path):
        tr = Tracer(clock=FakeClock())
        tr.begin_step()
        tr.end_step()
        path = tr.save(str(tmp_path / "sub" / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["traceEvents"]

    def test_null_tracer_is_inert(self):
        assert NULL_TRACER.enabled is False
        assert isinstance(NULL_TRACER, NullTracer)
        NULL_TRACER.begin_step()
        NULL_TRACER.end_step(anything=1)
        NULL_TRACER.request_begin(0, x=1)
        NULL_TRACER.request_event(0, "admit")
        NULL_TRACER.request_end(0)
        NULL_TRACER.instant("evict")
        with NULL_TRACER.phase("schedule"):
            pass  # usable as a context manager, records nothing
        assert not hasattr(NULL_TRACER, "events")


# ---------------------------------------------------------------- registry


class TestMetricsRegistry:
    def test_counters_and_gauges_push_and_pull(self):
        reg = MetricsRegistry(namespace="t")
        c = reg.counter("requests_total")
        c.inc()
        c.inc(2)
        g = reg.gauge("depth")
        g.set(5.0)
        state = {"steps": 7}
        reg.counter_fn("steps_total", lambda: state["steps"])
        snap = reg.snapshot()
        assert snap["counters"] == {
            "t_requests_total": 3, "t_steps_total": 7,
        }
        assert snap["gauges"] == {"t_depth": 5.0}
        state["steps"] = 9  # pull-based: re-resolved at snapshot time
        assert reg.snapshot()["counters"]["t_steps_total"] == 9

    def test_duplicate_name_rejected(self):
        reg = MetricsRegistry()
        reg.counter("x_total")
        with pytest.raises(ValueError):
            reg.counter_fn("x_total", lambda: 0)
        with pytest.raises(ValueError):
            reg.gauge("x_total")

    def test_reservoir_summary_and_labeled_series(self):
        reg = MetricsRegistry(namespace="s")
        h = ReservoirHistogram(64, seed=0)
        for v in (1.0, 2.0, 3.0):
            h.record(v)
        reg.reservoir("ttft_seconds", h)
        grp = ReservoirGroup(("hit", "miss"), 64, seed=1)
        grp.record("hit", 0.5)
        reg.reservoir("ttft_seconds_by_source", grp, label="source")
        snap = reg.snapshot()
        res = snap["reservoirs"]["s_ttft_seconds"]
        assert res["count"] == 3 and res["p50"] == 2.0
        series = snap["reservoirs"]["s_ttft_seconds_by_source"]
        assert series["label"] == "source"
        assert series["series"]["hit"]["count"] == 1
        assert series["series"]["miss"] == {"count": 0}  # empty: no NaNs

    def test_snapshot_is_json_serializable(self):
        reg = MetricsRegistry(namespace="s")
        reg.reservoir("empty_seconds", ReservoirHistogram(8))
        reg.gauge("g", 1.5)
        json.dumps(reg.snapshot(include_state=True))  # must not raise

    def test_resolver_survives_object_replacement(self):
        """bench.py swaps engine.metrics wholesale after warm-up — a
        callable-registered reservoir must follow the swap."""
        holder = {"h": ReservoirHistogram(8)}
        holder["h"].record(1.0)
        reg = MetricsRegistry()
        reg.reservoir("lat_seconds", lambda: holder["h"])
        assert reg.snapshot()["reservoirs"]["lat_seconds"]["count"] == 1
        holder["h"] = ReservoirHistogram(8)  # the reset
        assert reg.snapshot()["reservoirs"]["lat_seconds"] == {"count": 0}

    def test_prometheus_text_exposition(self):
        reg = MetricsRegistry(namespace="srv")
        reg.counter("reqs_total").inc(4)
        reg.gauge("depth", 2.0)
        h = ReservoirHistogram(64)
        h.record(1.0)
        h.record(3.0)
        reg.reservoir("ttft_seconds", h)
        grp = ReservoirGroup(("hit", "miss"), 64)
        grp.record("hit", 0.25)
        reg.reservoir("ttft_by_source", grp, label="source")
        text = reg.prometheus_text()
        assert "# TYPE srv_reqs_total counter" in text
        assert "srv_reqs_total 4" in text
        assert "# TYPE srv_depth gauge" in text
        assert "# TYPE srv_ttft_seconds summary" in text
        assert 'srv_ttft_seconds{quantile="0.5"} 2.0' in text
        assert "srv_ttft_seconds_sum 4.0" in text
        assert "srv_ttft_seconds_count 2" in text
        assert 'srv_ttft_by_source{source="hit",quantile="0.5"} 0.25' in text
        # empty labels emit _count 0, never NaN quantile samples
        assert 'srv_ttft_by_source{source="miss",quantile' not in text
        assert "nan" not in text.lower()

    def test_cross_host_merge(self):
        """Counters sum, reservoir percentiles come from the UNION of the
        hosts' sample streams (not averaged per-host percentiles)."""

        def host(seed, lo):
            reg = MetricsRegistry(namespace="srv")
            reg.counter("reqs_total").inc(10)
            h = ReservoirHistogram(256, seed=seed)
            for v in range(lo, lo + 100):
                h.record(float(v))
            reg.reservoir("lat_seconds", h)
            grp = ReservoirGroup(("hit", "miss"), 256, seed=seed)
            grp.record("hit", float(lo))
            reg.reservoir("lat_by_source", grp, label="source")
            return reg.snapshot(include_state=True)

        # the wire is JSON: round-trip each host's payload
        snaps = [
            json.loads(json.dumps(host(1, 0))),
            json.loads(json.dumps(host(2, 100))),
        ]
        merged = MetricsRegistry.merge(snaps)
        assert merged["counters"]["srv_reqs_total"] == 20
        lat = merged["reservoirs"]["srv_lat_seconds"]
        assert lat["count"] == 200
        assert lat["min"] == 0.0 and lat["max"] == 199.0
        assert abs(lat["p50"] - 99.5) < 1e-9  # union, under capacity: exact
        by_src = merged["reservoirs"]["srv_lat_by_source"]
        assert by_src["series"]["hit"]["count"] == 2
        assert by_src["series"]["miss"] == {"count": 0}
        # merged payload re-merges (associative surface for tree gathers)
        again = MetricsRegistry.merge([merged, merged])
        assert again["counters"]["srv_reqs_total"] == 40


# ------------------------------------------------------- engine integration


def _tiny_engine(tracer=None, **kw):
    from distributed_pytorch_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("token_budget", 16)
    kw.setdefault("max_prefill_chunk", 8)
    return InferenceEngine(model, params, tracer=tracer, **kw)


PROMPTS = [[5, 7, 11, 2, 9, 3], [1, 4, 8], [2, 2, 3, 17, 40], [6, 1, 9, 9]]


def _run_all(eng):
    ids = [
        eng.submit(p, SamplingParams(max_new_tokens=6)) for p in PROMPTS
    ]
    eng.run()
    return [eng.poll(r).generated for r in ids]


class TestEngineObservability:
    def test_tracing_does_not_change_tokens(self):
        """Acceptance: with tracing enabled, greedy outputs are
        bitwise-identical to the untraced engine."""
        plain = _run_all(_tiny_engine())
        traced = _run_all(_tiny_engine(tracer=Tracer()))
        assert traced == plain

    def test_span_count_equals_completed_requests(self, tmp_path):
        tr = Tracer()
        eng = _tiny_engine(tracer=tr)
        _run_all(eng)
        completed = eng.metrics.requests_completed
        assert completed == len(PROMPTS)
        assert tr.spans_opened == completed
        assert tr.spans_closed == completed
        doc = json.load(open(eng.save_trace(str(tmp_path / "t.json"))))
        begins = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "b" and e.get("cat") == "request"
        ]
        ends = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "e" and e.get("cat") == "request"
        ]
        assert len(begins) == completed and len(ends) == completed
        # the step timeline is there too: step slices and phase slices
        assert any(
            e.get("ph") == "X" and e.get("name") == "step"
            for e in doc["traceEvents"]
        )
        assert any(
            e.get("ph") == "X" and e.get("name") == "schedule"
            for e in doc["traceEvents"]
        )
        # every request span carries an admit event
        admits = [
            e for e in doc["traceEvents"] if e.get("name") == "admit"
        ]
        assert {e["id"] for e in admits} == {e["id"] for e in begins}

    def test_registry_counters_match_engine_ground_truth(self):
        eng = _tiny_engine(tracer=Tracer())
        tokens = _run_all(eng)
        snap = eng.registry.snapshot()
        c = snap["counters"]
        assert c["serving_requests_completed_total"] == len(PROMPTS)
        assert c["serving_tokens_generated_total"] == sum(
            len(t) for t in tokens
        )
        assert c["serving_engine_steps_total"] == (
            eng.metrics.engine_steps
        )
        assert c["serving_admission_accepted_total"] == len(PROMPTS)
        # drained engine: no pages referenced, everything free or idle
        g = snap["gauges"]
        assert g["serving_pages_referenced"] == 0
        assert g["serving_running_requests"] == 0
        assert (
            snap["reservoirs"]["serving_ttft_seconds"]["count"]
            == len(PROMPTS)
        )
        # and the Prometheus rendering carries the same counter
        assert (
            f"serving_requests_completed_total {len(PROMPTS)}"
            in eng.registry.prometheus_text()
        )

    def test_save_trace_requires_tracer(self, tmp_path):
        eng = _tiny_engine()
        with pytest.raises(RuntimeError):
            eng.save_trace(str(tmp_path / "t.json"))

    def test_step_gauges_on_timeline(self):
        tr = Tracer()
        eng = _tiny_engine(tracer=tr)
        _run_all(eng)
        steps = [e for e in tr.events if e["name"] == "step"]
        assert steps, "no step slices recorded"
        args = steps[0]["args"]
        for key in (
            "decode_rows", "prefill_chunks", "prefill_tokens",
            "budget_utilization", "queue_depth", "running_requests",
            "pages_free", "pages_referenced", "pages_cached_idle",
        ):
            assert key in args, f"step gauge {key} missing"
        assert all(
            0.0 <= e["args"]["budget_utilization"] <= 1.0 for e in steps
        )
        assert not math.isnan(args["budget_utilization"])
